// Native (C++) GF(2^8) Reed-Solomon matrix apply — the framework's
// CPU data-plane backend behind the ErasureCodec gate
// (cess_tpu/ops/rs.py make_codec backend="native").
//
// Role: the reference's off-chain components do sequential CPU
// RS-encode (SURVEY.md §2.4); this is that path done properly in
// native code — nibble-split table lookups (the classic SIMD erasure
// scheme) with an AVX2 vpshufb fast path and a portable scalar
// fallback, optionally threaded across the batch axis. It doubles as
// the honest "single-node CPU reed-solomon" baseline for the ≥40×
// TPU-speedup metric in BASELINE.md.
//
// ABI (ctypes, cess_tpu/ops/rs_native.py):
//   cess_rs_apply(mat[r*q], r, q, data[batch*q*n], batch, n,
//                 out[batch*r*n], threads)
// applies the GF(2^8) matrix to every batch element:
//   out[b, i, :] = XOR_j mat[i, j] * data[b, j, :]
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

uint8_t EXPT[510];
int LOGT[256];

struct TableInit {
    TableInit() {
        int x = 1;
        for (int i = 0; i < 255; i++) {
            EXPT[i] = static_cast<uint8_t>(x);
            LOGT[x] = i;
            x <<= 1;
            if (x & 0x100) x ^= 0x11D;  // same polynomial as ops/gf.py
        }
        for (int i = 255; i < 510; i++) EXPT[i] = EXPT[i - 255];
        LOGT[0] = 0;
    }
} table_init;

inline uint8_t gf_mul(uint8_t a, uint8_t b) {
    if (!a || !b) return 0;
    return EXPT[LOGT[a] + LOGT[b]];
}

// one output row for one batch element: dst ^= sum_j mat[i,j] * src_j
void apply_row(const uint8_t* tabs, int q, const uint8_t* dbase,
               int64_t n, uint8_t* dst) {
    std::memset(dst, 0, static_cast<size_t>(n));
    for (int j = 0; j < q; j++) {
        const uint8_t* src = dbase + static_cast<int64_t>(j) * n;
        const uint8_t* t = tabs + static_cast<size_t>(j) * 32;
        int64_t x = 0;
#if defined(__AVX2__)
        const __m256i tlo = _mm256_broadcastsi128_si256(
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(t)));
        const __m256i thi = _mm256_broadcastsi128_si256(
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(t + 16)));
        const __m256i maskf = _mm256_set1_epi8(0x0F);
        for (; x + 32 <= n; x += 32) {
            __m256i v = _mm256_loadu_si256(
                reinterpret_cast<const __m256i*>(src + x));
            __m256i lo = _mm256_and_si256(v, maskf);
            __m256i hi = _mm256_and_si256(_mm256_srli_epi16(v, 4), maskf);
            __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(tlo, lo),
                                         _mm256_shuffle_epi8(thi, hi));
            __m256i o = _mm256_loadu_si256(
                reinterpret_cast<const __m256i*>(dst + x));
            _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + x),
                                _mm256_xor_si256(o, p));
        }
#endif
        for (; x < n; x++)
            dst[x] ^= static_cast<uint8_t>(t[src[x] & 15] ^
                                           t[16 + (src[x] >> 4)]);
    }
}

void apply_range(const uint8_t* tabs, int r, int q, const uint8_t* data,
                 int64_t b0, int64_t b1, int64_t n, uint8_t* out) {
    for (int64_t b = b0; b < b1; b++) {
        const uint8_t* dbase = data + b * q * n;
        uint8_t* obase = out + b * r * n;
        for (int i = 0; i < r; i++)
            apply_row(tabs + static_cast<size_t>(i) * q * 32, q, dbase, n,
                      obase + static_cast<int64_t>(i) * n);
    }
}

}  // namespace

extern "C" {

int cess_rs_simd() {
#if defined(__AVX2__)
    return 2;
#else
    return 0;
#endif
}

void cess_rs_apply(const uint8_t* mat, int r, int q, const uint8_t* data,
                   int64_t batch, int64_t n, uint8_t* out, int threads) {
    // nibble split tables per matrix entry: t[0..15] = c * x,
    // t[16..31] = c * (x << 4); so c*b == t[b&15] ^ t[16 + (b>>4)]
    std::vector<uint8_t> tabs(static_cast<size_t>(r) * q * 32);
    for (int i = 0; i < r; i++)
        for (int j = 0; j < q; j++) {
            uint8_t c = mat[i * q + j];
            uint8_t* t = &tabs[(static_cast<size_t>(i) * q + j) * 32];
            for (int x = 0; x < 16; x++) {
                t[x] = gf_mul(c, static_cast<uint8_t>(x));
                t[16 + x] = gf_mul(c, static_cast<uint8_t>(x << 4));
            }
        }
    if (threads <= 1 || batch <= 1) {
        apply_range(tabs.data(), r, q, data, 0, batch, n, out);
        return;
    }
    int nt = threads < batch ? threads : static_cast<int>(batch);
    std::vector<std::thread> pool;
    int64_t per = (batch + nt - 1) / nt;
    for (int t = 0; t < nt; t++) {
        int64_t b0 = t * per, b1 = b0 + per < batch ? b0 + per : batch;
        if (b0 >= b1) break;
        pool.emplace_back(apply_range, tabs.data(), r, q, data, b0, b1, n,
                          out);
    }
    for (auto& th : pool) th.join();
}

}  // extern "C"
