// BLS12-381 min-sig fast path: libcessbls.so, loaded via ctypes by
// cess_tpu/crypto/bls_native.py.
//
// Role: the native half of the verify-bls-signatures equivalent
// (SURVEY.md 2.3 "C++ BLS12-381 host-side"; the reference vendors the
// ic-verify-bls-signature Rust crate,
// /root/reference/utils/verify-bls-signatures/src/lib.rs:1-247). The
// pure-Python implementation (cess_tpu/crypto/bls12381.py) is the
// readable oracle; this file mirrors its exact constructions —
// Fp2(u^2=-1) -> Fp6(v^3=1+u) -> Fp12(w^2=v) tower, optimal-ate loop
// over |u| with trailing conjugation, try-and-increment hash-to-G1
// over expand_message_xmd(SHA-256), ZCash point encoding — so the two
// produce BYTE-IDENTICAL signatures and agree on every verify
// (differentially tested in tests/test_bls.py). 6x64-bit Montgomery
// arithmetic; derived exponents (inversion, sqrt, Legendre, Frobenius
// gammas, final-exp hard part) are baked as hex with regeneration
// notes and cross-checked by the differential tests.
//
// Build: make -C cess_tpu/native libcessbls.so
#include <cstddef>
#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;

// ---------------------------------------------------------------- Fp
// p = 0x1a0111ea...aaab (381 bits), limbs little-endian
static const uint64_t PL[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
// R2 = 2^768 mod p   (regen: python -c "print(hex(pow(2,768,P)))")
static const uint64_t R2L[6] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};
// -p^-1 mod 2^64
static const uint64_t NP = 0x89f3fffcfffcfffdULL;

struct Fp { uint64_t l[6]; };

static inline bool fp_is_zero(const Fp &a) {
  uint64_t o = 0;
  for (int i = 0; i < 6; i++) o |= a.l[i];
  return o == 0;
}
static inline bool fp_eq(const Fp &a, const Fp &b) {
  uint64_t o = 0;
  for (int i = 0; i < 6; i++) o |= a.l[i] ^ b.l[i];
  return o == 0;
}
static inline int cmp6(const uint64_t *a, const uint64_t *b) {
  for (int i = 5; i >= 0; i--) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}
static inline void sub6(uint64_t *r, const uint64_t *a, const uint64_t *b) {
  u128 bw = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a[i] - b[i] - (uint64_t)bw;
    r[i] = (uint64_t)d;
    bw = (d >> 64) ? 1 : 0;
  }
}
static inline void fp_add(Fp &r, const Fp &a, const Fp &b) {
  u128 c = 0;
  for (int i = 0; i < 6; i++) {
    c += (u128)a.l[i] + b.l[i];
    r.l[i] = (uint64_t)c;
    c >>= 64;
  }
  if (c || cmp6(r.l, PL) >= 0) sub6(r.l, r.l, PL);
}
static inline void fp_sub(Fp &r, const Fp &a, const Fp &b) {
  if (cmp6(a.l, b.l) >= 0) {
    sub6(r.l, a.l, b.l);
  } else {
    uint64_t t[6];
    sub6(t, b.l, a.l);
    sub6(r.l, PL, t);
  }
}
static inline void fp_neg(Fp &r, const Fp &a) {
  if (fp_is_zero(a)) { r = a; return; }
  sub6(r.l, PL, a.l);
}
// CIOS Montgomery multiplication
static void fp_mul(Fp &r, const Fp &a, const Fp &b) {
  uint64_t t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 6; i++) {
    u128 c = 0;
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)t[j] + (u128)a.l[i] * b.l[j] + (uint64_t)c;
      t[j] = (uint64_t)s;
      c = s >> 64;
    }
    u128 s = (u128)t[6] + (uint64_t)c;
    t[6] = (uint64_t)s;
    t[7] = (uint64_t)(s >> 64);
    uint64_t m = t[0] * NP;
    c = ((u128)m * PL[0] + t[0]) >> 64;
    for (int j = 1; j < 6; j++) {
      s = (u128)t[j] + (u128)m * PL[j] + (uint64_t)c;
      t[j - 1] = (uint64_t)s;
      c = s >> 64;
    }
    s = (u128)t[6] + (uint64_t)c;
    t[5] = (uint64_t)s;
    t[6] = t[7] + (uint64_t)(s >> 64);
    t[7] = 0;
  }
  if (t[6] || cmp6(t, PL) >= 0) sub6(t, t, PL);
  memcpy(r.l, t, 48);
}
static inline void fp_sqr(Fp &r, const Fp &a) { fp_mul(r, a, a); }

static Fp FP_ZERO, FP_ONE;  // FP_ONE = R mod p (Montgomery 1)

static void fp_from_bytes_be(Fp &r, const uint8_t *b48) {  // -> Montgomery
  Fp t;
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | b48[(5 - i) * 8 + j];
    t.l[i] = w;
  }
  Fp r2;
  memcpy(r2.l, R2L, 48);
  fp_mul(r, t, r2);
}
static void fp_to_bytes_be(uint8_t *b48, const Fp &a) {  // Montgomery ->
  Fp one = {{1, 0, 0, 0, 0, 0}}, std;
  fp_mul(std, a, one);
  for (int i = 0; i < 6; i++)
    for (int j = 0; j < 8; j++)
      b48[(5 - i) * 8 + j] = (uint8_t)(std.l[i] >> (8 * (7 - j)));
}
// pow over a big-endian hex-derived exponent (byte array)
static void fp_pow(Fp &r, const Fp &a, const uint8_t *e, size_t n) {
  Fp acc = FP_ONE, base = a;
  for (size_t i = 0; i < n; i++)
    for (int bit = 7; bit >= 0; bit--) {
      fp_sqr(acc, acc);
      if ((e[i] >> bit) & 1) fp_mul(acc, acc, base);
    }
  r = acc;
}
// exponent constants (big-endian bytes). Regenerate with python:
//   hex(P-2), hex((P+1)//4), hex((P-1)//2)
static const uint8_t EXP_INV[48] = {  // p-2
    0x1a,0x01,0x11,0xea,0x39,0x7f,0xe6,0x9a,0x4b,0x1b,0xa7,0xb6,
    0x43,0x4b,0xac,0xd7,0x64,0x77,0x4b,0x84,0xf3,0x85,0x12,0xbf,
    0x67,0x30,0xd2,0xa0,0xf6,0xb0,0xf6,0x24,0x1e,0xab,0xff,0xfe,
    0xb1,0x53,0xff,0xff,0xb9,0xfe,0xff,0xff,0xff,0xff,0xaa,0xa9};
static const uint8_t EXP_SQRT[48] = {  // (p+1)/4
    0x06,0x80,0x44,0x7a,0x8e,0x5f,0xf9,0xa6,0x92,0xc6,0xe9,0xed,
    0x90,0xd2,0xeb,0x35,0xd9,0x1d,0xd2,0xe1,0x3c,0xe1,0x44,0xaf,
    0xd9,0xcc,0x34,0xa8,0x3d,0xac,0x3d,0x89,0x07,0xaa,0xff,0xff,
    0xac,0x54,0xff,0xff,0xee,0x7f,0xbf,0xff,0xff,0xff,0xea,0xab};
static const uint8_t EXP_LEGENDRE[48] = {  // (p-1)/2
    0x0d,0x00,0x88,0xf5,0x1c,0xbf,0xf3,0x4d,0x25,0x8d,0xd3,0xdb,
    0x21,0xa5,0xd6,0x6b,0xb2,0x3b,0xa5,0xc2,0x79,0xc2,0x89,0x5f,
    0xb3,0x98,0x69,0x50,0x7b,0x58,0x7b,0x12,0x0f,0x55,0xff,0xff,
    0x58,0xa9,0xff,0xff,0xdc,0xff,0x7f,0xff,0xff,0xff,0xd5,0x55};

static inline void fp_inv(Fp &r, const Fp &a) { fp_pow(r, a, EXP_INV, 48); }
// sqrt candidate (p == 3 mod 4); returns false if non-residue
static bool fp_sqrt(Fp &r, const Fp &a) {
  Fp s, s2;
  fp_pow(s, a, EXP_SQRT, 48);
  fp_sqr(s2, s);
  if (!fp_eq(s2, a)) return false;
  r = s;
  return true;
}
// standard-form helpers (for serialization decisions)
static void fp_std(uint64_t out[6], const Fp &a) {
  Fp one = {{1, 0, 0, 0, 0, 0}}, std;
  fp_mul(std, a, one);
  memcpy(out, std.l, 48);
}
static bool fp_is_big(const Fp &a) {  // standard(a) > (p-1)/2
  static const uint64_t HALF[6] = {
      0xdcff7fffffffd555ULL, 0x0f55ffff58a9ffffULL, 0xb39869507b587b12ULL,
      0xb23ba5c279c2895fULL, 0x258dd3db21a5d66bULL, 0x0d0088f51cbff34dULL};
  uint64_t s[6];
  fp_std(s, a);
  return cmp6(s, HALF) > 0;
}
static bool fp_is_odd(const Fp &a) {
  uint64_t s[6];
  fp_std(s, a);
  return s[0] & 1;
}

// ---------------------------------------------------------------- Fp2
struct Fp2 { Fp c0, c1; };  // c0 + c1*u, u^2 = -1
static Fp2 F2_ZERO, F2_ONE, XI;  // XI = 1 + u

static inline void f2_add(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  fp_add(r.c0, a.c0, b.c0);
  fp_add(r.c1, a.c1, b.c1);
}
static inline void f2_sub(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  fp_sub(r.c0, a.c0, b.c0);
  fp_sub(r.c1, a.c1, b.c1);
}
static inline void f2_neg(Fp2 &r, const Fp2 &a) {
  fp_neg(r.c0, a.c0);
  fp_neg(r.c1, a.c1);
}
static void f2_mul(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  Fp t0, t1, t2, s1, s2;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(s1, a.c0, a.c1);
  fp_add(s2, b.c0, b.c1);
  fp_mul(t2, s1, s2);
  fp_sub(r.c0, t0, t1);
  fp_sub(t2, t2, t0);
  fp_sub(r.c1, t2, t1);
}
static void f2_sqr(Fp2 &r, const Fp2 &a) {
  Fp s, d, t;
  fp_add(s, a.c0, a.c1);
  fp_sub(d, a.c0, a.c1);
  fp_mul(t, a.c0, a.c1);
  fp_mul(r.c0, s, d);
  fp_add(r.c1, t, t);
}
static void f2_inv(Fp2 &r, const Fp2 &a) {
  Fp n, t0, t1, d;
  fp_sqr(t0, a.c0);
  fp_sqr(t1, a.c1);
  fp_add(n, t0, t1);
  fp_inv(d, n);
  fp_mul(r.c0, a.c0, d);
  Fp nd;
  fp_neg(nd, a.c1);
  fp_mul(r.c1, nd, d);
}
static inline void f2_conj(Fp2 &r, const Fp2 &a) {
  r.c0 = a.c0;
  fp_neg(r.c1, a.c1);
}
static inline bool f2_is_zero(const Fp2 &a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool f2_eq(const Fp2 &a, const Fp2 &b) {
  return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
static void f2_muls(Fp2 &r, const Fp2 &a, uint64_t s) {  // small scalar
  Fp2 acc = F2_ZERO, base = a;
  while (s) {
    if (s & 1) f2_add(acc, acc, base);
    f2_add(base, base, base);
    s >>= 1;
  }
  r = acc;
}
static void f2_pow(Fp2 &r, const Fp2 &a, const uint8_t *e, size_t n) {
  Fp2 acc = F2_ONE;
  for (size_t i = 0; i < n; i++)
    for (int bit = 7; bit >= 0; bit--) {
      f2_sqr(acc, acc);
      if ((e[i] >> bit) & 1) f2_mul(acc, acc, a);
    }
  r = acc;
}
// sqrt in Fp2 (complex method, matches the Python oracle's structure)
static bool f2_sqrt(Fp2 &r, const Fp2 &a) {
  if (f2_is_zero(a)) { r = F2_ZERO; return true; }
  Fp n, t0, t1, d;
  fp_sqr(t0, a.c0);
  fp_sqr(t1, a.c1);
  fp_add(n, t0, t1);            // norm
  if (!fp_sqrt(d, n)) return false;
  Fp two = FP_ONE, inv2;
  fp_add(two, FP_ONE, FP_ONE);
  fp_inv(inv2, two);
  Fp x0, r0;
  fp_add(x0, a.c0, d);
  fp_mul(x0, x0, inv2);
  if (!fp_sqrt(r0, x0)) {
    fp_sub(x0, a.c0, d);
    fp_mul(x0, x0, inv2);
    if (!fp_sqrt(r0, x0)) return false;
  }
  if (fp_is_zero(r0)) {
    Fp half_c1, r1;
    fp_mul(half_c1, a.c1, inv2);
    if (!fp_sqrt(r1, half_c1)) return false;
    Fp2 cand = {FP_ZERO, r1}, sq;
    f2_sqr(sq, cand);
    if (!f2_eq(sq, a)) return false;
    r = cand;
    return true;
  }
  Fp r0x2, r0x2i, r1;
  fp_add(r0x2, r0, r0);
  fp_inv(r0x2i, r0x2);
  fp_mul(r1, a.c1, r0x2i);
  Fp2 cand = {r0, r1}, sq;
  f2_sqr(sq, cand);
  if (!f2_eq(sq, a)) return false;
  r = cand;
  return true;
}

// ---------------------------------------------------------------- Fp6
struct Fp6 { Fp2 c0, c1, c2; };  // over Fp2, v^3 = XI
static Fp6 F6_ZERO, F6_ONE;

static inline void f6_add(Fp6 &r, const Fp6 &a, const Fp6 &b) {
  f2_add(r.c0, a.c0, b.c0);
  f2_add(r.c1, a.c1, b.c1);
  f2_add(r.c2, a.c2, b.c2);
}
static inline void f6_sub(Fp6 &r, const Fp6 &a, const Fp6 &b) {
  f2_sub(r.c0, a.c0, b.c0);
  f2_sub(r.c1, a.c1, b.c1);
  f2_sub(r.c2, a.c2, b.c2);
}
static inline void f6_neg(Fp6 &r, const Fp6 &a) {
  f2_neg(r.c0, a.c0);
  f2_neg(r.c1, a.c1);
  f2_neg(r.c2, a.c2);
}
static void f6_mul(Fp6 &r, const Fp6 &a, const Fp6 &b) {
  Fp2 t0, t1, t2, s1, s2, x, y;
  f2_mul(t0, a.c0, b.c0);
  f2_mul(t1, a.c1, b.c1);
  f2_mul(t2, a.c2, b.c2);
  // c0 = t0 + XI*((a1+a2)(b1+b2) - t1 - t2)
  f2_add(s1, a.c1, a.c2);
  f2_add(s2, b.c1, b.c2);
  f2_mul(x, s1, s2);
  f2_sub(x, x, t1);
  f2_sub(x, x, t2);
  f2_mul(x, XI, x);
  f2_add(r.c0, t0, x);
  // c1 = (a0+a1)(b0+b1) - t0 - t1 + XI*t2
  f2_add(s1, a.c0, a.c1);
  f2_add(s2, b.c0, b.c1);
  f2_mul(x, s1, s2);
  f2_sub(x, x, t0);
  f2_sub(x, x, t1);
  f2_mul(y, XI, t2);
  f2_add(r.c1, x, y);
  // c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
  f2_add(s1, a.c0, a.c2);
  f2_add(s2, b.c0, b.c2);
  f2_mul(x, s1, s2);
  f2_sub(x, x, t0);
  f2_sub(x, x, t2);
  f2_add(r.c2, x, t1);
}
static inline void f6_sqr(Fp6 &r, const Fp6 &a) { f6_mul(r, a, a); }
static void f6_mulv(Fp6 &r, const Fp6 &a) {  // * v
  Fp2 t;
  f2_mul(t, XI, a.c2);
  r.c2 = a.c1;
  r.c1 = a.c0;
  r.c0 = t;
}
static void f6_inv(Fp6 &r, const Fp6 &a) {
  Fp2 t0, t1, t2, x, y, den, di;
  f2_sqr(t0, a.c0);
  f2_mul(x, a.c1, a.c2);
  f2_mul(x, XI, x);
  f2_sub(t0, t0, x);                 // t0 = a0^2 - XI*a1*a2
  f2_sqr(t1, a.c2);
  f2_mul(t1, XI, t1);
  f2_mul(x, a.c0, a.c1);
  f2_sub(t1, t1, x);                 // t1 = XI*a2^2 - a0*a1
  f2_sqr(t2, a.c1);
  f2_mul(x, a.c0, a.c2);
  f2_sub(t2, t2, x);                 // t2 = a1^2 - a0*a2
  f2_mul(den, a.c0, t0);
  f2_mul(x, a.c2, t1);
  f2_mul(y, a.c1, t2);
  f2_add(x, x, y);
  f2_mul(x, XI, x);
  f2_add(den, den, x);
  f2_inv(di, den);
  f2_mul(r.c0, t0, di);
  f2_mul(r.c1, t1, di);
  f2_mul(r.c2, t2, di);
}

// --------------------------------------------------------------- Fp12
struct Fp12 { Fp6 c0, c1; };  // over Fp6, w^2 = v
static Fp12 F12_ONE;

static inline void f12_sub(Fp12 &r, const Fp12 &a, const Fp12 &b) {
  f6_sub(r.c0, a.c0, b.c0);
  f6_sub(r.c1, a.c1, b.c1);
}
static void f12_mul(Fp12 &r, const Fp12 &a, const Fp12 &b) {
  Fp6 t0, t1, s1, s2, x;
  f6_mul(t0, a.c0, b.c0);
  f6_mul(t1, a.c1, b.c1);
  f6_add(s1, a.c0, a.c1);
  f6_add(s2, b.c0, b.c1);
  f6_mul(x, s1, s2);
  f6_sub(x, x, t0);
  f6_sub(r.c1, x, t1);
  f6_mulv(t1, t1);
  f6_add(r.c0, t0, t1);
}
static inline void f12_sqr(Fp12 &r, const Fp12 &a) { f12_mul(r, a, a); }
static void f12_inv(Fp12 &r, const Fp12 &a) {
  Fp6 t0, t1, den, di, n1;
  f6_sqr(t0, a.c0);
  f6_sqr(t1, a.c1);
  f6_mulv(t1, t1);
  f6_sub(den, t0, t1);
  f6_inv(di, den);
  f6_mul(r.c0, a.c0, di);
  f6_neg(n1, a.c1);
  f6_mul(r.c1, n1, di);
}
static inline void f12_conj(Fp12 &r, const Fp12 &a) {  // Frobenius^6
  r.c0 = a.c0;
  f6_neg(r.c1, a.c1);
}
static bool f12_is_one(const Fp12 &a) {
  if (!f2_eq(a.c0.c0, F2_ONE)) return false;
  return f2_is_zero(a.c0.c1) && f2_is_zero(a.c0.c2) &&
         f2_is_zero(a.c1.c0) && f2_is_zero(a.c1.c1) && f2_is_zero(a.c1.c2);
}
static void f12_pow(Fp12 &r, const Fp12 &a, const uint8_t *e, size_t n) {
  Fp12 acc = F12_ONE;
  for (size_t i = 0; i < n; i++)
    for (int bit = 7; bit >= 0; bit--) {
      f12_sqr(acc, acc);
      if ((e[i] >> bit) & 1) f12_mul(acc, acc, a);
    }
  r = acc;
}

// Frobenius gammas: GAMMA_V = XI^((p-1)/3), GAMMA_V2 = XI^(2(p-1)/3),
// GAMMA_W = XI^((p-1)/6) — computed at init from baked exponents.
static Fp2 GAMMA_V, GAMMA_V2, GAMMA_W;
// (p-1)/6 BE bytes (regen: hex((P-1)//6))
static const uint8_t EXP_P1_6[48] = {
    0x04,0x55,0x82,0xfc,0x5e,0xea,0xa6,0x6f,0x0c,0x84,0x9b,0xf3,
    0xb5,0xe1,0xf2,0x23,0xe6,0x13,0xe1,0xeb,0x7d,0xeb,0x83,0x1f,
    0xe6,0x88,0x23,0x1a,0xd3,0xc8,0x29,0x06,0x05,0x1c,0xaa,0xaa,
    0x72,0xe3,0x55,0x55,0x49,0xaa,0x7f,0xff,0xff,0xff,0xf1,0xc7};

static void f6_frob(Fp6 &r, const Fp6 &a) {
  Fp2 t;
  f2_conj(r.c0, a.c0);
  f2_conj(t, a.c1);
  f2_mul(r.c1, t, GAMMA_V);
  f2_conj(t, a.c2);
  f2_mul(r.c2, t, GAMMA_V2);
}
static void f12_frob(Fp12 &r, const Fp12 &a) {
  Fp6 t;
  f6_frob(r.c0, a.c0);
  f6_frob(t, a.c1);
  f2_mul(r.c1.c0, t.c0, GAMMA_W);
  f2_mul(r.c1.c1, t.c1, GAMMA_W);
  f2_mul(r.c1.c2, t.c2, GAMMA_W);
}

// hard exponent (p^4 - p^2 + 1)/r, 1268 bits -> 159 BE bytes
// (regen: hex((P**4 - P**2 + 1)//R))
static const uint8_t EXP_HARD[159] = {
    0x0f,0x68,0x6b,0x3d,0x80,0x7d,0x01,0xc0,0xbd,0x38,0xc3,0x19,
    0x5c,0x89,0x9e,0xd3,0xcd,0xe8,0x8e,0xeb,0x99,0x6c,0xa3,0x94,
    0x50,0x66,0x32,0x52,0x8d,0x6a,0x9a,0x2f,0x23,0x00,0x63,0xcf,
    0x08,0x15,0x17,0xf6,0x8f,0x77,0x64,0xc2,0x8b,0x6f,0x8a,0xe5,
    0xa7,0x2b,0xce,0x8d,0x63,0xcb,0x9f,0x82,0x7e,0xca,0x0b,0xa6,
    0x21,0x31,0x5b,0x20,0x76,0x99,0x50,0x03,0xfc,0x77,0xa1,0x79,
    0x88,0xf8,0x76,0x1b,0xdc,0x51,0xdc,0x23,0x78,0xb9,0x03,0x90,
    0x96,0xd1,0xb7,0x67,0xf1,0x7f,0xcb,0xde,0x78,0x37,0x65,0x91,
    0x5c,0x97,0xf3,0x6c,0x6f,0x18,0x21,0x2e,0xd0,0xb2,0x83,0xed,
    0x23,0x7d,0xb4,0x21,0xd1,0x60,0xae,0xb6,0xa1,0xe7,0x99,0x83,
    0x77,0x49,0x40,0x99,0x67,0x54,0xc8,0xc7,0x1a,0x26,0x29,0xb0,
    0xde,0xa2,0x36,0x90,0x5c,0xe9,0x37,0x33,0x5d,0x5b,0x68,0xfa,
    0x99,0x12,0xaa,0xe2,0x08,0xcc,0xf1,0xe5,0x16,0xc3,0xf4,0x38,
    0xe3,0xba,0x79};

static void final_exp(Fp12 &r, const Fp12 &f) {
  Fp12 g, inv, fr;
  f12_inv(inv, f);
  f12_conj(g, f);
  f12_mul(g, g, inv);       // f^(p^6-1)
  f12_frob(fr, g);
  f12_frob(fr, fr);
  f12_mul(g, fr, g);        // ^(p^2+1)
  f12_pow(r, g, EXP_HARD, sizeof(EXP_HARD));
}

// -------------------------------------------------------------- curves
struct G1 { Fp x, y; bool inf; };
struct G2 { Fp2 x, y; bool inf; };
static Fp B1;       // 4 (Montgomery)
static Fp2 B2;      // 4*(1+u)
static G1 G1_GEN;
static G2 G2_GEN;

static bool g1_on_curve(const G1 &p) {
  if (p.inf) return true;
  Fp y2, x3, t;
  fp_sqr(y2, p.y);
  fp_sqr(t, p.x);
  fp_mul(x3, t, p.x);
  fp_add(x3, x3, B1);
  return fp_eq(y2, x3);
}
static bool g2_on_curve(const G2 &p) {
  if (p.inf) return true;
  Fp2 y2, x3, t;
  f2_sqr(y2, p.y);
  f2_sqr(t, p.x);
  f2_mul(x3, t, p.x);
  f2_add(x3, x3, B2);
  return f2_eq(y2, x3);
}

// G1 Jacobian
struct G1J { Fp X, Y, Z; bool inf; };
static void g1j_dbl(G1J &r, const G1J &p) {
  if (p.inf) { r = p; return; }
  Fp A, Bv, C, D, E, F, t, X3, Y3, Z3;
  fp_sqr(A, p.X);
  fp_sqr(Bv, p.Y);
  fp_sqr(C, Bv);
  fp_add(t, p.X, Bv);
  fp_sqr(t, t);
  fp_sub(t, t, A);
  fp_sub(t, t, C);
  fp_add(D, t, t);
  fp_add(E, A, A);
  fp_add(E, E, A);
  fp_sqr(F, E);
  fp_sub(X3, F, D);
  fp_sub(X3, X3, D);
  fp_sub(t, D, X3);
  fp_mul(Y3, E, t);
  Fp c8;
  fp_add(c8, C, C);
  fp_add(c8, c8, c8);
  fp_add(c8, c8, c8);
  fp_sub(Y3, Y3, c8);
  fp_mul(Z3, p.Y, p.Z);
  fp_add(Z3, Z3, Z3);
  r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = fp_is_zero(Z3);
}
static void g1j_add_aff(G1J &r, const G1J &p, const G1 &q) {
  if (q.inf) { r = p; return; }
  if (p.inf) {
    r.X = q.x; r.Y = q.y; r.Z = FP_ONE; r.inf = false;
    return;
  }
  Fp Z1Z1, U2, S2, H, HH, I, J, rr, V, t, X3, Y3, Z3;
  fp_sqr(Z1Z1, p.Z);
  fp_mul(U2, q.x, Z1Z1);
  fp_mul(S2, q.y, p.Z);
  fp_mul(S2, S2, Z1Z1);
  if (fp_eq(U2, p.X)) {
    if (!fp_eq(S2, p.Y)) { r.inf = true; r.X = FP_ONE; r.Y = FP_ONE; r.Z = FP_ZERO; return; }
    g1j_dbl(r, p);
    return;
  }
  fp_sub(H, U2, p.X);
  fp_sqr(HH, H);
  fp_add(I, HH, HH);
  fp_add(I, I, I);
  fp_mul(J, H, I);
  fp_sub(rr, S2, p.Y);
  fp_add(rr, rr, rr);
  fp_mul(V, p.X, I);
  fp_sqr(X3, rr);
  fp_sub(X3, X3, J);
  fp_sub(X3, X3, V);
  fp_sub(X3, X3, V);
  fp_sub(t, V, X3);
  fp_mul(Y3, rr, t);
  fp_mul(t, p.Y, J);
  fp_add(t, t, t);
  fp_sub(Y3, Y3, t);
  fp_mul(Z3, H, p.Z);
  fp_add(Z3, Z3, Z3);
  r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = fp_is_zero(Z3);
}
static void g1j_to_aff(G1 &r, const G1J &p) {
  if (p.inf || fp_is_zero(p.Z)) { r.inf = true; r.x = FP_ZERO; r.y = FP_ONE; return; }
  Fp zi, zi2, zi3;
  fp_inv(zi, p.Z);
  fp_sqr(zi2, zi);
  fp_mul(zi3, zi2, zi);
  fp_mul(r.x, p.X, zi2);
  fp_mul(r.y, p.Y, zi3);
  r.inf = false;
}
static void g1_mul_bytes(G1 &r, const G1 &p, const uint8_t *k, size_t n) {
  G1J acc;
  acc.inf = true; acc.X = FP_ONE; acc.Y = FP_ONE; acc.Z = FP_ZERO;
  bool started = false;
  for (size_t i = 0; i < n; i++)
    for (int bit = 7; bit >= 0; bit--) {
      if (started) g1j_dbl(acc, acc);
      if ((k[i] >> bit) & 1) {
        g1j_add_aff(acc, acc, p);
        started = true;
      }
    }
  g1j_to_aff(r, acc);
}
static void g1_add(G1 &r, const G1 &a, const G1 &b) {
  G1J j;
  j.inf = a.inf;
  if (!a.inf) { j.X = a.x; j.Y = a.y; j.Z = FP_ONE; }
  else { j.X = FP_ONE; j.Y = FP_ONE; j.Z = FP_ZERO; }
  g1j_add_aff(j, j, b);
  g1j_to_aff(r, j);
}

// G2 Jacobian (same shapes over Fp2)
struct G2J { Fp2 X, Y, Z; bool inf; };
static void g2j_dbl(G2J &r, const G2J &p) {
  if (p.inf) { r = p; return; }
  Fp2 A, Bv, C, D, E, F, t, X3, Y3, Z3;
  f2_sqr(A, p.X);
  f2_sqr(Bv, p.Y);
  f2_sqr(C, Bv);
  f2_add(t, p.X, Bv);
  f2_sqr(t, t);
  f2_sub(t, t, A);
  f2_sub(t, t, C);
  f2_add(D, t, t);
  f2_add(E, A, A);
  f2_add(E, E, A);
  f2_sqr(F, E);
  f2_sub(X3, F, D);
  f2_sub(X3, X3, D);
  f2_sub(t, D, X3);
  f2_mul(Y3, E, t);
  Fp2 c8;
  f2_add(c8, C, C);
  f2_add(c8, c8, c8);
  f2_add(c8, c8, c8);
  f2_sub(Y3, Y3, c8);
  f2_mul(Z3, p.Y, p.Z);
  f2_add(Z3, Z3, Z3);
  r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = f2_is_zero(Z3);
}
static void g2j_add_aff(G2J &r, const G2J &p, const G2 &q) {
  if (q.inf) { r = p; return; }
  if (p.inf) {
    r.X = q.x; r.Y = q.y; r.Z = F2_ONE; r.inf = false;
    return;
  }
  Fp2 Z1Z1, U2, S2, H, HH, I, J, rr, V, t, X3, Y3, Z3;
  f2_sqr(Z1Z1, p.Z);
  f2_mul(U2, q.x, Z1Z1);
  f2_mul(S2, q.y, p.Z);
  f2_mul(S2, S2, Z1Z1);
  if (f2_eq(U2, p.X)) {
    if (!f2_eq(S2, p.Y)) { r.inf = true; r.X = F2_ONE; r.Y = F2_ONE; r.Z = F2_ZERO; return; }
    g2j_dbl(r, p);
    return;
  }
  f2_sub(H, U2, p.X);
  f2_sqr(HH, H);
  f2_add(I, HH, HH);
  f2_add(I, I, I);
  f2_mul(J, H, I);
  f2_sub(rr, S2, p.Y);
  f2_add(rr, rr, rr);
  f2_mul(V, p.X, I);
  f2_sqr(X3, rr);
  f2_sub(X3, X3, J);
  f2_sub(X3, X3, V);
  f2_sub(X3, X3, V);
  f2_sub(t, V, X3);
  f2_mul(Y3, rr, t);
  f2_mul(t, p.Y, J);
  f2_add(t, t, t);
  f2_sub(Y3, Y3, t);
  f2_mul(Z3, H, p.Z);
  f2_add(Z3, Z3, Z3);
  r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = f2_is_zero(Z3);
}
static void g2j_to_aff(G2 &r, const G2J &p) {
  if (p.inf || f2_is_zero(p.Z)) { r.inf = true; r.x = F2_ZERO; r.y = F2_ONE; return; }
  Fp2 zi, zi2, zi3;
  f2_inv(zi, p.Z);
  f2_sqr(zi2, zi);
  f2_mul(zi3, zi2, zi);
  f2_mul(r.x, p.X, zi2);
  f2_mul(r.y, p.Y, zi3);
  r.inf = false;
}
static void g2_mul_bytes(G2 &r, const G2 &p, const uint8_t *k, size_t n) {
  G2J acc;
  acc.inf = true; acc.X = F2_ONE; acc.Y = F2_ONE; acc.Z = F2_ZERO;
  bool started = false;
  for (size_t i = 0; i < n; i++)
    for (int bit = 7; bit >= 0; bit--) {
      if (started) g2j_dbl(acc, acc);
      if ((k[i] >> bit) & 1) {
        g2j_add_aff(acc, acc, p);
        started = true;
      }
    }
  g2j_to_aff(r, acc);
}

// group order r (BE bytes) for subgroup checks
static const uint8_t R_BYTES[32] = {
    0x73,0xed,0xa7,0x53,0x29,0x9d,0x7d,0x48,0x33,0x39,0xd8,0x08,
    0x09,0xa1,0xd8,0x05,0x53,0xbd,0xa4,0x02,0xff,0xfe,0x5b,0xfe,
    0xff,0xff,0xff,0xff,0x00,0x00,0x00,0x01};
// G1 cofactor (derived (p-u)/r; regen: hex(H1))
static const uint8_t H1_BYTES[16] = {
    0x39,0x6c,0x8c,0x00,0x55,0x55,0xe1,0x56,
    0x8c,0x00,0xaa,0xab,0x00,0x00,0xaa,0xab};

static bool g1_in_subgroup(const G1 &p) {
  if (!g1_on_curve(p)) return false;
  if (p.inf) return true;
  G1 t;
  g1_mul_bytes(t, p, R_BYTES, 32);
  return t.inf;
}
static bool g2_in_subgroup(const G2 &p) {
  if (!g2_on_curve(p)) return false;
  if (p.inf) return true;
  G2 t;
  g2_mul_bytes(t, p, R_BYTES, 32);
  return t.inf;
}

// ------------------------------------------------------------- pairing
// untwist Q=(x,y) in E'(Fp2) to E(Fp12): X = x*v^2/XI (c2 slot),
// Y = (y*v/XI)*w (c1.c1 slot) — same embedding as the Python oracle.
struct QEmb { Fp12 x, y; };
static void untwist(QEmb &r, const G2 &q) {
  Fp2 xi_inv, t;
  f2_inv(xi_inv, XI);
  memset(&r, 0, sizeof(r));
  r.x.c0 = F6_ZERO;
  r.x.c1 = F6_ZERO;
  f2_mul(t, q.x, xi_inv);
  r.x.c0.c2 = t;
  f2_mul(t, q.y, xi_inv);
  r.y.c0 = F6_ZERO;
  r.y.c1 = F6_ZERO;
  r.y.c1.c1 = t;
}
static void f12_from_fp(Fp12 &r, const Fp &a) {
  memset(&r, 0, sizeof(r));
  r.c0.c0.c0 = a;
  r.c0.c0.c1 = FP_ZERO;
  r.c0.c1 = F2_ZERO;
  r.c0.c2 = F2_ZERO;
  r.c1 = F6_ZERO;
}
// |u| = 0xd201000000010000, 64 bits
static const uint64_t ABS_U = 0xd201000000010000ULL;

static void miller_loop(Fp12 &f, const G1 &p, const G2 &q) {
  if (p.inf || q.inf) { f = F12_ONE; return; }
  QEmb Q, T;
  untwist(Q, q);
  T = Q;
  Fp12 xp, yp, lam, line, t0, t1, t2, three, two;
  f12_from_fp(xp, p.x);
  f12_from_fp(yp, p.y);
  Fp fp3, fp2v;
  fp_add(fp3, FP_ONE, FP_ONE);
  fp_add(fp3, fp3, FP_ONE);
  fp_add(fp2v, FP_ONE, FP_ONE);
  f12_from_fp(three, fp3);
  f12_from_fp(two, fp2v);
  f = F12_ONE;
  int top = 63;
  while (top >= 0 && !((ABS_U >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    // doubling step: lam = 3*xT^2 / (2*yT)
    f12_sqr(t0, T.x);
    f12_mul(t0, t0, three);
    f12_mul(t1, T.y, two);
    f12_inv(t1, t1);
    f12_mul(lam, t0, t1);
    // line = yP - yT - lam*(xP - xT)
    f12_sub(t0, xp, T.x);
    f12_mul(t0, lam, t0);
    f12_sub(line, yp, T.y);
    f12_sub(line, line, t0);
    f12_sqr(f, f);
    f12_mul(f, f, line);
    // T = 2T
    f12_sqr(t0, lam);
    f12_sub(t0, t0, T.x);
    f12_sub(t0, t0, T.x);          // x3
    f12_sub(t1, T.x, t0);
    f12_mul(t1, lam, t1);
    f12_sub(T.y, t1, T.y);
    T.x = t0;
    if ((ABS_U >> i) & 1) {
      // addition step: lam = (yQ - yT)/(xQ - xT)
      f12_sub(t0, Q.y, T.y);
      f12_sub(t1, Q.x, T.x);
      f12_inv(t1, t1);
      f12_mul(lam, t0, t1);
      f12_sub(t0, xp, T.x);
      f12_mul(t0, lam, t0);
      f12_sub(line, yp, T.y);
      f12_sub(line, line, t0);
      f12_mul(f, f, line);
      f12_sqr(t0, lam);
      f12_sub(t0, t0, T.x);
      f12_sub(t0, t0, Q.x);        // x3
      f12_sub(t2, T.x, t0);
      f12_mul(t2, lam, t2);
      f12_sub(T.y, t2, T.y);
      T.x = t0;
    }
  }
  Fp12 cf;
  f12_conj(cf, f);                 // u < 0
  f = cf;
}

// ------------------------------------------------------------- SHA-256
struct Sha256 {
  uint32_t h[8];
  uint64_t len;
  uint8_t buf[64];
  size_t fill;
};
static const uint32_t SHA_K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2};
static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}
static void sha_block(Sha256 &s, const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
           ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = s.h[0], b = s.h[1], c = s.h[2], d = s.h[3];
  uint32_t e = s.h[4], f = s.h[5], g = s.h[6], hh = s.h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + SHA_K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + mj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  s.h[0] += a; s.h[1] += b; s.h[2] += c; s.h[3] += d;
  s.h[4] += e; s.h[5] += f; s.h[6] += g; s.h[7] += hh;
}
static void sha_init(Sha256 &s) {
  static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  memcpy(s.h, H0, sizeof(H0));
  s.len = 0;
  s.fill = 0;
}
static void sha_update(Sha256 &s, const uint8_t *p, size_t n) {
  s.len += n;
  while (n) {
    size_t take = 64 - s.fill;
    if (take > n) take = n;
    memcpy(s.buf + s.fill, p, take);
    s.fill += take;
    p += take;
    n -= take;
    if (s.fill == 64) {
      sha_block(s, s.buf);
      s.fill = 0;
    }
  }
}
static void sha_final(Sha256 &s, uint8_t out[32]) {
  uint64_t bits = s.len * 8;
  uint8_t pad = 0x80;
  sha_update(s, &pad, 1);
  uint8_t z = 0;
  while (s.fill != 56) sha_update(s, &z, 1);
  uint8_t lb[8];
  for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (8 * (7 - i)));
  sha_update(s, lb, 8);
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 4; j++)
      out[4 * i + j] = (uint8_t)(s.h[i] >> (8 * (3 - j)));
}
static void sha256(uint8_t out[32], const uint8_t *a, size_t an,
                   const uint8_t *b, size_t bn, const uint8_t *c, size_t cn) {
  Sha256 s;
  sha_init(s);
  if (an) sha_update(s, a, an);
  if (bn) sha_update(s, b, bn);
  if (cn) sha_update(s, c, cn);
  sha_final(s, out);
}

// expand_message_xmd(SHA-256) for length 64 (RFC 9380 5.3.1)
static int xmd64(uint8_t out[64], const uint8_t *msg, size_t msg_len,
                 const uint8_t *dst, size_t dst_len) {
  if (dst_len > 255) return -1;
  uint8_t dst_prime[256];
  memcpy(dst_prime, dst, dst_len);
  dst_prime[dst_len] = (uint8_t)dst_len;
  size_t dpl = dst_len + 1;
  uint8_t b0[32], bi[32];
  {
    Sha256 s;
    sha_init(s);
    uint8_t zpad[64] = {0};
    sha_update(s, zpad, 64);
    sha_update(s, msg, msg_len);
    uint8_t lib[3] = {0x00, 0x40, 0x00};  // I2OSP(64,2) || 0x00
    sha_update(s, lib, 3);
    sha_update(s, dst_prime, dpl);
    sha_final(s, b0);
  }
  {
    Sha256 s;
    sha_init(s);
    sha_update(s, b0, 32);
    uint8_t one = 1;
    sha_update(s, &one, 1);
    sha_update(s, dst_prime, dpl);
    sha_final(s, bi);
  }
  memcpy(out, bi, 32);
  {
    Sha256 s;
    sha_init(s);
    uint8_t x[32];
    for (int i = 0; i < 32; i++) x[i] = b0[i] ^ bi[i];
    sha_update(s, x, 32);
    uint8_t two = 2;
    sha_update(s, &two, 1);
    sha_update(s, dst_prime, dpl);
    sha_final(s, bi);
  }
  memcpy(out + 32, bi, 32);
  return 0;
}

// big-endian reduce 48 bytes mod p -> Fp (Montgomery)
static void fp_from_wide_be(Fp &r, const uint8_t *b48) {
  // value < 2^384; subtract p at most a few times in standard form
  uint64_t v[7] = {0};
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | b48[(5 - i) * 8 + j];
    v[i] = w;
  }
  // v < 2^384 < 6p, so at most a handful of subtractions (v[6] is
  // always 0 for 48-byte input; no borrow can leave the low 6 limbs)
  while (!(v[6] == 0 && cmp6(v, PL) < 0)) {
    u128 bw = 0;
    for (int i = 0; i < 6; i++) {
      u128 d = (u128)v[i] - PL[i] - (uint64_t)bw;
      v[i] = (uint64_t)d;
      bw = (d >> 64) ? 1 : 0;
    }
  }
  Fp t, r2;
  memcpy(t.l, v, 48);
  memcpy(r2.l, R2L, 48);
  fp_mul(r, t, r2);
}

// try-and-increment hash-to-G1 (identical to the Python oracle)
static int hash_to_g1(G1 &out, const uint8_t *msg, size_t msg_len,
                      const uint8_t *dst, size_t dst_len) {
  uint8_t dstc[300];
  if (dst_len > 250) return -1;
  memcpy(dstc, dst, dst_len);
  memcpy(dstc + dst_len, "|ctr=", 5);
  for (int ctr = 0; ctr < 256; ctr++) {
    dstc[dst_len + 5] = (uint8_t)ctr;
    uint8_t seed[64];
    if (xmd64(seed, msg, msg_len, dstc, dst_len + 6) != 0) return -1;
    Fp x;
    fp_from_wide_be(x, seed);
    Fp rhs, t;
    fp_sqr(t, x);
    fp_mul(rhs, t, x);
    fp_add(rhs, rhs, B1);
    Fp y;
    if (!fp_sqrt(y, rhs)) continue;
    bool odd = fp_is_odd(y);
    if (odd != ((seed[63] & 1) != 0)) fp_neg(y, y);
    G1 pt = {x, y, false};
    G1 cleared;
    g1_mul_bytes(cleared, pt, H1_BYTES, 16);
    if (!cleared.inf) {
      out = cleared;
      return 0;
    }
  }
  return -1;
}

// --------------------------------------------------- serialization
static const uint8_t C_FLAG = 0x80, I_FLAG = 0x40, S_FLAG = 0x20;

static void g1_compress(uint8_t out[48], const G1 &p) {
  if (p.inf) {
    memset(out, 0, 48);
    out[0] = C_FLAG | I_FLAG;
    return;
  }
  fp_to_bytes_be(out, p.x);
  out[0] |= C_FLAG;
  if (fp_is_big(p.y)) out[0] |= S_FLAG;
}
static int g1_decompress(G1 &r, const uint8_t in[48], bool subgroup) {
  uint8_t flags = in[0];
  if (!(flags & C_FLAG)) return -1;
  if (flags & I_FLAG) {
    if (flags & 0x3F) return -1;
    for (int i = 1; i < 48; i++)
      if (in[i]) return -1;
    r.inf = true;
    r.x = FP_ZERO;
    r.y = FP_ONE;
    return 0;
  }
  uint8_t xb[48];
  memcpy(xb, in, 48);
  xb[0] &= 0x1F;
  // range check x < p
  {
    uint64_t v[6];
    for (int i = 0; i < 6; i++) {
      uint64_t w = 0;
      for (int j = 0; j < 8; j++) w = (w << 8) | xb[(5 - i) * 8 + j];
      v[i] = w;
    }
    if (cmp6(v, PL) >= 0) return -1;
  }
  Fp x;
  fp_from_bytes_be(x, xb);
  Fp rhs, t, y;
  fp_sqr(t, x);
  fp_mul(rhs, t, x);
  fp_add(rhs, rhs, B1);
  if (!fp_sqrt(y, rhs)) return -1;
  bool big = fp_is_big(y);
  if (big != ((flags & S_FLAG) != 0)) fp_neg(y, y);
  r.x = x;
  r.y = y;
  r.inf = false;
  if (subgroup && !g1_in_subgroup(r)) return -1;
  return 0;
}
static void g2_compress(uint8_t out[96], const G2 &p) {
  if (p.inf) {
    memset(out, 0, 96);
    out[0] = C_FLAG | I_FLAG;
    return;
  }
  fp_to_bytes_be(out, p.x.c1);
  fp_to_bytes_be(out + 48, p.x.c0);
  out[0] |= C_FLAG;
  bool big = fp_is_big(p.y.c1) ||
             (fp_is_zero(p.y.c1) && fp_is_big(p.y.c0));
  if (big) out[0] |= S_FLAG;
}
static int g2_decompress(G2 &r, const uint8_t in[96], bool subgroup) {
  uint8_t flags = in[0];
  if (!(flags & C_FLAG)) return -1;
  if (flags & I_FLAG) {
    if (flags & 0x3F) return -1;
    for (int i = 1; i < 96; i++)
      if (in[i]) return -1;
    r.inf = true;
    r.x = F2_ZERO;
    r.y = F2_ONE;
    return 0;
  }
  uint8_t c1b[48], c0b[48];
  memcpy(c1b, in, 48);
  c1b[0] &= 0x1F;
  memcpy(c0b, in + 48, 48);
  for (int part = 0; part < 2; part++) {
    const uint8_t *b = part ? c0b : c1b;
    uint64_t v[6];
    for (int i = 0; i < 6; i++) {
      uint64_t w = 0;
      for (int j = 0; j < 8; j++) w = (w << 8) | b[(5 - i) * 8 + j];
      v[i] = w;
    }
    if (cmp6(v, PL) >= 0) return -1;
  }
  Fp2 x;
  fp_from_bytes_be(x.c1, c1b);
  fp_from_bytes_be(x.c0, c0b);
  Fp2 rhs, t, y;
  f2_sqr(t, x);
  f2_mul(rhs, t, x);
  f2_add(rhs, rhs, B2);
  if (!f2_sqrt(y, rhs)) return -1;
  bool big = fp_is_big(y.c1) || (fp_is_zero(y.c1) && fp_is_big(y.c0));
  if (big != ((flags & S_FLAG) != 0)) f2_neg(y, y);
  r.x = x;
  r.y = y;
  r.inf = false;
  if (subgroup && !g2_in_subgroup(r)) return -1;
  return 0;
}

// ---------------------------------------------------------------- init
static bool INIT_DONE = false;
static G2 NEG_G2_GEN;
static void init_all() {
  if (INIT_DONE) return;
  memset(&FP_ZERO, 0, sizeof(FP_ZERO));
  // FP_ONE = to_mont(1)
  {
    Fp one = {{1, 0, 0, 0, 0, 0}}, r2;
    memcpy(r2.l, R2L, 48);
    fp_mul(FP_ONE, one, r2);
  }
  F2_ZERO.c0 = FP_ZERO;
  F2_ZERO.c1 = FP_ZERO;
  F2_ONE.c0 = FP_ONE;
  F2_ONE.c1 = FP_ZERO;
  XI.c0 = FP_ONE;
  XI.c1 = FP_ONE;
  F6_ZERO.c0 = F2_ZERO; F6_ZERO.c1 = F2_ZERO; F6_ZERO.c2 = F2_ZERO;
  F6_ONE.c0 = F2_ONE; F6_ONE.c1 = F2_ZERO; F6_ONE.c2 = F2_ZERO;
  F12_ONE.c0 = F6_ONE;
  F12_ONE.c1 = F6_ZERO;
  // B1 = 4, B2 = 4*XI
  Fp two;
  fp_add(two, FP_ONE, FP_ONE);
  fp_add(B1, two, two);
  f2_muls(B2, XI, 4);
  // generators (standard constants, big-endian)
  static const uint8_t G1X[48] = {
      0x17,0xf1,0xd3,0xa7,0x31,0x97,0xd7,0x94,0x26,0x95,0x63,0x8c,
      0x4f,0xa9,0xac,0x0f,0xc3,0x68,0x8c,0x4f,0x97,0x74,0xb9,0x05,
      0xa1,0x4e,0x3a,0x3f,0x17,0x1b,0xac,0x58,0x6c,0x55,0xe8,0x3f,
      0xf9,0x7a,0x1a,0xef,0xfb,0x3a,0xf0,0x0a,0xdb,0x22,0xc6,0xbb};
  static const uint8_t G1Y[48] = {
      0x08,0xb3,0xf4,0x81,0xe3,0xaa,0xa0,0xf1,0xa0,0x9e,0x30,0xed,
      0x74,0x1d,0x8a,0xe4,0xfc,0xf5,0xe0,0x95,0xd5,0xd0,0x0a,0xf6,
      0x00,0xdb,0x18,0xcb,0x2c,0x04,0xb3,0xed,0xd0,0x3c,0xc7,0x44,
      0xa2,0x88,0x8a,0xe4,0x0c,0xaa,0x23,0x29,0x46,0xc5,0xe7,0xe1};
  static const uint8_t G2X0[48] = {
      0x02,0x4a,0xa2,0xb2,0xf0,0x8f,0x0a,0x91,0x26,0x08,0x05,0x27,
      0x2d,0xc5,0x10,0x51,0xc6,0xe4,0x7a,0xd4,0xfa,0x40,0x3b,0x02,
      0xb4,0x51,0x0b,0x64,0x7a,0xe3,0xd1,0x77,0x0b,0xac,0x03,0x26,
      0xa8,0x05,0xbb,0xef,0xd4,0x80,0x56,0xc8,0xc1,0x21,0xbd,0xb8};
  static const uint8_t G2X1[48] = {
      0x13,0xe0,0x2b,0x60,0x52,0x71,0x9f,0x60,0x7d,0xac,0xd3,0xa0,
      0x88,0x27,0x4f,0x65,0x59,0x6b,0xd0,0xd0,0x99,0x20,0xb6,0x1a,
      0xb5,0xda,0x61,0xbb,0xdc,0x7f,0x50,0x49,0x33,0x4c,0xf1,0x12,
      0x13,0x94,0x5d,0x57,0xe5,0xac,0x7d,0x05,0x5d,0x04,0x2b,0x7e};
  static const uint8_t G2Y0[48] = {
      0x0c,0xe5,0xd5,0x27,0x72,0x7d,0x6e,0x11,0x8c,0xc9,0xcd,0xc6,
      0xda,0x2e,0x35,0x1a,0xad,0xfd,0x9b,0xaa,0x8c,0xbd,0xd3,0xa7,
      0x6d,0x42,0x9a,0x69,0x51,0x60,0xd1,0x2c,0x92,0x3a,0xc9,0xcc,
      0x3b,0xac,0xa2,0x89,0xe1,0x93,0x54,0x86,0x08,0xb8,0x28,0x01};
  static const uint8_t G2Y1[48] = {
      0x06,0x06,0xc4,0xa0,0x2e,0xa7,0x34,0xcc,0x32,0xac,0xd2,0xb0,
      0x2b,0xc2,0x8b,0x99,0xcb,0x3e,0x28,0x7e,0x85,0xa7,0x63,0xaf,
      0x26,0x74,0x92,0xab,0x57,0x2e,0x99,0xab,0x3f,0x37,0x0d,0x27,
      0x5c,0xec,0x1d,0xa1,0xaa,0xa9,0x07,0x5f,0xf0,0x5f,0x79,0xbe};
  fp_from_bytes_be(G1_GEN.x, G1X);
  fp_from_bytes_be(G1_GEN.y, G1Y);
  G1_GEN.inf = false;
  fp_from_bytes_be(G2_GEN.x.c0, G2X0);
  fp_from_bytes_be(G2_GEN.x.c1, G2X1);
  fp_from_bytes_be(G2_GEN.y.c0, G2Y0);
  fp_from_bytes_be(G2_GEN.y.c1, G2Y1);
  G2_GEN.inf = false;
  NEG_G2_GEN = G2_GEN;
  f2_neg(NEG_G2_GEN.y, G2_GEN.y);
  // Frobenius gammas: GAMMA_V = XI^((p-1)/3) = (XI^((p-1)/6))^2
  f2_pow(GAMMA_W, XI, EXP_P1_6, 48);
  f2_sqr(GAMMA_V, GAMMA_W);
  f2_mul(GAMMA_V2, GAMMA_V, GAMMA_V);
  INIT_DONE = true;
}

// ----------------------------------------------------------------- API
extern "C" {

// 1 = valid, 0 = invalid/malformed
int cessbls_verify(const uint8_t *pk96, const uint8_t *msg, size_t msg_len,
                   const uint8_t *sig48, const uint8_t *dst,
                   size_t dst_len) {
  init_all();
  G2 pk;
  G1 sig;
  if (g2_decompress(pk, pk96, true) != 0) return 0;
  if (g1_decompress(sig, sig48, true) != 0) return 0;
  if (pk.inf || sig.inf) return 0;
  G1 h;
  if (hash_to_g1(h, msg, msg_len, dst, dst_len) != 0) return 0;
  Fp12 f1, f2v, f;
  miller_loop(f1, sig, NEG_G2_GEN);
  miller_loop(f2v, h, pk);
  f12_mul(f, f1, f2v);
  Fp12 out;
  final_exp(out, f);
  return f12_is_one(out) ? 1 : 0;
}

// sig = sk * H(msg); sk is 32 bytes big-endian (already reduced mod r
// by the caller). Returns 0 on success.
int cessbls_sign(const uint8_t *sk32, const uint8_t *msg, size_t msg_len,
                 const uint8_t *dst, size_t dst_len, uint8_t *out48) {
  init_all();
  G1 h, s;
  if (hash_to_g1(h, msg, msg_len, dst, dst_len) != 0) return -1;
  g1_mul_bytes(s, h, sk32, 32);
  g1_compress(out48, s);
  return 0;
}

// pk = sk * G2. Returns 0 on success.
int cessbls_pk_from_sk(const uint8_t *sk32, uint8_t *out96) {
  init_all();
  G2 pk;
  g2_mul_bytes(pk, G2_GEN, sk32, 32);
  g2_compress(out96, pk);
  return 0;
}

// aggregate verify over n (pk, msg) pairs against one aggregate sig.
// msgs are concatenated; msg_lens holds each length. 1 = valid.
int cessbls_aggregate_verify(size_t n, const uint8_t *pks96,
                             const uint8_t *msgs, const size_t *msg_lens,
                             const uint8_t *sig48, const uint8_t *dst,
                             size_t dst_len) {
  init_all();
  G1 sig;
  if (g1_decompress(sig, sig48, true) != 0) return 0;
  if (sig.inf) return 0;
  Fp12 f, fi;
  miller_loop(f, sig, NEG_G2_GEN);
  const uint8_t *mp = msgs;
  for (size_t i = 0; i < n; i++) {
    G2 pk;
    if (g2_decompress(pk, pks96 + 96 * i, true) != 0) return 0;
    if (pk.inf) return 0;
    G1 h;
    if (hash_to_g1(h, mp, msg_lens[i], dst, dst_len) != 0) return 0;
    mp += msg_lens[i];
    miller_loop(fi, h, pk);
    f12_mul(f, f, fi);
  }
  Fp12 out;
  final_exp(out, f);
  return f12_is_one(out) ? 1 : 0;
}

// aggregate n G1 signatures. Returns 0 on success.
int cessbls_aggregate(size_t n, const uint8_t *sigs48, uint8_t *out48) {
  init_all();
  G1 acc;
  acc.inf = true;
  acc.x = FP_ZERO;
  acc.y = FP_ONE;
  for (size_t i = 0; i < n; i++) {
    G1 s;
    if (g1_decompress(s, sigs48 + 48 * i, true) != 0) return -1;
    G1 sum;
    g1_add(sum, acc, s);
    acc = sum;
  }
  g1_compress(out48, acc);
  return 0;
}

// internal sanity: generator orders + pairing bilinearity on small
// scalars. 1 = healthy.
int cessbls_selftest() {
  init_all();
  if (!g1_on_curve(G1_GEN) || !g2_on_curve(G2_GEN)) return 0;
  if (!g1_in_subgroup(G1_GEN) || !g2_in_subgroup(G2_GEN)) return 0;
  // e(2P, 3Q) == e(3P, 2Q) (both = e(P,Q)^6), != 1
  uint8_t two[1] = {2}, three[1] = {3};
  G1 p2, p3;
  G2 q2, q3;
  g1_mul_bytes(p2, G1_GEN, two, 1);
  g1_mul_bytes(p3, G1_GEN, three, 1);
  g2_mul_bytes(q2, G2_GEN, two, 1);
  g2_mul_bytes(q3, G2_GEN, three, 1);
  Fp12 a, b, ea, eb;
  miller_loop(a, p2, q3);
  miller_loop(b, p3, q2);
  final_exp(ea, a);
  final_exp(eb, b);
  if (f12_is_one(ea)) return 0;
  for (int i = 0; i < 1; i++) {
    if (memcmp(&ea, &eb, sizeof(ea)) != 0) return 0;
  }
  return 1;
}

}  // extern "C"
