"""cess-tpu: a TPU-native decentralized-storage framework.

A brand-new framework with the capability set of the reference CESS
chain (see SURVEY.md): purchased storage space, 16 MiB segments
erasure-coded into fragments dispatched to storage miners, a PoDR2
random-challenge audit loop with rewards/slashing, a repair market,
credit-weighted validator election, and TEE-attested verification —
with the two computational hot paths (Reed-Solomon erasure coding and
PoDR2 tag/proof computation) executed as batched GF(2^8) / prime-field
matmuls on TPU via JAX/XLA/Pallas.

Layout:
- ``cess_tpu.ops``       device-layer kernels (GF(2^8) RS codec, PoDR2)
- ``cess_tpu.parallel``  mesh/sharding for multi-chip scale-out
- ``cess_tpu.models``    end-to-end pipelines (the "flagship model" =
                         storage pipeline: segment -> encode -> tag)
- ``cess_tpu.chain``     deterministic protocol state machine (pallet
                         equivalents: file-bank, audit, sminer, ...)
- ``cess_tpu.node``      consensus (RRSC-style VRF), scheduler, RPC
- ``cess_tpu.crypto``    host-side crypto (SHA-256, RSA, VRF)
- ``cess_tpu.native``    C++ native components (CPU codec baseline)
"""

__version__ = "0.1.0"
