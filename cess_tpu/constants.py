"""Protocol constants for cess-tpu.

Mirrors the reference chain's protocol constants (citations into /root/reference):

- ``SEGMENT_SIZE``/``FRAGMENT_SIZE``/``CHUNK_COUNT``: primitives/common/src/lib.rs:56-62
- ``FRAGMENT_COUNT`` (this reference snapshot pins 3 fragments/segment = RS(2,1)):
  runtime/src/lib.rs:1026-1027
- challenge coverage 46/1000 of chunks: c-pallets/audit/src/lib.rs:956
- challenge scale caps: runtime/src/lib.rs:988-992

The codec geometry (k data + m parity fragments) is a first-class parameter here —
the reference snapshot uses (k=2, m=1); the TPU performance configs use (k=4, m=8)
per BASELINE.json.
"""

MIB = 1024 * 1024

# --- data-plane geometry (primitives/common/src/lib.rs:56-62) ---
SEGMENT_SIZE = 16 * MIB          # bytes per segment
FRAGMENT_SIZE = 8 * MIB          # bytes per fragment in the reference (k=2) geometry
CHUNK_COUNT = 1024               # audit chunks per fragment

# Reference snapshot erasure geometry: 3 fragments per segment = RS(k=2, m=1)
# (runtime/src/lib.rs:1026-1027, redundancy math c-pallets/file-bank/src/lib.rs:440)
REF_K = 2
REF_M = 1
FRAGMENT_COUNT = REF_K + REF_M

# BASELINE.json target geometry: RS(4+8) = 12 fragments/segment
BASE_K = 4
BASE_M = 8

# --- audit (c-pallets/audit/src/lib.rs) ---
CHALLENGE_RATE_NUM = 46          # 46/1000 of CHUNK_COUNT chunks challenged per round
CHALLENGE_RATE_DEN = 1000        # c-pallets/audit/src/lib.rs:956
CHALLENGE_RANDOM_LEN = 20        # 20-byte randoms per challenged chunk (:966-974)
CHALLENGE_MINER_MAX = 8000       # runtime/src/lib.rs:988
VERIFY_MISSION_MAX = 500         # runtime/src/lib.rs:990
SIGMA_MAX = 2048                 # proof blob cap, runtime/src/lib.rs:992
AUDIT_FAULT_TOLERANCE = 2        # consecutive failures before punish, audit/src/constants.rs:1-3

# --- chain timing (runtime/src/lib.rs:234-255,561) ---
MILLISECS_PER_BLOCK = 6000
BLOCKS_PER_HOUR = 600
EPOCH_DURATION_BLOCKS = BLOCKS_PER_HOUR          # 1 h epochs
SESSIONS_PER_ERA = 6

# --- file-bank (runtime/src/lib.rs:1026-1032, c-pallets/file-bank) ---
SEGMENT_COUNT_MAX = 1000         # max segments per deal, runtime/src/lib.rs:1014,1032
DEAL_TIMEOUT_BLOCKS = 600        # per assigned miner, file-bank/src/functions.rs:156
DEAL_MAX_RETRIES = 5             # file-bank/src/lib.rs:511
SPACE_OVERHEAD_NUM = 3           # needed space = segs * SEGMENT_SIZE * 1.5
SPACE_OVERHEAD_DEN = 2           # file-bank/src/lib.rs:440-441
RESTORAL_ORDER_LIFE = 250        # blocks, restoral order deadline
FROZEN_SWEEP_MAX_FILES = 300     # lease-GC files per block, file-bank/src/lib.rs:362-402

# --- sminer economics (c-pallets/sminer/src/constants.rs, lib.rs) ---
IDLE_POWER_WEIGHT_NUM = 3        # power = 30% idle + 70% service (lib.rs:665-673)
SERVICE_POWER_WEIGHT_NUM = 7
POWER_WEIGHT_DEN = 10
REWARD_IMMEDIATE_NUM = 2         # 20% of reward order released immediately
REWARD_IMMEDIATE_DEN = 10        # sminer/src/lib.rs:675-733
RELEASE_NUMBER = 180             # tranches for the remaining 80% (prod value; test=2)
BASE_COLLATERAL = 2000           # CESS per (1 + power/TiB), sminer constants.rs:27
TIB = 1024 * 1024 * MIB

# punish tiers for missed challenges: 30% / 60% / 100% of collateral limit
CLEAR_PUNISH_TIERS = (30, 60, 100)   # c-pallets/audit/src/lib.rs:614-655

# --- staking economics (c-pallets/staking, runtime/src/lib.rs:585-589) ---
DOLLARS = 10 ** 12               # token base unit (12 decimals, typical CESS config)
VALIDATOR_REWARD_YEAR1 = 238_500_000 * DOLLARS
SMINER_REWARD_YEAR1 = 477_000_000 * DOLLARS
REWARD_DECAY_NUM = 841           # x0.841 per year for 30 years
REWARD_DECAY_DEN = 1000
REWARD_YEARS = 30
SCHEDULER_SLASH_PERMILL = 50     # slash_scheduler = 5% of MinValidatorBond
MIN_ELECTABLE_STAKE = 3_000_000 * DOLLARS   # runtime/src/lib.rs:764-772

# --- storage-handler ---
GIB = 1024 * MIB
SPACE_UNIT_GIB = 1               # price unit: per GiB per 30 days
ONE_DAY_BLOCKS = 14400           # 6 s blocks
MONTH_BLOCKS = 30 * ONE_DAY_BLOCKS

# --- scheduler-credit (c-pallets/scheduler-credit/src/lib.rs:36-42,61-75) ---
CREDIT_HISTORY_WEIGHTS = (50, 20, 15, 10, 5)   # percent, most-recent first
CREDIT_SCORE_SCALE = 1000

# --- transaction fees (TransactionPayment; runtime/src/lib.rs:190-204) ---
# 80% of fees to treasury, 20% to block author; values are framework
# choices (the reference derives them from weight benchmarks)
TX_BASE_FEE = 10 ** 8            # 1e-4 DOLLARS flat per signed extrinsic
TX_BYTE_FEE = 10 ** 5            # per encoded byte

# --- consensus (RRSC; runtime/src/lib.rs:181-185,240-241) ---
RRSC_C_NUM = 1                   # VRF threshold c = 1/4
RRSC_C_DEN = 4
