"""Durability plane: per-segment custody lineage + erasure margins.

The PoDR2 loop proves miners still *hold* fragments; nothing so far
answered "which segments are one erasure from loss, and what happened
to fragment F between upload and now?". This module closes that gap
as a data-plane observability layer under the house contracts:

* :class:`CustodyLedger` — a bounded, count-sequenced ledger of
  lineage events ingested from the existing offchain seams via the
  flight recorder (``("custody", ...)`` notes): gateway encode +
  dispatch, per-row custody transfers, TEE audit verdicts, repair
  completions and chain-reported losses (open restoral orders).
  Every event lands in a per-fragment timeline, so one query answers
  fragment F's whole history.

* :class:`DurabilityScorer` — folds ledger state against each
  segment's (k, m) geometry into a live erasure margin::

      margin = (# healthy fragments) - k
      healthy = not lost AND (holder unknown  # still gateway custody
                              OR (holder alive AND last audit passed))

  plus a fleet-wide margin histogram. Margins are pure folds of
  count-sequenced state — no wallclock, no entropy.

* :class:`CustodyDetector` — the edge-triggered ok/bad state machine
  (shape of chainwatch's ChainAnomalyDetector): ``at_risk`` when a
  margin falls to the threshold, ``lost`` when it goes negative, and
  ``market-divergence`` when the MarketWatch fake-capacity heuristic
  disagrees with the ledger's audit view of a miner. Each transition
  announces a ``custody.<cls>`` span plus a ``("custody", <cls>)``
  flight note — the edge serve/remediate.py maps to proactive symbol
  repair and obs/incident.py turns into a "custody" incident whose
  bundle embeds the segment's full timeline.

Zero-cost when off: the plane only exists when armed (sim
``Scenario.custody=True``, ``node.cli --custody``); the seams pay one
guarded ``_flight.note`` call otherwise. Everything here is
count-sequenced and seeded-deterministic: two same-seed sim runs
produce byte-identical :meth:`CustodyPlane.witness` bytes.
"""
from __future__ import annotations

import collections
import json
import threading

from . import flight as _flight
from . import trace as _trace

# at_risk fires while margin <= threshold: with the default 1 a
# segment announces one whole erasure BEFORE the last spare dies
AT_RISK_MARGIN = 1


def _hex(v) -> str:
    return v.hex() if isinstance(v, (bytes, bytearray)) else str(v)


class CustodyLedger:
    """Bounded per-fragment lineage timelines plus the custody state
    the scorer folds: segment geometry, current holder, last audit
    verdict per miner and the chain-reported loss set. Events carry
    the ledger's own count sequence (never wallclock)."""

    def __init__(self, *, timeline_cap: int = 32,
                 fragment_cap: int = 4096, log_cap: int = 2048):
        self._mu = threading.Lock()
        self._seq = 0
        self._events_total = 0
        self.timeline_cap = int(timeline_cap)
        self.fragment_cap = int(fragment_cap)
        # frag hex -> deque of event dicts (the per-fragment timeline)
        self._timelines: dict[str, collections.deque] = {}
        # frag hex -> current custodian account (None = gateway)
        self._holder: dict[str, str | None] = {}
        # frag hex -> "file:index" segment key
        self._frag_seg: dict[str, str] = {}
        # seg key -> {"file", "index", "k", "m", "frags": [hex, ...]}
        self._segments: dict[str, dict] = {}
        # miner -> latest audit verdict {"round", "service", "idle"}
        self._verdicts: dict[str, dict] = {}
        self._lost: set[str] = set()
        # flat count-sequenced event log (the witness spine)
        self._log: collections.deque = collections.deque(maxlen=log_cap)

    # -- recording (listener thread) -----------------------------------------
    def _event_locked(self, frag: str, kind: str, **detail) -> None:
        if frag not in self._timelines:
            if len(self._timelines) >= self.fragment_cap:
                return                      # bounded: drop new tails
            self._timelines[frag] = collections.deque(
                maxlen=self.timeline_cap)
        self._seq += 1
        self._events_total += 1
        self._timelines[frag].append({"seq": self._seq, "kind": kind,
                                      **detail})
        self._log.append((self._seq, kind, frag,
                          tuple(sorted(detail.items()))))

    def record_dispatch(self, owner: str, file_hex: str, k: int,
                        m: int, segments) -> None:
        """One gateway upload: ``segments`` is the declared seg_list
        — ``[(seg_hash, (frag_hash, ...)), ...]`` — straight off the
        ``("custody", "dispatch")`` note."""
        with self._mu:
            for index, (_seg_hash, frags) in enumerate(segments):
                key = f"{file_hex}:{index}"
                frag_hexes = [_hex(h) for h in frags]
                self._segments[key] = {"file": file_hex, "index": index,
                                       "k": int(k), "m": int(m),
                                       "frags": frag_hexes}
                for row, fh in enumerate(frag_hexes):
                    self._frag_seg[fh] = key
                    self._holder.setdefault(fh, None)
                    self._event_locked(fh, "dispatch", owner=owner,
                                       file=file_hex, segment=index,
                                       row=row)

    def record_transfer(self, miner: str, file_hex: str, row: int,
                        frags) -> None:
        with self._mu:
            for h in frags:
                fh = _hex(h)
                self._holder[fh] = miner
                self._event_locked(fh, "transfer", miner=miner,
                                   row=int(row))

    def record_verdict(self, miner: str, rnd: int, service: bool,
                       idle: bool, frags) -> None:
        with self._mu:
            self._verdicts[miner] = {"round": int(rnd),
                                     "service": bool(service),
                                     "idle": bool(idle)}
            for h in frags:
                fh = _hex(h)
                if fh in self._frag_seg:
                    self._event_locked(fh, "verdict", miner=miner,
                                       round=int(rnd),
                                       service=bool(service),
                                       idle=bool(idle))

    def record_repair(self, miner: str, frag, mode: str,
                      ingress: int) -> None:
        with self._mu:
            fh = _hex(frag)
            self._holder[fh] = miner
            self._lost.discard(fh)
            self._event_locked(fh, "repair", miner=miner,
                               mode=str(mode), ingress=int(ingress))

    def observe_restorals(self, frags) -> None:
        """Chain-reported losses: the open restoral-order set, scraped
        from runtime state once per round. New entries event as
        ``restoral``; completions are covered by the repair note."""
        with self._mu:
            now = {_hex(h) for h in frags}
            for fh in sorted(now - self._lost):
                if fh in self._frag_seg:
                    self._event_locked(fh, "restoral")
            self._lost = now

    # -- reading -------------------------------------------------------------
    def timeline(self, frag) -> tuple:
        with self._mu:
            return tuple(dict(e)
                         for e in self._timelines.get(_hex(frag), ()))

    def view(self) -> dict:
        """One consistent copy of the custody state the scorer folds."""
        with self._mu:
            return {
                "segments": {k: dict(v, frags=list(v["frags"]))
                             for k, v in self._segments.items()},
                "holder": dict(self._holder),
                "verdicts": {m: dict(v)
                             for m, v in self._verdicts.items()},
                "lost": set(self._lost),
            }

    def sizes(self) -> dict:
        with self._mu:
            return {"events_total": self._events_total,
                    "fragments": len(self._timelines),
                    "segments": len(self._segments),
                    "timeline_cap": self.timeline_cap,
                    "fragment_cap": self.fragment_cap}

    def log(self) -> tuple:
        with self._mu:
            return tuple(self._log)


class DurabilityScorer:
    """Pure fold: ledger view + holder-liveness map -> per-segment
    erasure margins and the fleet histogram. Stateless, so the sim
    invariant can re-run the exact fold against a fresh ledger view
    and compare it with raw world storage."""

    @staticmethod
    def healthy(view: dict, alive: dict, frag_hex: str) -> bool:
        if frag_hex in view["lost"]:
            return False
        holder = view["holder"].get(frag_hex)
        if holder is None:
            return True                 # still gateway custody
        if not alive.get(holder, True):
            return False
        v = view["verdicts"].get(holder)
        return v is None or bool(v["service"])

    @classmethod
    def fold(cls, view: dict, alive: dict) -> dict:
        margins: dict[str, int] = {}
        for key in sorted(view["segments"]):
            seg = view["segments"][key]
            good = sum(1 for fh in seg["frags"]
                       if cls.healthy(view, alive, fh))
            margins[key] = good - seg["k"]
        return margins

    @staticmethod
    def histogram(margins: dict) -> dict:
        hist: dict[str, int] = {}
        for m in margins.values():
            b = "neg" if m < 0 else ("3plus" if m >= 3 else str(m))
            hist[b] = hist.get(b, 0) + 1
        return {b: hist.get(b, 0)
                for b in ("neg", "0", "1", "2", "3plus")}


class CustodyDetector:
    """Edge-triggered ok/bad state per (class, key) with a bounded
    count-sequenced transition log — ChainAnomalyDetector's shape.
    Transitions announce FIFO under ``_announce_mu`` OUTSIDE the
    detector lock: a ``custody.<cls>`` span plus a
    ``("custody", <cls>)`` flight note per edge."""

    CLASSES = ("at_risk", "lost", "market-divergence")

    def __init__(self, *, log_cap: int = 512):
        self._mu = threading.Lock()
        self._seq = 0
        self._edges = 0
        self._state: dict[tuple, str] = {}
        self._log: collections.deque = collections.deque(maxlen=log_cap)
        # whichever thread holds the announce lock drains everything
        self._announce_mu = threading.RLock()
        self._pending: collections.deque = collections.deque()

    def update(self, cls: str, key: str, bad: bool, **detail) -> None:
        to = "bad" if bad else "ok"
        with self._mu:
            old = self._state.get((cls, key), "ok")
            if old == to:
                return
            self._state[(cls, key)] = to
            self._seq += 1
            if bad:
                self._edges += 1
            self._log.append((self._seq, cls, key, old, to))
            self._pending.append((cls, key, old, to, dict(detail)))
        self._drain_announcements()

    def _drain_announcements(self) -> None:
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending:
                        return
                    item = self._pending.popleft()
                self._announce(*item)

    def _announce(self, cls: str, key: str, old: str, to: str,
                  detail: dict) -> None:
        with _trace.span(f"custody.{cls}", sys="custody", key=key,
                         frm=old, to=to):
            pass
        _flight.note("custody", cls, key=key, frm=old, to=to, **detail)

    # -- reading -------------------------------------------------------------
    def transition_log(self) -> tuple:
        with self._mu:
            return tuple(self._log)

    def active(self) -> dict:
        with self._mu:
            out: dict = {}
            for (cls, key), st in sorted(self._state.items()):
                if st == "bad":
                    out.setdefault(cls, []).append(key)
            return out

    def snapshot(self) -> dict:
        with self._mu:
            state = dict(self._state)
            return {
                "seq": self._seq,
                "edges": self._edges,
                "active": {
                    cls: [k for (c, k), st in sorted(state.items())
                          if c == cls and st == "bad"]
                    for cls in self.CLASSES},
                "transitions": [list(t) for t in self._log],
            }

    def witness(self) -> bytes:
        with self._mu:
            canon = {
                "transitions": [list(t) for t in self._log],
                "active": sorted([c, k]
                                 for (c, k), st in self._state.items()
                                 if st == "bad"),
            }
        return json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()


class CustodyPlane:
    """Ledger + scorer + detector behind the house plane API.

    Arm it by subscribing :meth:`on_note` to the flight recorder (the
    seams' ``("custody", ...)`` notes feed the ledger) and calling
    :meth:`seal_round` once per observation round after feeding
    :meth:`observe_alive` / :meth:`observe_restorals`. Surfaces:
    ``cess_custodyStatus`` (:meth:`snapshot`), ``cess_custody_*``
    gauges (:meth:`metrics`), the remediation plane's repair targets
    (:meth:`repair_targets`) and the replay witness
    (:meth:`witness`)."""

    def __init__(self, instance: str = "node", *,
                 at_risk_margin: int = AT_RISK_MARGIN,
                 timeline_cap: int = 32, fragment_cap: int = 4096):
        self.instance = str(instance)
        self.at_risk_margin = int(at_risk_margin)
        self.ledger = CustodyLedger(timeline_cap=timeline_cap,
                                    fragment_cap=fragment_cap)
        self.detector = CustodyDetector()
        self._mu = threading.Lock()
        self._rounds = 0
        self._alive: dict[str, bool] = {}
        self._margins: dict[str, int] = {}

    # -- ingestion (flight-recorder listener) --------------------------------
    def on_note(self, seq: int, subsystem: str, kind: str,
                detail: dict) -> None:
        if subsystem != "custody":
            return
        if kind == "dispatch":
            self.ledger.record_dispatch(str(detail["owner"]),
                                        _hex(detail["file"]),
                                        detail["k"], detail["m"],
                                        detail["segments"])
        elif kind == "transfer":
            self.ledger.record_transfer(str(detail["miner"]),
                                        _hex(detail["file"]),
                                        detail["row"], detail["frags"])
        elif kind == "verdict":
            self.ledger.record_verdict(str(detail["miner"]),
                                       detail["round"],
                                       detail["service"],
                                       detail["idle"], detail["frags"])
        elif kind == "repair":
            self.ledger.record_repair(str(detail["miner"]),
                                      detail["frag"], detail["mode"],
                                      detail["ingress"])
        # detector announcements (at_risk/lost/market-divergence) are
        # also ("custody", ...) notes: ours, not lineage — ignored

    # -- per-round feeds ------------------------------------------------------
    def observe_alive(self, alive: dict) -> None:
        """Holder-liveness map {account: bool} for the next seal; on a
        live node the plane defaults every holder to alive."""
        with self._mu:
            self._alive = {str(k): bool(v) for k, v in alive.items()}

    def observe_restorals(self, frags) -> None:
        self.ledger.observe_restorals(frags)

    def holder_alive(self, acct: str) -> bool:
        """Last-fed liveness for an account (unknown = alive)."""
        with self._mu:
            return self._alive.get(str(acct), True)

    def fold_margins(self) -> dict:
        """Recompute per-segment margins from the CURRENT ledger view
        (the exact fold :meth:`seal_round` runs) without touching the
        sealed state — the custody-ledger-consistent invariant
        re-derives against this."""
        with self._mu:
            alive = dict(self._alive)
        return DurabilityScorer.fold(self.ledger.view(), alive)

    def seal_round(self) -> dict:
        """Fold margins and run the detector over them (edges announce
        outside every lock). Returns the sealed margins."""
        margins = self.fold_margins()
        with self._mu:
            self._margins = dict(margins)
            self._rounds += 1
        for key in sorted(margins):
            m = margins[key]
            self.detector.update("at_risk", key,
                                 m <= self.at_risk_margin, margin=m)
            self.detector.update("lost", key, m < 0, margin=m)
        return margins

    def cross_check_market(self, market: dict) -> None:
        """MarketWatch vs ledger (satellite): a miner the
        fake-capacity heuristic flags whose fragments still audit-pass
        in the ledger — or the inverse, a market-clean miner whose
        last ledger verdict failed — is a ``market-divergence`` edge
        keyed by the miner."""
        view = self.ledger.view()
        held: dict[str, int] = {}
        for holder in view["holder"].values():
            if holder is not None:
                held[holder] = held.get(holder, 0) + 1
        miners = market.get("miners", {})
        for who in sorted(miners):
            flagged = bool(miners[who].get("fake_capacity"))
            v = view["verdicts"].get(who)
            holds = held.get(who, 0) > 0
            if flagged and holds and v is not None and v["service"]:
                self.detector.update("market-divergence", who, True,
                                     reason="market-flags-audit-clean",
                                     frags=held[who])
            elif not flagged and holds and v is not None \
                    and not v["service"]:
                self.detector.update("market-divergence", who, True,
                                     reason="audit-fail-market-clean",
                                     frags=held[who])
            else:
                self.detector.update("market-divergence", who, False)

    # -- remediation feed ------------------------------------------------------
    def repair_targets(self, seg_key: str) -> tuple:
        """The unhealthy fragments of one segment, for the proactive
        repair action: ``({"file", "frag", "holder"}, ...)`` sorted by
        fragment hex. ``holder`` is the last custodian (the account a
        restoral order must be generated for)."""
        view = self.ledger.view()
        seg = view["segments"].get(str(seg_key))
        if seg is None:
            return ()
        with self._mu:
            alive = dict(self._alive)
        out = []
        for fh in sorted(seg["frags"]):
            if not DurabilityScorer.healthy(view, alive, fh):
                out.append({"file": seg["file"], "frag": fh,
                            "holder": view["holder"].get(fh)})
        return tuple(out)

    # -- surfaces --------------------------------------------------------------
    def margins(self) -> dict:
        with self._mu:
            return dict(self._margins)

    def segment_timeline(self, seg_key: str) -> dict:
        """Every fragment timeline of one segment — what incident
        bundles embed for a custody trigger."""
        view = self.ledger.view()
        seg = view["segments"].get(str(seg_key))
        if seg is None:
            return {}
        return {fh: [dict(e) for e in self.ledger.timeline(fh)]
                for fh in seg["frags"]}

    def metrics(self) -> dict:
        with self._mu:
            margins = dict(self._margins)
            rounds = self._rounds
        sizes = self.ledger.sizes()
        hist = DurabilityScorer.histogram(margins)
        active = self.detector.active()
        out = {
            "cess_custody_rounds": rounds,
            "cess_custody_segments": sizes["segments"],
            "cess_custody_fragments": sizes["fragments"],
            "cess_custody_ledger_events_total": sizes["events_total"],
            "cess_custody_margin_min": min(margins.values())
            if margins else 0,
            "cess_custody_segments_at_risk": len(active.get("at_risk",
                                                            ())),
            "cess_custody_segments_lost": len(active.get("lost", ())),
            "cess_custody_market_divergence": len(
                active.get("market-divergence", ())),
            "cess_custody_anomaly_edges": self.detector.snapshot()
            ["edges"],
        }
        for b, n in hist.items():
            out[f"cess_custody_margin_hist_{b}"] = n
        return out

    def snapshot(self) -> dict:
        """The ``cess_custodyStatus`` payload: geometry + margins +
        per-fragment custody rows per segment, the margin histogram,
        the at-risk/lost lists, the detector state and every bounded
        per-fragment timeline."""
        view = self.ledger.view()
        with self._mu:
            margins = dict(self._margins)
            alive = dict(self._alive)
            rounds = self._rounds
        segments = {}
        for key in sorted(view["segments"]):
            seg = view["segments"][key]
            segments[key] = {
                "file": seg["file"], "index": seg["index"],
                "k": seg["k"], "m": seg["m"],
                "margin": margins.get(key),
                "frags": [{
                    "hash": fh,
                    "holder": view["holder"].get(fh),
                    "healthy": DurabilityScorer.healthy(view, alive,
                                                        fh),
                    "lost": fh in view["lost"],
                } for fh in seg["frags"]],
            }
        active = self.detector.active()
        return {
            "instance": self.instance,
            "rounds": rounds,
            "at_risk_margin": self.at_risk_margin,
            "ledger": self.ledger.sizes(),
            "segments": segments,
            "histogram": DurabilityScorer.histogram(margins),
            "at_risk": list(active.get("at_risk", ())),
            "lost": list(active.get("lost", ())),
            "market_divergence": list(active.get("market-divergence",
                                                 ())),
            "anomalies": self.detector.snapshot(),
            "timelines": {fh: [dict(e)
                               for e in self.ledger.timeline(fh)]
                          for fh in sorted(view["holder"])},
        }

    def witness(self) -> bytes:
        """Canonical bytes of the flat ledger event log, the sealed
        margins and the detector transitions. Two same-seed sim runs
        must return identical bytes."""
        with self._mu:
            margins = dict(self._margins)
            rounds = self._rounds
        canon = {
            "rounds": rounds,
            "events": [[s, k, f, [list(p) for p in d]]
                       for (s, k, f, d) in self.ledger.log()],
            "margins": margins,
            "transitions": [list(t)
                            for t in self.detector.transition_log()],
        }
        return json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()
