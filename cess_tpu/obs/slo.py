"""SLO monitors + per-tenant accounting: the consumption layer over
the PR-5 histograms.

PR 5 made every queue-wait, occupancy and stage latency observable;
this module is the first thing that WATCHES it. A :class:`SloBoard`
holds declarative :class:`SloTarget` objectives (op class -> p99
latency bound + error-rate budget) and evaluates them over rolling
windows of the live engine observations with multi-window burn-rate
detection (the Google SRE shape: a fast window that confirms the
problem is happening NOW, a slow window that confirms it is
significant), plus per-tenant x per-class accounting so one heavy
uploader's traffic is attributable — and, downstream, fair-queued
(serve/engine.py) and sheddable (serve/adaptive.py).

Design contracts, matching the rest of cess_tpu/obs:

- **Deterministic**: windows advance on OBSERVATION COUNT, never wall
  clock — state is (re)evaluated every ``eval_every``-th observation
  of a class, so two replays of the same workload under the same
  seeded FaultPlan produce the identical state-transition log
  (tests/test_slo.py pins two replays transition-for-transition).
- **Zero-cost when off**: nothing here is consulted unless an engine
  was built with a board (``make_engine(slo=...)``); the disabled
  engine path is one attribute load and a ``None`` check, and
  allocates no SLO or tenant objects (the NOOP_SPAN contract).
- **Bounded**: tenant cardinality is capped (``max_tenants``; overflow
  aggregates under ``~other`` so a tenant-id flood cannot grow the
  exposition unboundedly) and the transition log is a bounded deque.

Burn-rate semantics: an observation *breaches* its target when it
failed or exceeded the p99 latency bound. The target's error budget is
``0.01 + error_rate`` (a p99 objective concedes 1% of observations
above the bound by definition; ``error_rate`` concedes outright
failures on top). ``burn = breach_fraction / budget`` over a window —
burn 1.0 spends the budget exactly as fast as allowed. The state
machine: **burning** when the fast-window burn clears ``page_burn``
AND the slow window confirms (>= ``warn_burn``); **warn** when the
slow window alone burns >= ``warn_burn``; **ok** otherwise.

Every transition is announced: a ``slo.transition`` span on the armed
tracer (chaos drills show WHEN the SLO flipped inside the request
flow) and a callback to registered listeners — which is how
serve/adaptive.py's admission controller extends the PR-4 breaker
from "device broken" to "SLO at risk".

Exposition: :meth:`SloBoard.series` yields labeled families
(``cess_slo_*`` gauges with a ``class`` label — ``state`` uses the
enum pattern, one series per state — and ``cess_tenant_*_total``
counters labeled ``tenant``/``class``); :meth:`tenant_histograms`
yields the per-tenant latency histogram families. node/metrics.py
renders both (label values escaped per the exposition format), and
the ``cess_sloStatus`` RPC serves :meth:`snapshot`.
"""
from __future__ import annotations

import collections
import dataclasses
import threading

from . import flight as _flight
from . import prom
from . import trace as _trace

STATES = ("ok", "warn", "burning")

# the tenant bucket unattributed requests land in, and the overflow
# bucket once max_tenants distinct names have been seen ("~" sorts
# after every printable tenant name and cannot collide with an
# account id in this codebase)
UNTAGGED = "-"
OVERFLOW = "~other"


@dataclasses.dataclass(frozen=True)
class SloTarget:
    """One objective: requests of op class ``cls`` should complete
    within ``p99_s`` seconds at the 99th percentile, with at most
    ``error_rate`` of them failing outright."""

    cls: str
    p99_s: float
    error_rate: float = 0.0

    def __post_init__(self):
        if not self.cls:
            raise ValueError("SloTarget needs an op class")
        if not self.p99_s > 0:
            raise ValueError(f"p99 objective must be > 0, got "
                             f"{self.p99_s!r}")
        if not 0 <= self.error_rate < 1:
            raise ValueError(f"error-rate objective must be in [0, 1), "
                             f"got {self.error_rate!r}")

    @property
    def budget(self) -> float:
        """Tolerated breach fraction: the 1% the p99 bound concedes by
        definition, plus the explicit failure allowance."""
        return 0.01 + self.error_rate


def _seconds(text: str) -> float:
    text = text.strip().lower()
    if text.endswith("ms"):
        return float(text[:-2]) / 1000.0
    if text.endswith("s"):
        return float(text[:-1])
    return float(text)


def _fraction(text: str) -> float:
    text = text.strip()
    if text.endswith("%"):
        return float(text[:-1]) / 100.0
    return float(text)


def parse_targets(spec: str) -> tuple[SloTarget, ...]:
    """The ``--slo`` CLI syntax: ``;``-separated targets, each
    ``<class>:p99=<dur>[,err=<frac>]`` where durations take an ``ms``
    or ``s`` suffix (bare numbers are seconds) and error rates take a
    ``%`` suffix (bare numbers are fractions).

        verify:p99=50ms,err=1%;encode:p99=2s

    An empty spec yields :data:`DEFAULT_TARGETS`.
    """
    spec = spec.strip()
    if not spec:
        return DEFAULT_TARGETS
    out = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        cls, sep, body = entry.partition(":")
        if not sep or not body:
            raise ValueError(f"bad SLO target {entry!r}: expected "
                             "<class>:p99=<duration>[,err=<rate>]")
        p99 = None
        err = 0.0
        for kv in body.split(","):
            key, sep, val = kv.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"bad SLO parameter {kv!r} in {entry!r}")
            if key == "p99":
                p99 = _seconds(val)
            elif key == "err":
                err = _fraction(val)
            else:
                raise ValueError(f"unknown SLO parameter {key!r} in "
                                 f"{entry!r} (p99/err)")
        if p99 is None:
            raise ValueError(f"SLO target {entry!r} needs p99=<duration>")
        out.append(SloTarget(cls.strip(), p99, err))
    return tuple(out)


# the --slo defaults: protect the audit-critical verify class tightly
# (a missed verify window slashes a miner), give proving the same
# round deadline pressure, and let bulk encode ride a loose bound
DEFAULT_TARGETS = (
    SloTarget("verify", p99_s=0.050, error_rate=0.01),
    SloTarget("prove", p99_s=0.100, error_rate=0.01),
    SloTarget("encode", p99_s=1.000, error_rate=0.05),
)


class _TenantStats:
    """Per (tenant, class) accounting: request/failure/shed counters,
    SERVED device rows (failed/expired work never counts), and the
    mergeable latency histogram."""

    __slots__ = ("requests", "failed", "shed", "rows", "hist")

    def __init__(self):
        self.requests = 0
        self.failed = 0
        self.shed = 0
        self.rows = 0
        self.hist = prom.Histogram(prom.LATENCY_BUCKETS_S)


class _TargetState:
    """Rolling-window burn-rate state for one target (board-lock
    guarded, like every mutable field on the board)."""

    __slots__ = ("target", "fast", "slow", "count", "state",
                 "fast_burn", "slow_burn")

    def __init__(self, target: SloTarget, fast_window: int,
                 slow_window: int):
        self.target = target
        self.fast: collections.deque = collections.deque(
            maxlen=fast_window)
        self.slow: collections.deque = collections.deque(
            maxlen=slow_window)
        self.count = 0               # observations ever (eval clock)
        self.state = "ok"
        self.fast_burn = 0.0
        self.slow_burn = 0.0


def _burn(window, budget: float) -> float:
    if not window:
        return 0.0
    return (sum(window) / len(window)) / budget


class SloBoard:
    """See module doc. One board per engine (``make_engine(slo=...)``);
    observations arrive from the engine batcher/submitter threads and
    scrapes read concurrently, so every mutable field is guarded by
    the one internal lock. Listener callbacks and transition spans
    fire OUTSIDE the lock (they touch other subsystems' locks — the
    health breaker — and must never nest under this one)."""

    def __init__(self, targets=DEFAULT_TARGETS, *, fast_window: int = 32,
                 slow_window: int = 256, eval_every: int = 8,
                 warn_burn: float = 1.0, page_burn: float = 6.0,
                 max_tenants: int = 64, max_transitions: int = 256):
        if fast_window < 1 or slow_window < fast_window \
                or eval_every < 1 or max_tenants < 1:
            raise ValueError("invalid SLO board bounds")
        if not 0 < warn_burn <= page_burn:
            raise ValueError(f"need 0 < warn_burn <= page_burn, got "
                             f"{warn_burn}/{page_burn}")
        targets = tuple(targets)
        if len({t.cls for t in targets}) != len(targets):
            raise ValueError("duplicate SLO target class")
        self.fast_window = fast_window
        self.slow_window = slow_window
        self.eval_every = eval_every
        self.warn_burn = warn_burn
        self.page_burn = page_burn
        self.max_tenants = max_tenants
        self._mu = threading.Lock()
        self._states = {t.cls: _TargetState(t, fast_window, slow_window)
                        for t in targets}
        self._tenants: dict[tuple[str, str], _TenantStats] = {}
        self._tenant_names: set[str] = set()
        self._transitions: collections.deque = collections.deque(
            maxlen=max_transitions)
        self._transitions_total: dict[str, int] = {t.cls: 0
                                                   for t in targets}
        self._listeners: list = []
        # announcement serialization: transitions are ENQUEUED under
        # the same _mu hold that recorded them and DELIVERED under
        # this lock, FIFO — with concurrent observers (two stream
        # threads feeding one class), per-thread delivery could
        # otherwise reorder ok->burning after burning->ok and leave a
        # listener (the admission controller) engaged forever against
        # a board that reads ok. RLock: a listener that re-enters
        # observe() must not self-deadlock.
        self._announce_mu = threading.RLock()
        self._pending_announce: collections.deque = collections.deque()

    @property
    def targets(self) -> tuple[SloTarget, ...]:
        return tuple(st.target for st in self._states.values())

    def add_listener(self, fn) -> None:
        """Register ``fn(cls, old_state, new_state)`` — called on every
        state transition, outside the board lock, on the observing
        thread (the engine batcher in practice)."""
        with self._mu:
            self._listeners.append(fn)

    # -- recording -----------------------------------------------------------
    def observe(self, cls: str, latency_s: float, ok: bool = True,
                tenant: str | None = None, rows: int = 0) -> None:
        """One completed (or failed / timed-out) request: feeds the
        class's SLO windows and the tenant's accounting. The one hook
        the engine calls per resolved request."""
        fired = False
        with self._mu:
            ts = self._tenant_locked(tenant, cls)
            ts.requests += 1
            if ok:
                # SERVED device rows only — the same semantics as the
                # engine's fair-drain deficit counters, so per-tenant
                # throughput/billing never over-counts work that
                # failed or timed out before the device ran it
                ts.rows += rows
            else:
                ts.failed += 1
            ts.hist.observe(latency_s)
            st = self._states.get(cls)
            if st is not None:
                breach = (not ok) or latency_s > st.target.p99_s
                st.fast.append(breach)
                st.slow.append(breach)
                st.count += 1
                if st.count % self.eval_every == 0 \
                        and len(st.slow) >= self.fast_window:
                    ev = self._eval_locked(st)
                    if ev is not None:
                        # enqueue under THIS _mu hold: the log order
                        # and the announce order cannot diverge
                        self._pending_announce.append(ev)
                        fired = True
        if fired:
            self._drain_announcements()

    def _drain_announcements(self) -> None:
        """Deliver queued transitions in transition-log order (spans +
        listeners), outside the board lock. Whichever thread holds the
        announce lock drains EVERYTHING pending, so a descheduled
        observer can never deliver its older transition late."""
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending_announce:
                        return
                    item = self._pending_announce.popleft()
                self._announce(*item)

    def note_shed(self, cls: str, tenant: str | None = None) -> None:
        """A request rejected at admission (serve/adaptive.py): counted
        against the tenant, never against the SLO windows — shed load
        is the mechanism PROTECTING the objective, not a breach of it."""
        with self._mu:
            self._tenant_locked(tenant, cls).shed += 1

    def _tenant_locked(self, tenant: str | None, cls: str) -> _TenantStats:
        name = tenant or UNTAGGED
        if name not in self._tenant_names:
            if len(self._tenant_names) >= self.max_tenants:
                name = OVERFLOW
            self._tenant_names.add(name)
        key = (name, cls)
        ts = self._tenants.get(key)
        if ts is None:
            ts = self._tenants[key] = _TenantStats()
        return ts

    # -- evaluation ----------------------------------------------------------
    def _eval_locked(self, st: _TargetState):
        budget = st.target.budget
        st.fast_burn = _burn(st.fast, budget)
        st.slow_burn = _burn(st.slow, budget)
        if st.fast_burn >= self.page_burn \
                and st.slow_burn >= self.warn_burn:
            new = "burning"
        elif st.slow_burn >= self.warn_burn:
            new = "warn"
        else:
            new = "ok"
        if new == st.state:
            return None
        old, st.state = st.state, new
        self._transitions.append((st.target.cls, old, new, st.count))
        self._transitions_total[st.target.cls] += 1
        return (st.target.cls, old, new, st.fast_burn)

    def _announce(self, cls: str, old: str, new: str,
                  burn: float) -> None:
        # the transition is itself observable: a span on the armed
        # tracer (so a chaos drill's trace shows WHEN the SLO flipped
        # relative to the faults and the admission response) ...
        with _trace.span("slo.transition", sys="slo", cls=cls,
                         frm=old, to=new, burn=round(burn, 3)):
            pass
        # ... a black-box journal entry (ok->burning is an incident
        # trigger; burn is window-timing shaped, so it stays out of
        # the replay-canonical detail) ...
        _flight.note("slo", "transition", cls=cls, frm=old, to=new)
        # ... and a callback — the admission controller's seam
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(cls, old, new)

    # -- introspection -------------------------------------------------------
    def state(self, cls: str) -> str:
        with self._mu:
            st = self._states.get(cls)
            return "ok" if st is None else st.state

    def burning(self) -> bool:
        with self._mu:
            return any(st.state == "burning"
                       for st in self._states.values())

    def transition_log(self) -> tuple:
        """(cls, from, to, observation_count) per transition, in firing
        order — the replay-determinism witness (the fired_log analog of
        resilience/faults.py)."""
        with self._mu:
            return tuple(self._transitions)

    def snapshot(self) -> dict:
        """JSON-shaped dump for the ``cess_sloStatus`` RPC."""
        with self._mu:
            targets = {}
            for cls, st in self._states.items():
                t = st.target
                targets[cls] = {
                    "p99_s": t.p99_s,
                    "error_rate": t.error_rate,
                    "state": st.state,
                    "fast_burn": round(st.fast_burn, 4),
                    "slow_burn": round(st.slow_burn, 4),
                    "budget_remaining": round(
                        max(0.0, 1.0 - _burn(st.slow, 1.0) / t.budget), 4),
                    "observations": st.count,
                    "transitions": self._transitions_total[cls],
                }
            tenants: dict = {}
            for (name, cls), ts in self._tenants.items():
                tenants.setdefault(name, {})[cls] = {
                    "requests": ts.requests,
                    "failed": ts.failed,
                    "shed": ts.shed,
                    "rows": ts.rows,
                }
            return {"targets": targets, "tenants": tenants,
                    "transitions": list(self._transitions)}

    def series(self) -> list[tuple[str, str, dict, float]]:
        """Labeled exposition series: ``(family, kind, labels, value)``
        tuples, deterministically ordered. ``cess_slo_state`` uses the
        Prometheus enum pattern (one series per state, the active one
        1.0) so dashboards can plot transitions without decoding a
        numeric code."""
        snap = self.snapshot()
        out: list[tuple[str, str, dict, float]] = []
        for cls in sorted(snap["targets"]):
            t = snap["targets"][cls]
            out.append(("cess_slo_budget_remaining", "gauge",
                        {"class": cls}, float(t["budget_remaining"])))
            out.append(("cess_slo_burn_rate", "gauge",
                        {"class": cls}, float(t["fast_burn"])))
            out.append(("cess_slo_slow_burn_rate", "gauge",
                        {"class": cls}, float(t["slow_burn"])))
            for state in STATES:
                out.append(("cess_slo_state", "gauge",
                            {"class": cls, "state": state},
                            1.0 if t["state"] == state else 0.0))
            out.append(("cess_slo_transitions_total", "counter",
                        {"class": cls}, float(t["transitions"])))
        for name in sorted(snap["tenants"]):
            for cls in sorted(snap["tenants"][name]):
                ts = snap["tenants"][name][cls]
                labels = {"tenant": name, "class": cls}
                out.append(("cess_tenant_requests_total", "counter",
                            labels, float(ts["requests"])))
                out.append(("cess_tenant_failed_total", "counter",
                            labels, float(ts["failed"])))
                out.append(("cess_tenant_shed_total", "counter",
                            labels, float(ts["shed"])))
                out.append(("cess_tenant_rows_total", "counter",
                            labels, float(ts["rows"])))
        return out

    def tenant_histograms(self) -> list[tuple[str, dict, prom.Histogram]]:
        """Per-tenant latency histogram families for the exposition:
        ``(family, labels, Histogram)`` — rendering snapshots each one
        consistently (prom.Histogram's own lock), so the board lock is
        only held to list them."""
        with self._mu:
            items = sorted(self._tenants.items())
        return [("cess_tenant_latency_seconds",
                 {"tenant": name, "class": cls}, ts.hist)
                for (name, cls), ts in items]
