"""Incident postmortems: triggers -> self-contained evidence bundles.

obs/flight.py retains the evidence (pinned traces + the per-subsystem
journal); this module decides when an *incident* happened and
snapshots everything a postmortem needs into one bundle, at the
moment of the trigger — not whenever a scrape happens to run.

An :class:`IncidentReporter` registers as a journal listener on a
:class:`~cess_tpu.obs.flight.FlightRecorder` and triggers on:

==================  ========================================================
trigger class       journal entry (subsystem, kind)
==================  ========================================================
``slo-burning``     ``("slo", "transition")`` with ``to == "burning"``
``breaker-trip``    ``("breaker", "trip")`` (incl. ``force_open``)
``breaker-hold``    ``("breaker", "hold")`` (the SLO-vacate latch)
``shed-storm``      ``shed_storm`` consecutive ``("engine", "shed")``
``invariant``       ``("sim", "invariant")`` (a chaos-world check failed)
``thread-escape``   ``("engine"|"stream", "escape")`` — an exception
                    escaping the batcher / stream driver
``fleet-outlier``   ``("fleet", "outlier")`` — the fleet plane's MAD
                    straggler detector flagged a node (obs/fleet.py)
``perf-regression`` ``("perf", "regression")`` with ``to ==
                    "regressed"`` — the profile plane's bench-anchored
                    watchdog (obs/profile.py); the bundle embeds the
                    pad and compile ledgers
``finality-stall``  ``("chain", "anomaly")`` with ``to == "bad"`` —
``deep-reorg``      the chain plane's anomaly detector
``equivocation``    (obs/chainwatch.py); the journal detail's ``cls``
``audit-failure-``  names the trigger class and the bundle embeds the
``spike``           chain-health snapshot
``remediation-``    ``("remediation", "flap")`` — a remediation policy
``flap``            fired, released, and re-fired inside its own
                    cooldown window (serve/remediate.py): the control
                    loop is oscillating, so it files its own
                    postmortem instead of churning silently
``custody-at-``     ``("custody", "at_risk"|"lost")`` with ``to ==
``risk`` /          "bad"`` — the durability plane's erasure-margin
``custody-lost``    detector (obs/custody.py); the bundle embeds the
                    segment's full per-fragment custody timeline
==================  ========================================================

Each bundle is self-contained: the pinned traces, the journal tail,
metric deltas since the previous bundle, breaker / SLO / adaptive /
admission snapshots, the fault plan's ``fired_log``, and — in sim
runs — the scenario seed + witness needed to replay the episode
(supplied by a ``context`` callable). Bundles are **rate-limited per
trigger class** (``max_per_class``, count-based so replays agree) and
**deduplicated** (a trigger repeating its class's previous key is
dropped).

Determinism: every bundle carries a ``canon`` section — the
replay-stable view (trigger, key, journal entries from deterministic
subsystems, the recorder's retention witness, the fired-fault log).
:meth:`IncidentReporter.witness` serializes the canon sequence to
bytes; two same-seed chaos runs must produce identical witnesses
(tests/test_flight.py) — the ``fired_log`` contract of
resilience/faults.py extended to whole postmortems. Host-timing data
(span durations, latency metrics, the ``adaptive`` journal) rides in
the bundle for humans but never in ``canon``.

Surfaces: the ``cess_incidentDump`` RPC (node/rpc.py), ``node.cli
--flight[=DIR]`` (bundles written to DIR as JSON on exit), and
``sim.run_scenario`` reports. tools/incident_view.py renders a bundle
as a human-readable timeline.
"""
from __future__ import annotations

import collections
import json
import threading

from .trace import _json_safe

# journal subsystems whose entries are replay-stable (the ``adaptive``
# journal reacts to host-timed p99 estimates, so it is evidence, not
# witness)
_CANON_SYS = frozenset(("slo", "breaker", "engine", "stream", "sim",
                        "custody",
                        "finality", "flight", "fleet", "perf", "chain",
                        "repair", "remediation"))

# the chain anomaly classes obs/chainwatch.py announces; the journal
# detail's ``cls`` IS the trigger class (one note kind, four triggers)
_CHAIN_TRIGGERS = frozenset(("finality-stall", "deep-reorg",
                             "equivocation", "audit-failure-spike"))


def _sanitize(value):
    """JSON-safe deep copy (dicts included — trace._json_safe handles
    the scalar/bytes/sequence cases)."""
    if isinstance(value, dict):
        return {str(k): _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return _json_safe(value)


def _flatten(prefix: str, value, out: dict) -> None:
    if isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(value, bool):
        pass
    elif isinstance(value, (int, float)):
        out[prefix] = float(value)


class IncidentReporter:
    """Turn notable journal entries into bounded, deduplicated,
    rate-limited incident bundles.

    recorder:      the FlightRecorder to listen on (evidence source).
    engine:        optional SubmissionEngine — supplies breaker / SLO /
                   adaptive / admission snapshots and the metric
                   counters bundles diff.
    board:         optional SloBoard when there is no engine (sim).
    plan:          optional FaultPlan whose ``fired_log`` each bundle
                   embeds (falls back to the process-armed plan).
    stitcher:      optional obs/fleet.py TraceStitcher — bundles gain
                   a ``stitched`` section (the cross-node trace view
                   at trigger time) and canon gains its replay-stable
                   witness, so a multi-host incident's postmortem
                   holds ONE connected trace instead of N fragments.
    profile:       optional obs/profile.py ProfilePlane — bundles gain
                   a ``profile`` snapshot section (both ledgers);
                   falls back to ``engine.profile`` when the engine
                   carries one.
    chainwatch:    optional obs/chainwatch.py ChainWatch — bundles
                   gain a ``chain`` snapshot section (consensus views,
                   equivocation evidence, the market ledger), the
                   chain-anomaly postmortem's health truth source.
    remediation:   optional serve/remediate.py RemediationPlane —
                   bundles gain a ``remediation`` snapshot section
                   (policy table, engagements, the action journal
                   tail): what the autopilot was doing at trigger
                   time.
    custody:       optional obs/custody.py CustodyPlane — bundles gain
                   a ``custody`` snapshot section (margins, histogram,
                   detector state), and a custody-triggered bundle
                   embeds the at-risk segment's full per-fragment
                   timeline.
    context:       optional callable returning a dict merged into each
                   bundle — sim runs supply the scenario seed +
                   witness needed to replay the episode.
    max_per_class: bundles per trigger class (count-based rate limit).
    shed_storm:    consecutive engine sheds that constitute a storm.
    repair_degraded: symbol-repair fallbacks (node/offchain.py journal
                   notes ("repair", "fallback")) that constitute a
                   degraded repair plane — the regenerating path has
                   stopped engaging and every repair is paying the
                   whole-fragment bandwidth bill.
    """

    def __init__(self, recorder, *, engine=None, board=None, plan=None,
                 stitcher=None, profile=None, chainwatch=None,
                 remediation=None, custody=None, context=None,
                 max_per_class: int = 4,
                 max_bundles: int = 32, shed_storm: int = 8,
                 repair_degraded: int = 8,
                 journal_tail: int = 64):
        if max_per_class < 1 or max_bundles < 1 or shed_storm < 1 \
                or repair_degraded < 1:
            raise ValueError("incident reporter bounds must be >= 1")
        self.recorder = recorder
        self.engine = engine
        self.board = board if board is not None \
            else getattr(engine, "slo", None)
        self.plan = plan
        self.stitcher = stitcher
        self.profile = profile if profile is not None \
            else getattr(engine, "profile", None)
        self.chainwatch = chainwatch
        self.remediation = remediation
        self.custody = custody
        self.context = context
        self.max_per_class = max_per_class
        self.shed_storm = shed_storm
        self.repair_degraded = repair_degraded
        self.journal_tail = journal_tail
        self._mu = threading.Lock()
        self._bundles: collections.deque = collections.deque(
            maxlen=max_bundles)
        self._per_class: dict = {}
        self._last_key: dict = {}
        self._shed_run = 0
        self._repair_run = 0
        self._seq = 0
        self._last_metrics: dict = {}
        self.rate_limited = 0
        self.deduplicated = 0
        recorder.add_listener(self._on_note)

    # -- the journal listener ------------------------------------------------
    def _on_note(self, seq, subsystem, kind, detail) -> None:
        if subsystem == "engine" and kind == "shed":
            with self._mu:
                self._shed_run += 1
                storm = self._shed_run >= self.shed_storm
                if storm:
                    self._shed_run = 0
            if storm:
                self.trigger("shed-storm",
                             key=f"{detail.get('cls')}:"
                                 f"{detail.get('reason')}",
                             detail=dict(detail,
                                         storm=self.shed_storm))
            return
        if subsystem == "repair" and kind == "fallback":
            # symbol-chain repairs falling back to whole-fragment
            # fetch: each one is routine, a RUN of them means the
            # regenerating plane is degraded (same accumulation shape
            # as shed-storm)
            with self._mu:
                self._repair_run += 1
                degraded = self._repair_run >= self.repair_degraded
                if degraded:
                    self._repair_run = 0
            if degraded:
                self.trigger("repair-degraded",
                             key=str(detail.get("miner")),
                             detail=dict(detail,
                                         run=self.repair_degraded))
            return
        if subsystem == "slo" and kind == "transition":
            if detail.get("to") != "burning":
                return
            self.trigger("slo-burning", key=str(detail.get("cls")),
                         detail=detail)
        elif subsystem == "breaker" and kind in ("trip", "hold"):
            self.trigger(f"breaker-{kind}",
                         key=f"{detail.get('name')}:"
                             f"{detail.get('reason', '')}",
                         detail=detail)
        elif subsystem == "sim" and kind == "invariant":
            self.trigger("invariant", key=str(detail.get("context")),
                         detail=detail)
        elif kind == "escape" and subsystem in ("engine", "stream"):
            self.trigger("thread-escape",
                         key=f"{subsystem}:{detail.get('error')}",
                         detail=dict(detail, thread=subsystem))
        elif subsystem == "fleet" and kind == "outlier":
            self.trigger("fleet-outlier",
                         key=f"{detail.get('instance')}:"
                             f"{detail.get('metric')}",
                         detail=detail)
        elif subsystem == "perf" and kind == "regression":
            # edge-triggered both ways by the watchdog; only the
            # ok->regressed edge is an incident (recovery is good news)
            if detail.get("to") != "regressed":
                return
            self.trigger("perf-regression",
                         key=str(detail.get("metric")), detail=detail)
        elif subsystem == "remediation" and kind == "flap":
            self.trigger("remediation-flap",
                         key=f"{detail.get('policy')}:"
                             f"{detail.get('key')}",
                         detail=detail)
        elif subsystem == "custody" and kind in ("at_risk", "lost"):
            # the durability detector announces edge-triggered both
            # ways; only the ok->bad edge is an incident
            if detail.get("to") != "bad":
                return
            self.trigger("custody-at-risk" if kind == "at_risk"
                         else "custody-lost",
                         key=str(detail.get("key")), detail=detail)
        elif subsystem == "chain" and kind == "anomaly":
            # edge-triggered both ways by the detector; only the
            # ok->bad edge is an incident, and the detail's cls must
            # name a known trigger class (a skewed peer's journal
            # entry must not mint arbitrary classes)
            cls = detail.get("cls")
            if detail.get("to") != "bad" or cls not in _CHAIN_TRIGGERS:
                return
            self.trigger(cls, key=str(detail.get("key")),
                         detail=detail)

    # -- triggering ----------------------------------------------------------
    def trigger(self, cls: str, key: str, detail: dict) -> dict | None:
        """Snapshot a bundle for trigger class ``cls`` unless the
        class is rate-limited or ``key`` repeats the class's previous
        trigger (dedup). Returns the bundle, or None when dropped."""
        with self._mu:
            if self._last_key.get(cls) == key:
                self.deduplicated += 1
                return None
            if self._per_class.get(cls, 0) >= self.max_per_class:
                self.rate_limited += 1
                return None
            self._per_class[cls] = self._per_class.get(cls, 0) + 1
            self._last_key[cls] = key
            self._seq += 1
            seq = self._seq
        # snapshot OUTSIDE self._mu: bundle assembly reads the
        # recorder / board / breaker locks and must never nest them
        # under the reporter's
        bundle = self._build(seq, cls, key, detail)
        with self._mu:
            self._bundles.append(bundle)
        return bundle

    def _build(self, seq: int, cls: str, key: str, detail: dict) -> dict:
        rec = self.recorder
        journal = rec.journal_tail(limit=self.journal_tail)
        pinned = rec.pinned()
        plan = self.plan
        if plan is None:
            from ..resilience import faults as _faults
            plan = _faults.armed_plan()
        fired = [] if plan is None else [list(f) for f in plan.fired_log()]
        snapshots: dict = {"flight": rec.snapshot()}
        metrics: dict = {}
        engine = self.engine
        if engine is not None:
            stats = engine.stats_snapshot()
            _flatten("engine", stats, metrics)
            snapshots["engine"] = stats
            snapshots["breakers"] = {
                name: mon.snapshot()
                for name, mon in sorted(engine.monitors.items())}
        elif self.board is not None:
            _flatten("slo", self.board.snapshot(), metrics)
        if self.board is not None:
            snapshots["slo"] = self.board.snapshot()
        adaptive = getattr(engine, "adaptive", None)
        if adaptive is not None:
            snapshots["adaptive"] = adaptive.snapshot()
        admission = getattr(engine, "admission", None)
        if admission is not None:
            snapshots["admission"] = admission.snapshot()
        profile = self.profile
        if profile is not None:
            # both ledgers (pads + compiles) ride every bundle — the
            # perf-regression postmortem's "where did the time go".
            # Evidence-side only: compile wall times are host timings
            # and must never reach canon
            snapshots["profile"] = profile.ledgers()
        chainwatch = self.chainwatch
        if chainwatch is not None:
            # the chain-health truth source rides every bundle — the
            # chain-anomaly postmortem's consensus views, equivocation
            # evidence and market ledger at trigger time
            snapshots["chain"] = chainwatch.snapshot()
        remediation = self.remediation
        if remediation is not None:
            # what the autopilot was doing at trigger time: the policy
            # table, live engagements, and the action journal tail.
            # The journal is count-sequenced and replay-stable, but it
            # rides evidence-side here — the plane has its own witness
            snap = remediation.snapshot()
            snap["journal"] = snap["journal"][-self.journal_tail:]
            snapshots["remediation"] = snap
        custody = self.custody
        if custody is not None:
            # the durability truth source rides every bundle (margins,
            # histogram, detector state; timelines stay out — they are
            # per-segment evidence), and a custody trigger embeds the
            # at-risk segment's FULL per-fragment timeline: fragment
            # F's whole history from dispatch to the edge
            snap = custody.snapshot()
            snap.pop("timelines", None)
            snapshots["custody"] = snap
            if cls.startswith("custody-"):
                snapshots["custody_timeline"] = \
                    custody.segment_timeline(key)
        stitcher = self.stitcher
        stitched = [] if stitcher is None else stitcher.traces()
        with self._mu:
            delta = {k: round(v - self._last_metrics.get(k, 0.0), 6)
                     for k, v in metrics.items()
                     if v != self._last_metrics.get(k, 0.0)}
            self._last_metrics = metrics
        context = {}
        if self.context is not None:
            context = _sanitize(self.context())
        canon = {
            "trigger": cls,
            "key": key,
            "detail": {k: repr(_json_safe(v))
                       for k, v in sorted(detail.items())},
            "journal": [[e["sys"], e["kind"],
                         sorted((k, repr(v))
                                for k, v in e["detail"].items())]
                        for e in journal if e["sys"] in _CANON_SYS],
            "pins": _sanitize(rec.witness()),
            "faults": _sanitize(fired),
            "context": context,
        }
        if stitcher is not None:
            # structure only (uids, parent edges, truncation marks):
            # the stitched WITNESS is replay-stable; the full traces
            # carry host timings and stay evidence-side below
            canon["stitched"] = _sanitize(stitcher.witness())
        return {
            "seq": seq,
            "trigger": cls,
            "key": key,
            "detail": _sanitize(detail),
            "journal": _sanitize(journal),
            "pinned": _sanitize(pinned),
            "stitched": _sanitize(stitched),
            "metrics_delta": delta,
            "snapshots": _sanitize(snapshots),
            "faults": _sanitize(fired),
            "context": context,
            "canon": canon,
        }

    # -- introspection -------------------------------------------------------
    def bundles(self) -> list[dict]:
        with self._mu:
            return list(self._bundles)

    def witness(self) -> bytes:
        """The replay witness: every retained bundle's ``canon``
        section, serialized deterministically. Two same-seed runs of
        the same episode must return identical bytes."""
        with self._mu:
            canons = [b["canon"] for b in self._bundles]
        return json.dumps(canons, sort_keys=True,
                          separators=(",", ":")).encode()

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "bundles": len(self._bundles),
                "triggers": dict(sorted(self._per_class.items())),
                "rate_limited": self.rate_limited,
                "deduplicated": self.deduplicated,
            }

    def dump(self, limit: int | None = None) -> dict:
        """The ``cess_incidentDump`` RPC payload: reporter counters,
        the recorder snapshot, and the newest ``limit`` bundles."""
        bundles = self.bundles()
        if limit is not None:
            bundles = bundles[-limit:]
        return {
            "reporter": self.snapshot(),
            "recorder": self.recorder.snapshot(),
            "bundles": bundles,
        }
