"""cess_tpu.obs — request-scoped tracing + histogram observability +
SLO monitors + the flight-recorder retention layer.

Five modules, one contract (zero-cost when off, deterministic when on):

- trace.py    Tracer/Span core: counter-based span ids, contextvars
              current-span propagation, a bounded ring of finished
              spans, Chrome trace-event export (Perfetto-loadable), and
              the (trace_id, span_id) envelope contract that stitches a
              challenge -> prove -> verify round into ONE distributed
              trace across nodes. With no tracer armed every hook
              returns the NOOP_SPAN singleton (tier-1 pins the
              identity).
- prom.py     real Prometheus histograms (cumulative _bucket{le=...} /
              _sum / _count) for the engine and stream latencies,
              rendered beside the existing gauges by node/metrics.py —
              plus exposition label escaping for the labeled families.
- slo.py      the consumption layer: declarative SloTarget objectives
              evaluated with observation-count multi-window burn-rate
              detection, per-tenant x per-class accounting, and the
              transition listeners serve/adaptive.py's admission
              controller acts on. Gauges ride /metrics as cess_slo_* /
              cess_tenant_*, snapshots serve the cess_sloStatus RPC.
- flight.py   the retention layer: tail-sampled trace pinning (anomaly
              + seeded-baseline, exempt from ring eviction, bounded
              with anomaly-first retention) and the count-sequenced
              black-box journal the subsystems note into.
- incident.py IncidentReporter: turns notable journal entries (SLO
              ok->burning, breaker trip/hold, shed storms, sim
              invariant violations, thread escapes) into rate-limited,
              deduplicated, self-contained postmortem bundles with a
              deterministic replay witness.

Wire-up: ``node.cli --trace[=PATH] --slo[=TARGETS] --flight[=DIR]``,
``serve.make_engine(tracer=..., slo=...)``, ``bench.py --trace``, and
the ``cess_traceDump`` / ``cess_sloStatus`` / ``cess_incidentDump``
RPCs.
"""
from .prom import (LATENCY_BUCKETS_S, Histogram, escape_label,
                   format_labels, format_le, render_histogram)
from .slo import (DEFAULT_TARGETS, SloBoard, SloTarget, parse_targets)
from .trace import (NOOP_SPAN, Span, Tracer, arm, armed, armed_tracer,
                    context, current_span, disarm, event, span)
# flight before incident: incident.py imports from the flight/trace
# layer it listens on
from .flight import FlightRecorder
from .incident import IncidentReporter

__all__ = [
    "DEFAULT_TARGETS",
    "FlightRecorder",
    "Histogram",
    "IncidentReporter",
    "LATENCY_BUCKETS_S",
    "NOOP_SPAN",
    "SloBoard",
    "SloTarget",
    "Span",
    "Tracer",
    "arm",
    "armed",
    "armed_tracer",
    "context",
    "current_span",
    "disarm",
    "escape_label",
    "event",
    "format_labels",
    "format_le",
    "parse_targets",
    "render_histogram",
    "span",
]
