"""cess_tpu.obs — request-scoped tracing + histogram observability.

Two modules, one contract (zero-cost when off, deterministic when on):

- trace.py  Tracer/Span core: counter-based span ids, contextvars
            current-span propagation, a bounded ring of finished
            spans, Chrome trace-event export (Perfetto-loadable), and
            the (trace_id, span_id) envelope contract that stitches a
            challenge -> prove -> verify round into ONE distributed
            trace across nodes. With no tracer armed every hook
            returns the NOOP_SPAN singleton (tier-1 pins the
            identity).
- prom.py   real Prometheus histograms (cumulative _bucket{le=...} /
            _sum / _count) for the engine and stream latencies,
            rendered beside the existing gauges by node/metrics.py.

Wire-up: ``node.cli --trace[=PATH]``, ``serve.make_engine(tracer=...)``,
``bench.py --trace``, and the ``cess_traceDump`` RPC.
"""
from .prom import (LATENCY_BUCKETS_S, Histogram, format_le,
                   render_histogram)
from .trace import (NOOP_SPAN, Span, Tracer, arm, armed, armed_tracer,
                    context, current_span, disarm, event, span)

__all__ = [
    "Histogram",
    "LATENCY_BUCKETS_S",
    "NOOP_SPAN",
    "Span",
    "Tracer",
    "arm",
    "armed",
    "armed_tracer",
    "context",
    "current_span",
    "disarm",
    "event",
    "format_le",
    "render_histogram",
    "span",
]
