"""Fleet observability plane: metric federation, cross-node trace
stitching, and a global SLO view.

Everything below this module observes ONE process: the tracer
(obs/trace.py), the SLO board (obs/slo.py), the flight recorder
(obs/flight.py) and the device-pool gauges all stop at the node
boundary. This module is the layer above — a single plane that
aggregates N nodes' observability surfaces into one federated view,
and the seam the multi-host serving plane plugs its global admission
decisions into:

- :class:`MetricFederator` — ingests text-format Prometheus
  expositions (what ``node/metrics.py`` renders) from N instances,
  adds an ``instance`` label, clamps counter resets from restarted
  nodes (:func:`~cess_tpu.obs.prom.counter_delta`) and merges
  histogram families across instances by rebuilding each node's
  cumulative buckets (:meth:`~cess_tpu.obs.prom.Histogram.
  from_cumulative`) and reusing :meth:`~cess_tpu.obs.prom.Histogram.
  merge`. Scrape rounds are COUNT-sequenced — no wallclock anywhere —
  so two same-seed sim runs federate bit-identically.

- :class:`FleetBoard` — aggregates per-node ``SloBoard.snapshot()``
  dicts into global per-class burn state with two views: ``worst``
  (any node burning => fleet burning; the paging view) and ``quorum``
  (a strict majority must agree; the admission view — one sick node
  must not throttle a healthy fleet). Transitions append to a
  deterministic log and announce exactly like the per-node board:
  a ``fleet.transition`` span plus a ``("fleet", "transition")``
  flight-journal note, delivered FIFO outside the board lock.

- :class:`TraceStitcher` — merges trace dumps from multiple nodes
  into connected cross-node traces. The PR-5 net envelope already
  propagates ``(trace_id, span_id)`` across hops and the receiver's
  ``net.recv:*`` span adopts the sender's trace id — but span ids are
  only unique PER TRACER, so the stitcher keys every span by
  ``instance/span_id`` and resolves a ``remote_parent`` reference
  ``(trace_id, parent_id)`` against OTHER instances' spans of the
  same trace. Duplicate ``(trace_id, span_id)`` pairs within one
  instance (a trace dump plus a flight pin of the same episode)
  dedup first-wins; a parent no instance retains is marked
  ``remote_truncated`` — never silently dropped.

- :class:`StragglerDetector` — deterministic straggler detection:
  median-absolute-deviation outliers over count-sequenced per-node
  latency/occupancy windows. A node whose window median deviates
  from the fleet median by more than ``k``·MAD fires a
  ``("fleet", "outlier")`` journal note — the ``fleet-outlier``
  incident trigger (obs/incident.py) — edge-triggered so a persistent
  straggler yields one incident, not one per scan.

:class:`FleetPlane` composes all four behind one scrape-round API and
is what gets armed: ``node.fleet`` on a live node (``node.cli
--fleet``, fed by ``("fleet", ...)`` gossip frames from peers and
served by the ``cess_fleetStatus`` RPC), ``world.fleet`` in the sim
(per-round scrape with a ``fleet-consistency`` invariant checker).

Zero-cost-when-off contract: this module installs NO hooks. The hot
paths that feed it (the net author loop, the sim round loop) gate on
``getattr(x, "fleet", None)`` — one attribute load and a None check
when disarmed, same as the flight-recorder contract.

Determinism: fleet.py is in the sim-determinism lint family
(cess_tpu/analysis) — no wallclock, no entropy. Rounds, scans and
transition logs are sequenced by internal counters; :meth:`FleetPlane.
witness` serializes the federated snapshot, the FleetBoard transition
log and the stitched trace set to canonical bytes, and two same-seed
100-node sim runs must produce identical witnesses
(tests/test_fleet.py).
"""
from __future__ import annotations

import collections
import json
import math
import threading

from . import flight as _flight
from . import prom
from . import trace as _trace

STATES = ("ok", "warn", "burning")
_SEVERITY = {"ok": 0, "warn": 1, "burning": 2}


# -- exposition parsing ------------------------------------------------------

def _parse_labels(body: str) -> tuple:
    """``k="v",...`` (the inside of a label brace pair) as a tuple of
    ``(key, value)`` pairs, unescaping the format-0.0.4 sequences
    prom.escape_label produces. Raises ValueError on malformed input
    (truncated value, missing ``=``)."""
    out = []
    i = 0
    n = len(body)
    while i < n:
        eq = body.index("=", i)
        key = body[i:eq].strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value in {body!r}")
        j = eq + 2
        buf = []
        while body[j] != '"':           # IndexError => ValueError below
            ch = body[j]
            if ch == "\\":
                nxt = body[j + 1]
                buf.append({"n": "\n"}.get(nxt, nxt))
                j += 2
            else:
                buf.append(ch)
                j += 1
        out.append((key, "".join(buf)))
        i = j + 1
        if i < n and body[i] == ",":
            i += 1
    return tuple(out)


def parse_exposition(text: str) -> dict:
    """Parse a text-format 0.0.4 exposition (``render_metrics``
    output) into ``{"types": {family: kind}, "samples": [(name,
    labels, value), ...]}`` with labels as ``(key, value)`` tuples.
    Unparseable sample lines are skipped (a federator must survive a
    half-written scrape), malformed label bodies included."""
    types: dict[str, str] = {}
    samples: list[tuple] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        head, _, value_s = line.rpartition(" ")
        if not head:
            continue
        try:
            value = float(value_s)
        except ValueError:
            continue
        if "{" in head:
            name, _, rest = head.partition("{")
            rest = rest.rstrip()
            if not rest.endswith("}"):
                continue
            try:
                labels = _parse_labels(rest[:-1])
            except (ValueError, IndexError):
                continue
        else:
            name, labels = head, ()
        samples.append((name, labels, value))
    return {"types": types, "samples": samples}


def _hist_part(name: str, bases: set) -> tuple:
    """(family, part) when ``name`` is a histogram component sample
    (``_bucket``/``_sum``/``_count`` of a declared histogram family),
    else (None, None)."""
    for suffix, part in (("_bucket", "bucket"), ("_sum", "sum"),
                         ("_count", "count")):
        if name.endswith(suffix) and name[:-len(suffix)] in bases:
            return name[:-len(suffix)], part
    return None, None


def _le_value(le: str) -> float:
    return math.inf if le == "+Inf" else float(le)


# -- metric federation -------------------------------------------------------

class MetricFederator:
    """Federate per-node expositions into one fleet-wide metric view.

    Per series (``(name, labels)`` with the ``instance`` dimension
    added at ingest):

    - counters accumulate CLAMPED deltas: a restarted node's counter
      going backwards contributes ``cur`` (what accumulated after the
      restart), never a negative delta (prom.counter_delta) — so the
      federated total stays monotonic across node restarts;
    - gauges keep the latest scraped value per instance;
    - histograms keep the latest cumulative bucket vector per instance
      and merge across instances on demand (Histogram.from_cumulative
      + merge), giving the FleetBoard a real fleet-wide quantile.

    ``scrape_round`` is the only mutator; rounds are count-sequenced
    (no wallclock) so sim replays federate bit-identically. Instances
    are sorted before ingestion — the same set of expositions yields
    the same federated state regardless of dict order."""

    def __init__(self):
        self._mu = threading.Lock()
        self._round = 0
        self._instances: set[str] = set()
        self._types: dict[str, str] = {}
        # (name, labels) -> {instance: [last_raw, clamped_cumulative]}
        self._counters: dict = {}
        # (name, labels) -> {instance: value}
        self._gauges: dict = {}
        # (name, labels) -> {instance: (cumulative_buckets, sum)}
        self._hists: dict = {}

    def scrape_round(self, expositions: dict) -> int:
        """Ingest one scrape round: ``{instance: exposition_text}``.
        Returns the (count-sequenced) round number just sealed."""
        parsed = [(str(inst), parse_exposition(expositions[inst]))
                  for inst in sorted(expositions)]
        with self._mu:
            self._round += 1
            rnd = self._round
            for inst, p in parsed:
                self._instances.add(inst)
                self._types.update(p["types"])
                self._ingest_locked(inst, p)
        return rnd

    def _ingest_locked(self, inst: str, parsed: dict) -> None:
        hist_bases = {n for n, k in self._types.items()
                      if k == "histogram"}
        partial: dict = {}      # (family, labels) -> {"buckets": ...}
        for name, labels, value in parsed["samples"]:
            base, part = _hist_part(name, hist_bases)
            if base is not None:
                key = (base, tuple(sorted(
                    (k, v) for k, v in labels if k != "le")))
                ent = partial.setdefault(key, {})
                if part == "bucket":
                    le = dict(labels).get("le")
                    if le is None:
                        continue
                    try:
                        bound = _le_value(le)
                    except ValueError:
                        continue
                    ent.setdefault("buckets", []).append((bound, value))
                else:
                    ent[part] = value
                continue
            labels = tuple(sorted(labels))
            kind = self._types.get(name) or (
                "counter" if name.endswith("_total") else "gauge")
            if kind == "counter":
                per = self._counters.setdefault((name, labels), {})
                st = per.get(inst)
                if st is None:
                    per[inst] = [value, value]
                else:
                    st[1] += prom.counter_delta(st[0], value)
                    st[0] = value
            else:
                self._gauges.setdefault((name, labels), {})[inst] = value
        for (family, labels), ent in partial.items():
            buckets = tuple(sorted(ent.get("buckets", ())))
            if not buckets:
                continue
            self._hists.setdefault((family, labels), {})[inst] = (
                buckets, float(ent.get("sum", 0.0)))

    # -- reading -------------------------------------------------------------
    @property
    def round(self) -> int:
        with self._mu:
            return self._round

    def merged_histogram(self, name: str, labels=()):
        """Fleet-wide :class:`~cess_tpu.obs.prom.Histogram` for one
        family across every instance (None when the family is unknown
        or no instance's buckets parse). Merge order is sorted by
        instance — deterministic, and merge is commutative anyway.
        Instances whose bucket grids disagree (version skew, a hostile
        peer) cannot merge — only the grid MOST instances agree on is
        merged (ties break to the smaller grid: deterministic), the
        rest are skipped, never fatal."""
        with self._mu:
            per = dict(self._hists.get((name, tuple(sorted(labels))), {}))
        grids: dict = {}        # bounds tuple -> [Histogram...]
        for inst in sorted(per):
            buckets, total_sum = per[inst]
            try:
                h = prom.Histogram.from_cumulative(buckets, total_sum)
            except ValueError:
                continue            # malformed node scrape: skip it
            grids.setdefault(tuple(h.bounds), []).append(h)
        if not grids:
            return None
        majority = max(sorted(grids), key=lambda b: len(grids[b]))
        merged = None
        for h in grids[majority]:
            merged = h if merged is None else merged.merge(h)
        return merged

    def snapshot(self) -> dict:
        """Deterministic federated view: every series keyed by
        ``name{labels-with-instance}``, plus the merged per-family
        histograms. JSON-safe; sorted at every level."""
        with self._mu:
            rnd = self._round
            instances = sorted(self._instances)
            counters = {k: {i: list(v) for i, v in per.items()}
                        for k, per in self._counters.items()}
            gauges = {k: dict(per) for k, per in self._gauges.items()}
            hist_keys = sorted(self._hists)
        out_counters = {}
        for (name, labels), per in sorted(counters.items()):
            for inst in sorted(per):
                key = name + prom.format_labels(
                    dict(labels, instance=inst))
                out_counters[key] = per[inst][1]
        out_gauges = {}
        for (name, labels), per in sorted(gauges.items()):
            for inst in sorted(per):
                key = name + prom.format_labels(
                    dict(labels, instance=inst))
                out_gauges[key] = per[inst]
        out_hists = {}
        for name, labels in hist_keys:
            merged = self.merged_histogram(name, labels)
            if merged is None:
                continue
            snap = merged.snapshot()
            key = name + prom.format_labels(dict(labels))
            out_hists[key] = {
                "buckets": [[prom.format_le(b), n]
                            for b, n in snap["buckets"]],
                "sum": round(snap["sum"], 9),
                "count": snap["count"],
            }
        return {"round": rnd, "instances": instances,
                "counters": out_counters, "gauges": out_gauges,
                "histograms": out_hists}

    def render(self) -> str:
        """The federated exposition: every instance's counter and gauge
        series re-emitted with the ``instance`` label, histogram
        families re-emitted MERGED across instances (one fleet-wide
        grid per family — per-instance vectors live in ``snapshot``),
        one TYPE line per family, sorted — what a fleet-level scrape
        endpoint would serve."""
        snap = self.snapshot()
        with self._mu:
            hist_keys = sorted(self._hists)
        lines = []
        declared: set[str] = set()
        for key in sorted(snap["counters"]):
            self._declare(key, "counter", declared, lines)
            lines.append(f"{key} {snap['counters'][key]}")
        for key in sorted(snap["gauges"]):
            self._declare(key, "gauge", declared, lines)
            lines.append(f"{key} {snap['gauges'][key]}")
        for name, labels in hist_keys:
            merged = self.merged_histogram(name, labels)
            if merged is None:
                continue
            lines.extend(prom.render_histogram(
                name, merged, labels=dict(labels),
                type_line=name not in declared))
            declared.add(name)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _declare(key: str, kind: str, declared: set, lines: list) -> None:
        family = key.partition("{")[0]
        if family not in declared:
            declared.add(family)
            lines.append(f"# TYPE {family} {kind}")

    def witness(self) -> bytes:
        """Canonical bytes of the federated snapshot — one third of
        the fleet replay witness."""
        return json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":")).encode()


# -- global SLO view ---------------------------------------------------------

def _quorum_state(states: list) -> str:
    """The most severe state a STRICT MAJORITY of reporting nodes is
    at-or-beyond. One burning node in a ten-node fleet leaves the
    quorum view ok (that node is the straggler detector's problem);
    six burning nodes flip it."""
    n = len(states)
    for name in ("burning", "warn"):
        k = sum(1 for s in states
                if _SEVERITY.get(s, 0) >= _SEVERITY[name])
        if 2 * k > n:
            return name
    return "ok"


class FleetBoard:
    """Global per-class burn state aggregated from per-node
    ``SloBoard.snapshot()`` dicts — the seam a multi-host admission
    controller plugs into.

    Two views per class, updated every scrape round:

    - ``worst``: the most severe state ANY reporting node is in — the
      paging view (someone's budget is burning somewhere);
    - ``quorum``: the most severe state a strict majority agrees on —
      the admission view (global throttling must not be hostage to
      one sick node).

    Transitions of either view append ``(cls, view, old, new, round)``
    to a bounded deterministic log and announce exactly like the
    per-node SloBoard: enqueued under the same ``_mu`` hold that
    recorded them, delivered FIFO under ``_announce_mu`` OUTSIDE the
    board lock — a ``fleet.transition`` span on the armed tracer, a
    ``("fleet", "transition")`` flight note, then listener callbacks.
    """

    def __init__(self, *, max_transitions: int = 256):
        if max_transitions < 1:
            raise ValueError("max_transitions must be >= 1")
        self._mu = threading.Lock()
        self._round = 0
        self._nodes: dict = {}          # instance -> {cls: state}
        self._views: dict = {}          # cls -> {"worst": s, "quorum": s}
        self._p99: dict = {}            # cls -> fleet p99 seconds
        self._transitions: collections.deque = collections.deque(
            maxlen=max_transitions)
        self._listeners: list = []
        # same serialization contract as SloBoard: FIFO delivery,
        # whichever thread holds the announce lock drains everything
        self._announce_mu = threading.RLock()
        self._pending_announce: collections.deque = collections.deque()

    def add_listener(self, fn) -> None:
        """Register ``fn(cls, view, old, new)`` — called on every
        global transition, outside the board lock."""
        with self._mu:
            self._listeners.append(fn)

    def scrape_round(self, snapshots: dict, p99_s: dict | None = None) -> int:
        """Ingest one round of per-node SLO snapshots:
        ``{instance: SloBoard.snapshot()}`` (an instance absent this
        round keeps its last reported states — a crashed node's last
        word stands until it reports again). ``p99_s`` optionally
        carries fleet-wide quantiles (from the federator's merged
        histograms) for the snapshot. Returns the round number."""
        fired = False
        with self._mu:
            self._round += 1
            rnd = self._round
            for inst in sorted(snapshots):
                snap = snapshots[inst]
                targets = snap.get("targets") \
                    if isinstance(snap, dict) else None
                if not isinstance(targets, dict):
                    targets = {}
                # per-class entries that are not dicts are skipped, not
                # fatal — a malformed snapshot must not wedge the board
                self._nodes[str(inst)] = {
                    str(cls): str(d.get("state", "ok"))
                    for cls, d in sorted(targets.items(),
                                         key=lambda kv: str(kv[0]))
                    if isinstance(d, dict)}
            if p99_s:
                for cls in sorted(p99_s):
                    self._p99[str(cls)] = round(float(p99_s[cls]), 9)
            classes = sorted({c for states in self._nodes.values()
                              for c in states})
            for cls in classes:
                reporting = [self._nodes[i][cls]
                             for i in sorted(self._nodes)
                             if cls in self._nodes[i]]
                worst = max(reporting,
                            key=lambda s: _SEVERITY.get(s, 0))
                quorum = _quorum_state(reporting)
                views = self._views.setdefault(
                    cls, {"worst": "ok", "quorum": "ok"})
                for view, new in (("worst", worst), ("quorum", quorum)):
                    old = views[view]
                    if new != old:
                        views[view] = new
                        self._transitions.append(
                            (cls, view, old, new, rnd))
                        self._pending_announce.append(
                            (cls, view, old, new, rnd))
                        fired = True
        if fired:
            self._drain_announcements()
        return rnd

    def _drain_announcements(self) -> None:
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending_announce:
                        return
                    item = self._pending_announce.popleft()
                self._announce(*item)

    def _announce(self, cls: str, view: str, old: str, new: str,
                  rnd: int) -> None:
        # observable exactly like a per-node SLO transition: a span on
        # the armed tracer (WHEN the fleet flipped, relative to faults
        # and stitched cross-node spans), a journal note (the round is
        # count-sequenced, so it is replay-canonical), a callback
        with _trace.span("fleet.transition", sys="fleet", cls=cls,
                         view=view, frm=old, to=new, round=rnd):
            pass
        _flight.note("fleet", "transition", cls=cls, view=view,
                     frm=old, to=new, round=rnd)
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(cls, view, old, new)

    # -- introspection -------------------------------------------------------
    def state(self, cls: str, view: str = "quorum") -> str:
        with self._mu:
            return self._views.get(cls, {}).get(view, "ok")

    def burning(self, view: str = "worst") -> bool:
        with self._mu:
            return any(v.get(view) == "burning"
                       for v in self._views.values())

    def transition_log(self) -> tuple:
        """(cls, view, from, to, round) per transition, in firing
        order — one third of the fleet replay witness."""
        with self._mu:
            return tuple(self._transitions)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "round": self._round,
                "classes": {
                    cls: {
                        "worst": v["worst"],
                        "quorum": v["quorum"],
                        "p99_s": self._p99.get(cls),
                        "nodes": {i: states[cls]
                                  for i, states in
                                  sorted(self._nodes.items())
                                  if cls in states},
                    }
                    for cls, v in sorted(self._views.items())},
                "transitions": [list(t) for t in self._transitions],
            }


# -- cross-node trace stitching ----------------------------------------------

class TraceStitcher:
    """Merge per-node trace dumps into connected cross-node traces.

    Input spans are ``Tracer.finished()`` dicts. Within one instance,
    duplicate ``(trace_id, span_id)`` pairs dedup first-wins (a trace
    dump and a flight pin of the same episode overlap). Across
    instances, span ids are NOT unique (each tracer counts from 1), so
    every stitched span gets a fleet-unique ``uid`` =
    ``instance/span_id`` and parent references resolve to
    ``parent_uid``:

    - a local parent resolves within the same instance;
    - a ``remote_parent`` reference resolves against OTHER instances'
      spans carrying the same ``(trace_id, span_id)`` — the sender's
      side of a PR-5 net envelope hop. Span ids are per-tracer
      counters, so MULTIPLE other instances can match within one
      trace; resolution picks the lexicographically-first instance
      (deterministic) and marks the span ``ambiguous_parent`` so a
      postmortem reader knows the sender attribution is a guess, not
      a fact (exact resolution needs the sender identity in the net
      envelope — a wire change deferred to the multi-host PR);
    - a parent no retained dump contains is marked
      ``remote_truncated`` (ring-buffer eviction, a crashed node) and
      the span becomes a visible truncation point, never a silent
      orphan.
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._spans: dict = {}    # (instance, trace_id, span_id) -> span
        self._dumps = 0

    def add_dump(self, instance: str, spans) -> int:
        """Ingest one node's span dicts; returns how many were new
        (the rest deduplicated)."""
        instance = str(instance)
        added = 0
        with self._mu:
            self._dumps += 1
            for s in spans:
                if not isinstance(s, dict) or "span_id" not in s:
                    continue
                key = (instance, s.get("trace_id"), s["span_id"])
                if key in self._spans:
                    continue
                self._spans[key] = dict(s)
                added += 1
        return added

    def add_pins(self, instance: str, pins) -> int:
        """Ingest ``FlightRecorder.pinned()`` output (each pin holds a
        ``spans`` list)."""
        added = 0
        for pin in pins:
            if isinstance(pin, dict):
                added += self.add_dump(instance, pin.get("spans", ()))
        return added

    # -- stitching -----------------------------------------------------------
    def traces(self) -> list:
        """The stitched view: one dict per trace id, spans annotated
        with ``instance``/``uid``/``parent_uid``/``remote_truncated``,
        deterministically ordered (trace id, then instance, then span
        id). Pure function of the ingested spans."""
        with self._mu:
            spans = {k: dict(v) for k, v in self._spans.items()}
        local: dict = {}          # (instance, span_id) -> key
        cross: dict = {}          # (trace_id, span_id) -> [instance...]
        for (inst, tid, sid) in spans:
            local[(inst, sid)] = (inst, tid, sid)
            cross.setdefault((tid, sid), []).append(inst)
        by_trace: dict = {}
        for key in sorted(spans, key=lambda k: (str(k[0]), k[2])):
            inst, tid, sid = key
            s = spans[key]
            s["instance"] = inst
            s["uid"] = f"{inst}/{sid}"
            s["remote_truncated"] = False
            s["ambiguous_parent"] = False
            parent = s.get("parent_id") or 0
            if not parent:
                s["parent_uid"] = None
            elif s.get("remote_parent"):
                others = sorted(i for i in cross.get((tid, parent), ())
                                if i != inst)
                if others:
                    s["parent_uid"] = f"{others[0]}/{parent}"
                    # >1 candidate sender: per-tracer span ids collide
                    # across instances — flag, don't pick silently
                    s["ambiguous_parent"] = len(others) > 1
                elif (inst, parent) in local:
                    # loopback hop: the remote parent is local after all
                    s["parent_uid"] = f"{inst}/{parent}"
                else:
                    s["parent_uid"] = None
                    s["remote_truncated"] = True
            else:
                pkey = local.get((inst, parent))
                if pkey is not None and pkey[1] == tid:
                    s["parent_uid"] = f"{inst}/{parent}"
                else:
                    s["parent_uid"] = None
                    s["remote_truncated"] = True
            by_trace.setdefault(tid, []).append(s)
        out = []
        for tid in sorted(by_trace, key=lambda t: (str(type(t)), str(t))):
            tr = by_trace[tid]
            out.append({
                "trace_id": tid,
                "instances": sorted({s["instance"] for s in tr}),
                "spans": tr,
                "roots": [s["uid"] for s in tr
                          if s["parent_uid"] is None
                          and not s["remote_truncated"]],
                "truncated": [s["uid"] for s in tr
                              if s["remote_truncated"]],
                "ambiguous": [s["uid"] for s in tr
                              if s["ambiguous_parent"]],
            })
        return out

    def witness(self) -> tuple:
        """The replay-stable reduction of the stitched trace set —
        structure only (uids, names, parent edges, truncation marks),
        no host timings. One third of the fleet replay witness."""
        out = []
        for t in self.traces():
            out.append((t["trace_id"], tuple(
                (s["uid"], s.get("name", ""), s.get("sys", ""),
                 s["parent_uid"] or "", bool(s.get("remote_parent")),
                 s["remote_truncated"], s["ambiguous_parent"])
                for s in t["spans"])))
        return tuple(out)

    def snapshot(self) -> dict:
        """JSON-safe summary for the ``cess_fleetStatus`` RPC."""
        traces = self.traces()
        with self._mu:
            dumps, total = self._dumps, len(self._spans)
        return {
            "dumps": dumps,
            "spans": total,
            "traces": [{
                "trace_id": t["trace_id"],
                "instances": t["instances"],
                "n_spans": len(t["spans"]),
                "roots": t["roots"],
                "truncated": t["truncated"],
                "ambiguous": t["ambiguous"],
            } for t in traces],
        }


# -- straggler detection -----------------------------------------------------

def _median(values: list) -> float:
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    if n % 2:
        return float(vs[mid])
    return (vs[mid - 1] + vs[mid]) / 2.0


class StragglerDetector:
    """Median-absolute-deviation outlier detection over
    count-sequenced per-node windows.

    ``observe(instance, metric, value)`` appends to that node's
    bounded window; ``scan()`` reduces each node to its window median,
    takes the fleet median and MAD across nodes, and flags any node
    whose median deviates by more than ``k``·MAD (MAD floored at
    ``min_mad`` so an otherwise-identical fleet still flags the one
    deviant). Firing is EDGE-triggered — a ``("fleet", "outlier")``
    flight note (the ``fleet-outlier`` incident trigger) plus a
    ``fleet.outlier`` span when a node BECOMES an outlier, nothing
    while it stays one, re-armed once it rejoins the pack.

    Staleness: a window with no fresh sample for ``stale_scans``
    consecutive scans belongs to a node that stopped reporting
    (crashed, partitioned) — it is evicted so dead nodes neither skew
    the fleet median nor stay flagged forever; and any flag a scan
    can no longer derive (the window evicted, the metric's reporting
    count below ``min_nodes``) is dropped with it. If the evidence
    returns, the edge trigger re-fires.

    Determinism: windows, scans and staleness are count-sequenced;
    scans iterate instances and metrics sorted. No wallclock
    anywhere."""

    def __init__(self, *, window: int = 16, k: float = 4.0,
                 min_nodes: int = 4, min_mad: float = 1e-9,
                 stale_scans: int = 8):
        if window < 1 or min_nodes < 2 or k <= 0 or min_mad <= 0 \
                or stale_scans < 1:
            raise ValueError("invalid straggler detector bounds")
        self.window = int(window)
        self.k = float(k)
        self.min_nodes = int(min_nodes)
        self.min_mad = float(min_mad)
        self.stale_scans = int(stale_scans)
        self._mu = threading.Lock()
        self._windows: dict = {}    # (instance, metric) -> deque
        self._flagged: dict = {}    # (instance, metric) -> bool
        self._dirty: set = set()    # keys observed since the last scan
        self._last_obs: dict = {}   # key -> scan seq last seen fresh
        self._scans = 0

    def observe(self, instance: str, metric: str, value: float) -> None:
        key = (str(instance), str(metric))
        with self._mu:
            dq = self._windows.get(key)
            if dq is None:
                dq = self._windows[key] = collections.deque(
                    maxlen=self.window)
            dq.append(float(value))
            self._dirty.add(key)

    def scan(self) -> list:
        """One count-sequenced outlier scan; returns the NEW outliers
        as ``(instance, metric, value, median, mad, scan)`` tuples
        (and fires their notes/spans, outside the lock)."""
        fired = []
        with self._mu:
            self._scans += 1
            seq = self._scans
            for key in self._dirty:
                self._last_obs[key] = seq
            self._dirty.clear()
            stale = [k for k in self._windows
                     if seq - self._last_obs.get(k, seq)
                     >= self.stale_scans]
            for key in stale:
                del self._windows[key]
                self._last_obs.pop(key, None)
            by_metric: dict = {}
            for (inst, metric), dq in sorted(self._windows.items()):
                if dq:
                    by_metric.setdefault(metric, []).append(
                        (inst, _median(list(dq))))
            evaluated: set = set()
            for metric in sorted(by_metric):
                rows = by_metric[metric]
                if len(rows) < self.min_nodes:
                    continue
                med = _median([v for _, v in rows])
                mad = max(_median([abs(v - med) for _, v in rows]),
                          self.min_mad)
                for inst, v in rows:
                    is_out = abs(v - med) > self.k * mad
                    key = (inst, metric)
                    evaluated.add(key)
                    if is_out and not self._flagged.get(key, False):
                        fired.append((inst, metric, round(v, 9),
                                      round(med, 9), round(mad, 9),
                                      seq))
                    self._flagged[key] = is_out
            # a flag this scan could NOT re-derive (the metric fell
            # below min_nodes, the instance went silent) is stale —
            # drop it so snapshot()['outliers'] reflects only current
            # state; if the evidence returns, the edge re-fires
            self._flagged = {k: v for k, v in self._flagged.items()
                             if k in evaluated}
        for inst, metric, v, med, mad, sq in fired:
            with _trace.span("fleet.outlier", sys="fleet",
                             instance=inst, metric=metric):
                pass
            _flight.note("fleet", "outlier", instance=inst,
                         metric=metric, value=v, median=med,
                         mad=mad, scan=sq)
        return fired

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "scans": self._scans,
                "windows": len(self._windows),
                "outliers": sorted(f"{i}/{m}"
                                   for (i, m), on in
                                   self._flagged.items() if on),
            }


# -- the composite plane -----------------------------------------------------

class FleetPlane:
    """MetricFederator + FleetBoard + TraceStitcher +
    StragglerDetector behind one scrape-round API — the object that
    gets armed as ``node.fleet`` (live) or ``world.fleet`` (sim).

    The ingest/seal split matches how contributions actually arrive:
    ``ingest(...)`` buffers one node's exposition + SLO snapshot (net
    recv threads for peers, the local tick for self, the sim round
    loop for everyone) and ``seal_round()`` closes one count-sequenced
    round — federates buffered expositions, feeds the FleetBoard
    (with fleet-wide p99s from the merged ``latency_families``
    histograms) and runs a straggler scan. Straggler samples go
    straight to ``stragglers.observe`` (they are count-sequenced
    windows of their own).

    Zero-cost-when-off: nothing here hooks anything. Hot paths hold
    ONE attribute (``node.fleet`` / ``world.fleet``) and skip on None.
    """

    def __init__(self, instance: str, *, latency_families: dict | None
                 = None, straggler_window: int = 16,
                 straggler_k: float = 4.0, min_nodes: int = 4):
        self.instance = str(instance)
        # {slo_class: histogram_family} — which federated latency
        # family backs each class's fleet-wide p99
        self.latency_families = dict(latency_families or {})
        self.federator = MetricFederator()
        self.board = FleetBoard()
        self.stitcher = TraceStitcher()
        self.stragglers = StragglerDetector(
            window=straggler_window, k=straggler_k, min_nodes=min_nodes)
        self._mu = threading.Lock()
        self._pending: dict = {}    # instance -> (exposition, slo)
        self._rounds = 0
        self._source = None         # callable -> (exposition, slo)

    def attach_source(self, fn) -> None:
        """Register the SELF scrape source: a callable returning
        ``(exposition_text, slo_snapshot_dict_or_None)``."""
        with self._mu:
            self._source = fn

    # -- ingestion -----------------------------------------------------------
    def ingest(self, instance: str, exposition: str | None = None,
               slo: dict | None = None) -> None:
        """Buffer one node's contribution for the next seal. Called
        from net recv threads (peers) and the local tick (self); a
        node reporting twice in one round keeps its latest."""
        with self._mu:
            self._pending[str(instance)] = (exposition, slo)

    def ingest_frame(self, frame) -> None:
        """The ``("fleet", frame)`` gossip payload: ``(instance,
        exposition_text, slo_snapshot_json)``. Malformed frames are
        dropped — a peer must not be able to wedge the plane."""
        try:
            inst, expo, slo_json = frame
        except (TypeError, ValueError):
            return
        if not isinstance(inst, str) or not isinstance(expo, str):
            return
        slo = None
        if slo_json:
            try:
                slo = json.loads(slo_json)
            except (TypeError, ValueError):
                return
            if not isinstance(slo, dict):
                return
            # nested shape too: "targets" must be a dict of dicts —
            # ('{"targets": 123}', '{"targets": {"c": "burning"}}')
            # must not reach the FleetBoard and raise out of a seal
            targets = slo.get("targets")
            if targets is not None and (
                    not isinstance(targets, dict)
                    or any(not isinstance(d, dict)
                           for d in targets.values())):
                return
        self.ingest(inst, exposition=expo or None, slo=slo)

    def self_frame(self):
        """The gossip frame advertising THIS node's scrape, or None
        when no source is attached."""
        with self._mu:
            src = self._source
        if src is None:
            return None
        expo, slo = src()
        return (self.instance, expo or "",
                "" if slo is None else json.dumps(slo, sort_keys=True))

    # -- sealing -------------------------------------------------------------
    def seal_round(self) -> int:
        """Close one scrape round over everything buffered since the
        last seal. Sub-planes are fed OUTSIDE the plane lock — their
        announce paths reach the tracer and flight recorder and must
        never nest under it."""
        with self._mu:
            pending, self._pending = self._pending, {}
            self._rounds += 1
            rnd = self._rounds
        expositions = {i: e for i, (e, _) in pending.items() if e}
        if expositions:
            self.federator.scrape_round(expositions)
        slos = {i: s for i, (_, s) in pending.items() if s is not None}
        if slos:
            p99 = {}
            for cls in sorted(self.latency_families):
                merged = self.federator.merged_histogram(
                    self.latency_families[cls])
                if merged is not None and merged.count:
                    p99[cls] = merged.quantile(0.99)
            self.board.scrape_round(slos, p99_s=p99 or None)
        self.stragglers.scan()
        return rnd

    def tick(self) -> int:
        """One live scrape round: scrape self (if a source is
        attached), then seal whatever peers gossiped in since the last
        tick. The net author loop calls this every few slots."""
        frame = self.self_frame()
        if frame is not None:
            self.ingest_frame(frame)
        return self.seal_round()

    # -- introspection -------------------------------------------------------
    @property
    def rounds(self) -> int:
        with self._mu:
            return self._rounds

    def snapshot(self) -> dict:
        """The ``cess_fleetStatus`` RPC payload."""
        with self._mu:
            rounds = self._rounds
        return {
            "instance": self.instance,
            "rounds": rounds,
            "federation": self.federator.snapshot(),
            "board": self.board.snapshot(),
            "stitch": self.stitcher.snapshot(),
            "stragglers": self.stragglers.snapshot(),
        }

    def witness(self) -> bytes:
        """THE fleet replay witness: federated snapshot + FleetBoard
        transition log + stitched trace set, canonical JSON bytes.
        Two same-seed sim runs must return identical bytes."""
        canon = {
            "federation": self.federator.snapshot(),
            "transitions": [list(t)
                            for t in self.board.transition_log()],
            "stitched": [[tid, [list(s) for s in spans]]
                         for tid, spans in self.stitcher.witness()],
        }
        return json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()
