"""Request-scoped tracing for the serving data plane.

The framework's four instrumented subsystems (engine, stream driver,
resilience, node) export flat aggregate gauges — good for "is it
healthy", useless for "where did THIS upload's 40 ms go". This module
is the per-request signal: a :class:`Tracer` collects :class:`Span`
records threaded through every data-plane seam (StoragePipeline
forward, engine queue-wait -> batch -> device dispatch -> resolve,
streaming h2d/dispatch/stall, resilience retries and fallbacks,
offchain audit rounds, net envelope hops), so one trace shows one
request's whole path — the attribution the RS/PoDR2 tuning loop needs
(batch-composition effects only become actionable per-request; see
PAPERS.md, Ragged Paged Attention).

Design contracts, in priority order:

- **Zero-cost when off** (the ``resilience.faults`` contract): with no
  tracer armed every hook is one module-global load and a ``None``
  check, and returns the process-wide :data:`NOOP_SPAN` singleton — no
  span object, no dict, no clock read is allocated on the disabled
  path. tier-1 pins the singleton identity (tests/test_obs.py) and
  bench.py records the armed-vs-off overhead on the streamed path
  (``trace_overhead_frac``).
- **Deterministic span ids**: ids come from a per-tracer counter, and
  a trace id is fixed at construction — no wall clock, no randomness
  in identities — so two replays of the same workload under the same
  seeded FaultPlan produce correlatable traces (timings differ, the
  span graph does not).
- **Context propagation**: the current span lives in a
  ``contextvars.ContextVar``. ``span(...)`` (the ``with``-style hook)
  makes its span current for the block; children started inside
  inherit it as parent. Contexts do NOT cross threads — code that
  hands work to another thread (the engine batcher) carries the span
  object explicitly, and code that crosses processes carries
  ``context()`` = ``(trace_id, span_id)`` in the message envelope
  (node/net.py wraps gossip frames) and rebuilds with ``remote=``.
- **Bounded memory**: finished spans land in a thread-safe ring buffer
  (``capacity`` newest kept); an unfinished span is simply absent from
  exports, never a leak.

Exports: :meth:`Tracer.export_chrome` emits Chrome trace-event JSON
(one ``"X"`` complete event per span — load it in Perfetto or
chrome://tracing), the ``cess_traceDump`` RPC serves the same dump
from a live node, and ``node.cli --trace[=PATH]`` /
``bench.py --trace`` arm a tracer for a whole run.

``Tracer(jax_annotations=True)`` additionally wraps device batches in
``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` scopes so
an XLA profile captured during the run lines up with framework spans.
"""
from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time

MAX_EVENTS = 64           # per-span event cap (bounds a hot loop)

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "cess_current_span", default=None)


class _NoopSpan:
    """The process-wide no-op span: every disabled hook returns THIS
    object (singleton — the zero-allocation disabled-path witness),
    and every method on it is an attribute-free no-op that returns
    ``self`` so call chains and ``with`` blocks work unchanged."""

    __slots__ = ()
    span_id = 0
    trace_id = 0

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self

    def finish(self, **attrs):
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def __bool__(self):
        return False


NOOP_SPAN = _NoopSpan()


def _json_safe(value):
    """Attrs ride into JSON exports: coerce the common non-JSON guests
    (bytes, numpy scalars) instead of failing the whole dump."""
    if isinstance(value, (bytes, bytearray)):
        return value.hex()
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    item = getattr(value, "item", None)     # numpy scalar
    if callable(item):
        try:
            return _json_safe(item())
        except (TypeError, ValueError):
            pass
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    return repr(value)


class Span:
    """One timed unit of work. Identity (span_id/trace_id/parent_id)
    is fixed at start; timing is monotonic-clock; ``attrs`` and
    ``events`` accumulate under the owning tracer's lock (spans cross
    threads: the engine submitter starts one, the batcher annotates
    and finishes it)."""

    __slots__ = ("tracer", "name", "sys", "span_id", "parent_id",
                 "trace_id", "remote_parent", "t0", "dur_s", "attrs",
                 "events", "tid", "_token", "_finished")

    def __init__(self, tracer: "Tracer", name: str, sys: str,
                 span_id: int, parent_id: int, trace_id: int,
                 remote_parent: bool, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.sys = sys
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.remote_parent = remote_parent
        self.t0 = time.monotonic()
        self.dur_s = 0.0
        self.attrs = attrs
        self.events: list[tuple[float, str, dict]] = []
        self.tid = threading.get_ident()
        self._token = None
        self._finished = False

    def set(self, **attrs) -> "Span":
        """Merge attributes (last write wins)."""
        with self.tracer._mu:
            self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> "Span":
        """Append a point-in-time annotation (retry fired, fault
        injected, batch joined); capped at MAX_EVENTS per span."""
        t = time.monotonic() - self.t0
        with self.tracer._mu:
            if len(self.events) < MAX_EVENTS:
                self.events.append((t, name, attrs))
        return self

    def finish(self, **attrs) -> "Span":
        """Close the span: record duration, push it into the tracer's
        ring buffer, restore the previous current span (if this one
        was made current in this context). Idempotent."""
        dur = time.monotonic() - self.t0
        token = None
        with self.tracer._mu:
            if self._finished:
                return self
            self._finished = True
            self.dur_s = dur
            if attrs:
                self.attrs.update(attrs)
            if len(self.tracer._spans) >= self.tracer.capacity:
                # the bounded ring is about to evict its oldest
                # finished span — count it (a silent wrap used to look
                # identical to a quiet run in every export)
                self.tracer._dropped += 1
            self.tracer._spans.append(self)
            token, self._token = self._token, None
        if token is not None:
            try:
                _CURRENT.reset(token)
            except ValueError:
                pass   # finished from another thread/context: fine
        # the flight-recorder pin seam: one attribute load + None check
        # when no recorder is attached (the zero-cost contract, pinned
        # in tests/test_flight.py). Runs after _mu is released — the
        # recorder takes its own lock. The idempotence guard above
        # means a double finish() never reaches here twice.
        fl = self.tracer.flight
        if fl is not None:
            fl.offer(self)
        return self

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.set(error=repr(exc))
        self.finish()
        return False


class Tracer:
    """One trace session: a deterministic span-id counter, a fixed
    trace id, and a bounded ring buffer of finished spans.

    capacity:        finished spans kept (oldest evicted).
    trace_id:        the session identity every root span carries;
                     spans started from a remote ``context()`` adopt
                     the sender's instead (distributed traces).
    jax_annotations: instrumented device dispatch sites additionally
                     open ``jax.profiler`` annotation scopes so an XLA
                     profile lines up with framework spans.
    """

    def __init__(self, capacity: int = 4096, trace_id: int = 1,
                 jax_annotations: bool = False):
        if capacity < 1:
            raise ValueError(f"tracer capacity {capacity} < 1")
        self._mu = threading.Lock()
        self._next_id = 1
        self.trace_id = int(trace_id)
        self.capacity = capacity
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self.jax_annotations = jax_annotations
        self.origin = time.monotonic()   # ts origin for exports
        self.pid = os.getpid()
        self.started = 0                 # spans started (ever)
        self._dropped = 0                # finished spans the ring evicted
        # optional obs.flight.FlightRecorder offered every finished
        # span (tail-sampled retention); None = seam disabled
        self.flight = None

    def attach_flight(self, recorder) -> None:
        """Attach an ``obs.flight.FlightRecorder``: every span finished
        on this tracer is offered for tail-sampled retention (pinned
        traces survive ring eviction in the recorder's own bounded
        store). Pass None to detach."""
        self.flight = recorder

    @property
    def dropped(self) -> int:
        """Finished spans evicted by the bounded ring (capacity
        overflow). A nonzero value means exports are a WINDOW, not the
        whole run — exposed as ``cess_trace_spans_dropped_total`` on
        /metrics so a wrapped ring is visible from the scrape."""
        with self._mu:
            return self._dropped

    # -- span creation -------------------------------------------------------
    def start(self, name: str, *, sys: str = "", parent=None,
              remote: tuple | None = None, current: bool = False,
              **attrs) -> Span:
        """Start a span. MUST be balanced with ``finish()`` — use it as
        a context manager or close it in a ``finally`` (cesslint's
        span-balance rule enforces this); an unclosed span never
        reaches the ring buffer and orphans its children.

        parent:  explicit parent Span; default inherits the context's
                 current span; NOOP_SPAN/absent current = root.
        remote:  ``(trace_id, span_id)`` from a peer's ``context()`` —
                 joins the sender's distributed trace.
        current: make this span the context's current span until
                 finish (same-thread ``with`` usage).
        """
        if parent is None and remote is None:
            parent = _CURRENT.get()
        remote_parent = False
        if remote is not None:
            trace_id, parent_id = int(remote[0]), int(remote[1])
            remote_parent = parent_id != 0
        elif isinstance(parent, Span):
            parent_id, trace_id = parent.span_id, parent.trace_id
        else:
            parent_id, trace_id = 0, self.trace_id
        with self._mu:
            span_id = self._next_id
            self._next_id += 1
            self.started += 1
        span = Span(self, name, sys, span_id, parent_id, trace_id,
                    remote_parent, dict(attrs))
        if current:
            span._token = _CURRENT.set(span)
        return span

    # -- export --------------------------------------------------------------
    def finished(self) -> list[dict]:
        """Finished spans (newest-capacity window) as plain dicts, in
        finish order."""
        with self._mu:
            spans = list(self._spans)
        return [self._span_dict(s) for s in spans]

    def _span_dict(self, s: Span) -> dict:
        return {
            "name": s.name, "sys": s.sys, "span_id": s.span_id,
            "parent_id": s.parent_id, "trace_id": s.trace_id,
            "remote_parent": s.remote_parent, "tid": s.tid,
            "ts_s": round(s.t0 - self.origin, 6),
            "dur_s": round(s.dur_s, 6),
            "attrs": {k: _json_safe(v) for k, v in s.attrs.items()},
            "events": [{"t_s": round(t, 6), "name": n,
                        "attrs": {k: _json_safe(v)
                                  for k, v in a.items()}}
                       for t, n, a in s.events],
        }

    def export_chrome(self, trace_id: int | None = None,
                      limit: int | None = None) -> dict:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}``
        object form): one complete (``"ph": "X"``) event per finished
        span, microsecond timestamps relative to the tracer's origin.
        Write it to a file and open in Perfetto (ui.perfetto.dev) or
        chrome://tracing; span attrs + events ride in ``args``.

        trace_id: only spans of that trace (a distributed tracer may
                  hold several); limit: newest ``limit`` spans after
                  the filter — both optional, default = whole ring.

        A span whose parent the bounded ring already evicted would
        render as a dangling edge; such spans are re-parented to the
        trace root (``"parent": 0``) with a synthetic
        ``"truncated_parent": true`` arg so a wrapped ring stays
        loadable in Perfetto and the truncation is visible per span
        (tests/test_metrics.py pins the schema)."""
        spans = self.finished()
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        if limit is not None:
            spans = spans[-limit:]
        present = {s["span_id"] for s in spans}
        events = []
        for s in spans:
            args = {
                "span_id": s["span_id"],
                "parent": s["parent_id"],
                "trace_id": s["trace_id"],
                "remote_parent": s["remote_parent"],
                "sys": s["sys"],
                "events": s["events"],
                **s["attrs"],
            }
            if s["parent_id"] != 0 and not s["remote_parent"] \
                    and s["parent_id"] not in present:
                args["parent"] = 0
                args["truncated_parent"] = True
            events.append({
                "name": s["name"],
                "cat": s["sys"] or "span",
                "ph": "X",
                "ts": round(s["ts_s"] * 1e6, 3),
                "dur": round(s["dur_s"] * 1e6, 3),
                "pid": self.pid,
                "tid": s["tid"],
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- arming ------------------------------------------------------------------
_MU = threading.Lock()
_TRACER: Tracer | None = None


def arm(tracer: Tracer) -> Tracer:
    """Install ``tracer`` as the process-wide armed tracer."""
    global _TRACER
    with _MU:
        _TRACER = tracer
    return tracer


def disarm() -> None:
    global _TRACER
    with _MU:
        _TRACER = None


def armed_tracer() -> Tracer | None:
    return _TRACER


@contextlib.contextmanager
def armed(tracer: Tracer):
    """``with trace.armed(t): ...`` — arm for the block, always disarm
    after (tests must never leak a tracer into their neighbors)."""
    arm(tracer)
    try:
        yield tracer
    finally:
        disarm()


# -- hooks (the only calls production code makes) ----------------------------
def span(name: str, *, sys: str = "", **attrs):
    """The ``with``-style hook: a current-context span on the armed
    tracer, or :data:`NOOP_SPAN` (the singleton) when none is armed —
    one global load, one ``None`` check, nothing allocated."""
    tracer = _TRACER
    if tracer is None:
        return NOOP_SPAN
    return tracer.start(name, sys=sys, current=True, **attrs)


def current_span():
    """The context's active span, or :data:`NOOP_SPAN`."""
    if _TRACER is None:
        return NOOP_SPAN
    return _CURRENT.get() or NOOP_SPAN


def event(name: str, **attrs) -> None:
    """Annotate the active span (no-op without one) — the seam the
    fault injector and retry policies use."""
    sp = _CURRENT.get()
    if sp is not None:
        sp.event(name, **attrs)


def context() -> tuple[int, int] | None:
    """The ``(trace_id, span_id)`` pair a message envelope carries
    (span_id 0 = no active span), or None when no tracer is armed —
    the sender side of the distributed-trace contract; the receiver
    passes it to ``Tracer.start(remote=...)``. The trace id is the
    CURRENT SPAN's, not the local tracer's: a node relaying a message
    it handled under a remote-joined ``net.recv`` span must propagate
    the ORIGINATOR's trace id, or a multi-hop round would fracture
    into per-node trace ids with dangling parents."""
    tracer = _TRACER
    if tracer is None:
        return None
    sp = _CURRENT.get()
    if isinstance(sp, Span):
        return (sp.trace_id, sp.span_id)
    return (tracer.trace_id, 0)
