"""Flight recorder: tail-sampled trace retention + black-box journals.

The PR-5 tracer keeps finished spans in a bounded ring — exactly the
store that wraps under the heavy traffic that causes incidents, so the
traces worth keeping are the ones most likely to be gone by the time
anyone looks (PR 6's ``cess_trace_spans_dropped_total`` only counts
the loss). This module is the retention layer: a
:class:`FlightRecorder` watches every finished span through a
zero-cost seam in ``trace.Span.finish`` and, when a request's ROOT
span finishes, decides — deterministically — whether to *pin* the
whole trace into its own bounded store, exempt from ring eviction.

Pin policy (tail sampling — the decision runs after the outcome is
known):

- **always** pin a trace containing an error / shed / saturated /
  timeout outcome, a ``degraded`` (CPU-fallback) batch, or a fault
  firing (the ``fault`` span event resilience/faults.py emits);
- pin a trace whose root class ran **over its latency objective**
  (``objectives`` maps op class -> p99 seconds — host timing, so these
  pins are bundle-visible but excluded from the replay witness);
- pin a seeded **baseline fraction** of normal traffic: the draw is
  ``sha256(seed | trace_id | root_span_id)`` against ``baseline_rate``
  — the ``FaultPlan.seeded`` discipline (no ``random.*``, no
  wallclock), so two same-seed chaos replays retain bit-identical
  trace sets. tools/cesslint.py's ``sim-determinism`` family scans
  this file (tests/test_lint.py).

The pin store is budgeted in SPANS (``pin_budget``) with
anomaly-first-retention eviction: baseline pins evict oldest-first
before any anomalous pin is touched.

The second half is the black box proper: a bounded, COUNT-sequenced
journal of notable events per subsystem (engine shed/saturation,
breaker transitions including holds, SLO transitions, adaptive knob
adjustments, finality own-vote lock acquire/release, sim invariant
checks). Entries carry a monotone sequence number, never a timestamp —
the journal must replay byte-identically under a seeded run.
``obs/incident.py`` registers as a listener and turns notable entries
into incident bundles.

Zero-cost-when-off (the PR-5 contract): the module hook
:func:`note` is one global load + ``None`` check when no recorder is
armed, and the pin seam in ``trace.Span.finish`` is one attribute
load + ``None`` check when no recorder is attached — nothing is
allocated on either disabled path (pinned in tier-1,
tests/test_flight.py).
"""
from __future__ import annotations

import collections
import contextlib
import hashlib
import threading

_SCALE = float(2 ** 64)

# outcomes that mark a span anomalous (every non-"ok" outcome the
# engine resolves with; see serve/engine.py)
_BAD_OUTCOMES = ("error", "timeout", "saturated", "shed", "closed")

# span attrs stable across same-seed replays — the only attrs the
# retention witness may include (latency_s / occupancy-style numbers
# depend on host timing and batch composition). "device" is the pool
# lane index (serve/pool.py): placement is deterministic over a
# deterministic offered sequence, so lane identity replays.
_CANON_ATTRS = frozenset(("outcome", "cls", "op", "rows", "degraded",
                          "tenant", "reason", "scenario", "round",
                          "error", "device"))


def _pin_draw(seed: bytes, trace_id: int, root_span_id: int) -> float:
    """Uniform [0, 1) from a SHA-256 stream over (seed, trace identity)
    — the FaultPlan.seeded idiom: same seed, same trace => same draw."""
    h = hashlib.sha256(b"cess-flight:" + seed + b"|"
                       + str(trace_id).encode() + b"|"
                       + str(root_span_id).encode()).digest()
    return int.from_bytes(h[:8], "big") / _SCALE


class _Pin:
    """One retained trace: the root span plus every descendant the
    recorder saw, with the union of their anomaly reasons."""

    __slots__ = ("seq", "trace_id", "root_id", "root_name", "reasons",
                 "spans")

    def __init__(self, seq: int, trace_id: int, root_id: int,
                 root_name: str, reasons: tuple, spans: list):
        self.seq = seq
        self.trace_id = trace_id
        self.root_id = root_id
        self.root_name = root_name
        self.reasons = reasons
        self.spans = spans

    @property
    def anomalous(self) -> bool:
        return any(r != "baseline" for r in self.reasons)


class FlightRecorder:
    """Tail-sampled trace retention + the per-subsystem journal.

    seed:           the deterministic-sampling seed (bytes).
    baseline_rate:  fraction of non-anomalous traces pinned as the
                    healthy-traffic baseline (seeded draw, see module
                    doc); 0 disables baseline pinning.
    objectives:     op class -> latency objective seconds; a root
                    whose class objective its duration exceeds pins as
                    ``over-objective`` (host timing — excluded from
                    :meth:`witness`).
    pin_budget:     max total pinned SPANS; baseline pins evict
                    oldest-first before any anomalous pin.
    pending_cap:    finished non-root spans held awaiting their root's
                    decision (oldest evicted past the cap).
    journal_cap:    entries retained per journal subsystem.
    """

    def __init__(self, seed: bytes = b"", *, baseline_rate: float = 0.0,
                 objectives: dict | None = None, pin_budget: int = 4096,
                 pending_cap: int = 4096, journal_cap: int = 256):
        if not 0.0 <= baseline_rate <= 1.0:
            raise ValueError(f"baseline_rate {baseline_rate} not in [0, 1]")
        if pin_budget < 1 or pending_cap < 1 or journal_cap < 1:
            raise ValueError("flight recorder bounds must be >= 1")
        self.seed = seed if isinstance(seed, bytes) else str(seed).encode()
        self.baseline_rate = float(baseline_rate)
        self.objectives = dict(objectives or {})
        self.pin_budget = pin_budget
        self.pending_cap = pending_cap
        self.journal_cap = journal_cap
        self._mu = threading.Lock()
        # journal delivery serialization (the SloBoard announce
        # pattern): entries are ENQUEUED under _mu, DELIVERED in
        # sequence order under _deliver_mu with _mu released, so a
        # listener may read any snapshot without a lock cycle.
        # Lock order: _deliver_mu > _mu (never take _deliver_mu while
        # holding _mu).
        self._deliver_mu = threading.RLock()
        self._pending_notes: collections.deque = collections.deque()
        # finished non-root spans awaiting their root, span_id ->
        # (span, reasons); insertion order = eviction order
        self._pending: dict = {}
        self._children: dict = {}          # parent_id -> [span_id]
        self._pins: dict = {}              # root_id -> _Pin (pin order)
        self._pin_index: dict = {}         # span_id -> root_id
        self._pinned_spans = 0
        self._pin_seq = 0
        self._journals: dict = {}          # subsystem -> deque
        self._seq = 0
        self._listeners: list = []
        self.offered = 0
        self.roots_seen = 0
        self.baseline_pins = 0
        self.anomaly_pins = 0
        self.pin_evictions = 0
        self.pending_evictions = 0

    # -- the pin seam (trace.Span.finish calls this) -------------------------
    def offer(self, span) -> None:
        """A finished span. Non-roots are held (bounded) until their
        root's decision — or appended directly when their parent chain
        already resolved to a pinned trace; a root triggers the
        pin/drop decision for its whole held subtree."""
        reasons = self._span_reasons(span)
        with self._mu:
            self.offered += 1
            is_root = span.parent_id == 0 or span.remote_parent
            if not is_root:
                root_id = self._pin_index.get(span.parent_id)
                if root_id is not None:
                    # late arrival: its trace was already pinned
                    pin = self._pins[root_id]
                    pin.spans.append(span)
                    if reasons:
                        pin.reasons = tuple(sorted(
                            set(pin.reasons) | set(reasons)))
                    self._pin_index[span.span_id] = root_id
                    self._pinned_spans += 1
                    self._enforce_budget_locked()
                    return
                self._pending[span.span_id] = (span, tuple(reasons))
                self._children.setdefault(span.parent_id,
                                          []).append(span.span_id)
                while len(self._pending) > self.pending_cap:
                    evicted = next(iter(self._pending))
                    sp, _ = self._pending.pop(evicted)
                    sibs = self._children.get(sp.parent_id)
                    if sibs is not None:
                        sibs.remove(evicted)
                        if not sibs:
                            del self._children[sp.parent_id]
                    self.pending_evictions += 1
                return
            self.roots_seen += 1
            self._decide_locked(span, reasons)

    def _span_reasons(self, span) -> list:
        a = span.attrs
        reasons = []
        outcome = a.get("outcome")
        if outcome is not None and outcome != "ok":
            if outcome in _BAD_OUTCOMES:
                reasons.append(str(outcome))
        elif "error" in a:
            reasons.append("error")
        if a.get("degraded"):
            reasons.append("degraded")
        if any(name == "fault" for _, name, _ in list(span.events)):
            reasons.append("fault")
        cls = a.get("cls")
        if cls is not None:
            objective = self.objectives.get(cls)
            if objective is not None and span.dur_s > objective:
                reasons.append("over-objective")
        return reasons

    def _decide_locked(self, root, root_reasons: list) -> None:
        # gather the held subtree (children finished before the root)
        members: list = []
        reasons = set(root_reasons)
        frontier = [root.span_id]
        while frontier:
            pid = frontier.pop()
            for sid in self._children.pop(pid, ()):
                span, span_reasons = self._pending.pop(sid)
                members.append(span)
                reasons.update(span_reasons)
                frontier.append(sid)
        if not reasons and self.baseline_rate > 0.0 \
                and _pin_draw(self.seed, root.trace_id,
                              root.span_id) < self.baseline_rate:
            reasons.add("baseline")
        if not reasons:
            return                         # unpinned: the ring's problem
        self._pin_seq += 1
        # span order inside a pin is by id (creation order) — finish
        # order races across threads, creation order replays
        members.sort(key=lambda s: s.span_id)
        pin = _Pin(self._pin_seq, root.trace_id, root.span_id,
                   root.name, tuple(sorted(reasons)), [root] + members)
        self._pins[root.span_id] = pin
        for span in pin.spans:
            self._pin_index[span.span_id] = root.span_id
        self._pinned_spans += len(pin.spans)
        if pin.anomalous:
            self.anomaly_pins += 1
        else:
            self.baseline_pins += 1
        self._enforce_budget_locked()

    def _enforce_budget_locked(self) -> None:
        # anomaly-first RETENTION: evict oldest baseline pins first;
        # only when none remain do anomalous pins age out. A single
        # over-budget trace is kept whole (the budget bounds the
        # store, never truncates a trace).
        while self._pinned_spans > self.pin_budget and len(self._pins) > 1:
            victim = None
            for root_id, pin in self._pins.items():
                if not pin.anomalous:
                    victim = root_id
                    break
            if victim is None:
                victim = next(iter(self._pins))
            pin = self._pins.pop(victim)
            for span in pin.spans:
                self._pin_index.pop(span.span_id, None)
            self._pinned_spans -= len(pin.spans)
            self.pin_evictions += 1

    # -- pinned-trace export -------------------------------------------------
    def pinned(self) -> list[dict]:
        """Pinned traces (pin order) as self-contained dicts — full
        span records via the owning tracer's serializer."""
        with self._mu:
            pins = list(self._pins.values())
        return [{
            "seq": p.seq,
            "trace_id": p.trace_id,
            "root_span_id": p.root_id,
            "root": p.root_name,
            "reasons": list(p.reasons),
            "anomalous": p.anomalous,
            "spans": [s.tracer._span_dict(s) for s in p.spans],
        } for p in pins]

    def witness(self) -> tuple:
        """The deterministic retention witness (the ``fired_log``
        analog): every pin whose reasons survive with host-timing
        pins (``over-objective``-only) removed, reduced to
        replay-stable fields. Two same-seed runs must produce
        identical tuples (tests/test_flight.py)."""
        from .trace import _json_safe
        with self._mu:
            pins = list(self._pins.values())
        out = []
        for p in pins:
            reasons = tuple(r for r in p.reasons if r != "over-objective")
            if not reasons:
                continue
            spans = tuple(sorted(
                (s.span_id, s.parent_id, s.name, s.sys,
                 tuple(sorted((k, repr(_json_safe(v)))
                              for k, v in dict(s.attrs).items()
                              if k in _CANON_ATTRS)))
                for s in p.spans))
            out.append((p.trace_id, p.root_id, p.root_name, reasons,
                        spans))
        return tuple(out)

    # -- the black-box journal -----------------------------------------------
    def note(self, subsystem: str, kind: str, **detail) -> None:
        """Append one count-sequenced journal entry and deliver it to
        listeners (outside the recorder lock, in sequence order)."""
        with self._mu:
            self._seq += 1
            entry = (self._seq, subsystem, kind, detail)
            journal = self._journals.get(subsystem)
            if journal is None:
                journal = self._journals[subsystem] = collections.deque(
                    maxlen=self.journal_cap)
            journal.append(entry)
            if self._listeners:
                self._pending_notes.append(entry)
            else:
                return
        self._deliver()

    def add_listener(self, fn) -> None:
        """``fn(seq, subsystem, kind, detail)`` per journal entry,
        delivered outside the recorder lock on the noting thread —
        the obs/incident.py trigger seam."""
        with self._mu:
            self._listeners.append(fn)

    def _deliver(self) -> None:
        with self._deliver_mu:
            while True:
                with self._mu:
                    if not self._pending_notes:
                        return
                    entry = self._pending_notes.popleft()
                    fns = list(self._listeners)
                for fn in fns:
                    fn(*entry)

    def journal_tail(self, subsystem: str | None = None,
                     limit: int | None = None) -> list[dict]:
        """Newest journal entries (merged across subsystems by
        sequence number when ``subsystem`` is None)."""
        with self._mu:
            if subsystem is not None:
                entries = list(self._journals.get(subsystem, ()))
            else:
                entries = sorted(
                    (e for j in self._journals.values() for e in j))
        if limit is not None:
            entries = entries[-limit:]
        return [{"seq": seq, "sys": sys_, "kind": kind,
                 "detail": dict(detail)}
                for seq, sys_, kind, detail in entries]

    def snapshot(self) -> dict:
        with self._mu:
            pins = list(self._pins.values())
            journals = {s: len(j) for s, j in sorted(self._journals.items())}
            return {
                "offered": self.offered,
                "roots_seen": self.roots_seen,
                "pins": len(pins),
                "pinned_spans": self._pinned_spans,
                "anomaly_pins": self.anomaly_pins,
                "baseline_pins": self.baseline_pins,
                "pin_evictions": self.pin_evictions,
                "pending": len(self._pending),
                "pending_evictions": self.pending_evictions,
                "pin_budget": self.pin_budget,
                "journal_entries": self._seq,
                "journals": journals,
            }


# -- arming (the resilience.faults / obs.trace pattern) ----------------------
_MU = threading.Lock()
_RECORDER: FlightRecorder | None = None


def arm(recorder: FlightRecorder) -> FlightRecorder:
    """Install ``recorder`` as the process-wide armed flight recorder
    (the :func:`note` hook's target)."""
    global _RECORDER
    with _MU:
        _RECORDER = recorder
    return recorder


def disarm() -> None:
    global _RECORDER
    with _MU:
        _RECORDER = None


def armed_recorder() -> FlightRecorder | None:
    return _RECORDER


@contextlib.contextmanager
def armed(recorder: FlightRecorder):
    """``with flight.armed(r): ...`` — arm for the block, always
    disarm after (tests must never leak a recorder into neighbors)."""
    arm(recorder)
    try:
        yield recorder
    finally:
        disarm()


def note(subsystem: str, kind: str, **detail) -> None:
    """The journal hook production code calls: one module-global load
    + ``None`` check when disarmed. Call sites sit on anomaly paths
    (shed, trip, transition), never inside a lock whose holder an
    incident bundle might need to read."""
    rec = _RECORDER
    if rec is None:
        return
    rec.note(subsystem, kind, **detail)
