"""Chain-plane observability: consensus health, the storage-market
ledger, and byzantine anomaly detection.

The fleet/profiling planes (obs/fleet.py, obs/profile.py) watch the
SERVING side of a node; the chain plane — RRSC slot production,
GRANDPA-style finality, PoDR2 audit verdicts, the storage/restoral
market — was visible only as raw flight-journal notes. This module is
the missing layer: a deterministic chain-health truth source the
byzantine sim scenarios (and the multi-host plane) certify against.

- :class:`ConsensusWatch` — per-node consensus health fed from
  ``node/network.py``/``node/finality.py`` snapshots: head vs
  finalized height and the finality lag between them, reorg depth
  (inferred by diffing the canonical-hash tail between scans — the
  import path has no reorg hook, by design) and fork-count
  accounting, own-vote-lock hold ages against the gadget's
  ``LOCK_HORIZON``, slot/era progress, and an equivocation detector:
  two distinct block hashes claimed by one author for one slot (the
  slot claim signs (slot, author), NOT the block contents — exactly
  the BABE equivocation shape), or a conflicting vote pair recorded
  by the finality gadget. Either yields an evidence record shaped
  for ``chain/offences.py``: offender + round/slot + both signed
  objects, the same fields ``Offences.report_equivocation`` keys on.

- :class:`MarketWatch` — the storage-market ledger, recomputed
  idempotently each scan from retained chain state/events
  (``chain/file_bank.py``/``chain/sminer.py``/``chain/audit.py``):
  per-miner audit pass/fail rates with a windowed failure-spike
  detector, declared-vs-audited capacity drift (a miner whose
  declared service space is not evidenced by stored fragments is the
  fake-capacity heuristic), restoral-auction race/completion
  accounting, and space-sold/pledged totals.

- :class:`ChainAnomalyDetector` — edge-triggered ok↔bad transitions
  per (class, key), announced exactly like FleetBoard's: a
  ``chain.anomaly`` span plus a ``("chain", "anomaly")`` flight note
  delivered FIFO outside the detector lock. The four classes —
  ``finality-stall``, ``deep-reorg``, ``equivocation``,
  ``audit-failure-spike`` — are incident triggers (obs/incident.py);
  the bundle embeds the chain-health snapshot. Transitions append to
  a count-sequenced log; :meth:`ChainAnomalyDetector.witness`
  replays byte-identically under same-seed sim chaos.

:class:`ChainWatch` composes the three behind a scan/seal API and is
what gets armed: ``node.chainwatch`` on a live node (``node.cli
--chainwatch``, scanned by the net author loop, served by the
``cess_chainStatus`` RPC and as ``cess_chain_*`` gauges on
GET /metrics), ``world.chainwatch`` in the sim
(``Scenario.chainwatch=True``). Chain-health frames ride the PR-12
fleet gossip: the sender folds its consensus state into the fleet
frame's slo dict under a ``"chain"`` key (plus a ``finality_lag``
SLO class so :class:`~cess_tpu.obs.fleet.FleetBoard` folds per-node
lag into worst/quorum views), and the receiver's ``("fleet", ...)``
handler hands the same frame to ``chainwatch.ingest_frame`` so the
:class:`~cess_tpu.obs.fleet.StragglerDetector` can flag lag outliers
from :meth:`ChainWatch.seal_round`.

Zero-cost-when-off contract: this module installs NO hooks. The hot
paths that feed it (the net author loop, the sim round loop, the
metrics collector) gate on ``getattr(x, "chainwatch", None)`` — one
attribute load and a None check when disarmed, same as the fleet
contract; with ``--chainwatch`` off every existing path is
byte-identical.

Determinism: chainwatch.py is in the sim-determinism lint family
(cess_tpu/analysis) — no wallclock, no entropy. Scans, rounds and
transition logs are sequenced by internal counters;
:meth:`ChainWatch.witness` serializes the consensus views, the
evidence log, the market ledger and the anomaly transition log to
canonical bytes, and two same-seed ``equivocating_validator`` runs
must produce identical witnesses (tests/test_chainwatch.py).
"""
from __future__ import annotations

import collections
import json
import threading

from . import flight as _flight
from . import trace as _trace

# Finality-lag health grading (blocks of lag = head - finalized).
# A healthy sim world finalizes within a round or two; a stalled
# quorum grows lag by ~1/round, so warn trips a few rounds into a
# partition and burning marks a long outage.
LAG_WARN = 3
LAG_BURNING = 9
# Anomaly thresholds.
STALL_LAG = 4        # finality-stall when lag reaches this
DEEP_REORG = 3       # deep-reorg when one scan-to-scan reorg >= this
SPIKE_WINDOW = 8     # audit verdicts per miner considered for a spike
SPIKE_FAILS = 3      # fails inside the window => audit-failure-spike
TAIL = 32            # canonical-hash tail kept per node (reorg diffing)
EQUIVOCATION_WINDOW = 64   # block-number window scanned for doubles


def lag_state(lag: int) -> str:
    """Grade one node's finality lag for the fleet SLO board."""
    if lag > LAG_BURNING:
        return "burning"
    if lag > LAG_WARN:
        return "warn"
    return "ok"


def node_state(node) -> dict:
    """Build one consensus-state dict from a live ``network.Node`` —
    the unit :meth:`ChainWatch.ingest_state` consumes, what rides the
    fleet gossip frame under the ``"chain"`` key, and what bench.py
    synthesizes for 100 fake nodes. Duck-typed on purpose: obs/ never
    imports node/."""
    head = node.head()
    headn = int(head.number)
    chain = node.chain
    tail = {}
    for n in range(max(0, headn - TAIL), headn + 1):
        tail[str(n)] = chain[n].hash().hex()
    blocks = []
    floor = headn - EQUIVOCATION_WINDOW
    for h, hdr in node.headers.items():
        if hdr.claim is not None and hdr.number > floor:
            blocks.append([hdr.author, int(hdr.claim.slot), h.hex()])
    blocks.sort()
    gadget = node.finality
    locks = []
    for account in sorted(node.keystore):
        for rnd in gadget.locked_rounds(account, headn):
            locks.append([account, int(rnd)])
    votes = []
    for va, vb in gadget.equivocations:
        votes.append([va.voter, int(va.round),
                      va.target_hash.hex(), vb.target_hash.hex()])
    votes.sort()
    return {
        "head": headn,
        "finalized": int(node.finalized),
        "slot": int(head.claim.slot) if head.claim is not None else 0,
        "era": int(node.runtime.staking.current_era()),
        "forks": len(node.headers) - len(chain),
        "tail": tail,
        "blocks": blocks,
        "locks": locks,
        "vote_equivocations": votes,
    }


def market_state(st, *, fragment_size: int) -> dict:
    """Build one market-ledger dict from a chain ``State`` — chain
    state is replicated, so ONE node's runtime (the sim gateway, the
    live node itself) feeds the whole ledger. Recomputed from the
    retained event window each scan: idempotent, no cursors."""
    miners: dict = {}
    for (who,), info in sorted(st.iter_prefix("sminer", "miner")):
        audited = 0
        for _k, _v in st.iter_prefix("file_bank", "frag_of_miner", who):
            audited += fragment_size
        miners[who] = {
            "idle": int(info.idle_space),
            "service": int(info.service_space),
            "lock": int(info.lock_space),
            "state": str(info.state),
            "audited": audited,
        }
    verdicts: dict = {}
    for e in st.events_of("audit", "VerifyResult"):
        d = dict(e.data)
        both = bool(d.get("idle")) and bool(d.get("service"))
        verdicts.setdefault(str(d.get("miner")), []).append(int(both))
    generated = len(st.events_of("file_bank", "GenerateRestoralOrder"))
    claims = len(st.events_of("file_bank", "ClaimRestoralOrder"))
    completed = len(st.events_of("file_bank", "RestoralComplete"))
    open_orders = claimed = 0
    for _k, order in st.iter_prefix("file_bank", "restoral"):
        open_orders += 1
        if getattr(order, "miner", None):
            claimed += 1
    return {
        "miners": miners,
        "verdicts": verdicts,
        "restoral": {
            "open": open_orders, "claimed": claimed,
            "generated": generated, "claims": claims,
            "completed": completed,
        },
    }


class ConsensusWatch:
    """Per-node consensus health, count-sequenced. Fed one
    state dict (:func:`node_state` shape) per node per scan; keeps
    the canonical-hash tail from the previous scan to infer reorgs
    and a (author, slot) -> hashes map to detect double-signing."""

    def __init__(self, *, lock_horizon: int = 32,
                 evidence_cap: int = 256):
        self._mu = threading.Lock()
        self.lock_horizon = int(lock_horizon)
        self._scans = 0
        self._views: dict[str, dict] = {}
        self._tails: dict[str, dict[int, str]] = {}
        self._claims: dict[tuple, set] = {}
        self._evidence: collections.deque = collections.deque(
            maxlen=evidence_cap)
        self._evidence_keys: set = set()
        self._reorgs = 0
        self._max_reorg_depth = 0

    def observe(self, instance: str, state: dict) -> None:
        """Ingest one node's consensus state. Malformed input (a
        hostile or version-skewed gossip peer) is dropped whole —
        never fatal, never partially applied."""
        if not isinstance(state, dict):
            return
        try:
            view, tail = self._digest(str(instance), dict(state))
        except (TypeError, ValueError, KeyError, AttributeError):
            return
        with self._mu:
            self._scans += 1
            inst = str(instance)
            prev = self._tails.get(inst)
            depth = self._reorg_depth(prev, tail)
            if depth:
                self._reorgs += 1
                if depth > self._max_reorg_depth:
                    self._max_reorg_depth = depth
            view["reorg_depth"] = depth
            self._tails[inst] = tail
            self._views[inst] = view
            for author, slot, hex_hash in view.pop("_blocks"):
                key = (author, slot)
                seen = self._claims.setdefault(key, set())
                if hex_hash not in seen:
                    seen.add(hex_hash)
                    if len(seen) >= 2:
                        self._record_evidence({
                            "kind": "block-equivocation",
                            "offender": author, "round": slot,
                            "hashes": sorted(seen),
                        })
            for voter, rnd, ha, hb in view.pop("_votes"):
                self._record_evidence({
                    "kind": "vote-equivocation",
                    "offender": voter, "round": rnd,
                    "hashes": sorted((ha, hb)),
                })

    @staticmethod
    def _digest(instance: str, state: dict) -> tuple[dict, dict]:
        head = int(state["head"])
        finalized = int(state["finalized"])
        tail = {int(n): str(h) for n, h in dict(state["tail"]).items()}
        blocks = [(str(a), int(s), str(h))
                  for a, s, h in state.get("blocks", ())]
        votes = [(str(v), int(r), str(ha), str(hb))
                 for v, r, ha, hb in state.get("vote_equivocations", ())]
        ages = [head - int(r) for _a, r in state.get("locks", ())]
        return ({
            "head": head,
            "finalized": finalized,
            "lag": head - finalized,
            "slot": int(state.get("slot", 0)),
            "era": int(state.get("era", 0)),
            "forks": int(state.get("forks", 0)),
            "locks": len(ages),
            "max_lock_age": max(ages, default=0),
            "_blocks": blocks,
            "_votes": votes,
        }, tail)

    @staticmethod
    def _reorg_depth(prev, tail) -> int:
        """Depth of the reorg between two canonical-hash tails: how
        many blocks below the OLD head changed hash (0 = extension)."""
        if not prev:
            return 0
        old_head = max(prev)
        if tail.get(old_head) in (None, prev[old_head]):
            return 0
        common = 0
        for n in sorted(set(prev) & set(tail)):
            if prev[n] == tail[n]:
                common = n
        return old_head - common

    def _record_evidence(self, record: dict) -> None:
        key = (record["kind"], record["offender"], record["round"])
        if key in self._evidence_keys:
            return
        self._evidence_keys.add(key)
        self._evidence.append(record)

    # -- reading -------------------------------------------------------------
    def views(self) -> dict:
        with self._mu:
            return {inst: dict(v) for inst, v in self._views.items()}

    def evidence(self) -> tuple:
        with self._mu:
            return tuple(dict(e) for e in self._evidence)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "scans": self._scans,
                "lock_horizon": self.lock_horizon,
                "reorgs": self._reorgs,
                "max_reorg_depth": self._max_reorg_depth,
                "nodes": {inst: dict(v)
                          for inst, v in sorted(self._views.items())},
                "equivocations": [dict(e) for e in self._evidence],
            }


class MarketWatch:
    """The storage-market ledger. One :func:`market_state` dict per
    scan replaces the previous ledger view — chain state is already
    cumulative, so recompute-and-replace is idempotent and needs no
    event cursors."""

    def __init__(self, *, spike_window: int = SPIKE_WINDOW,
                 spike_fails: int = SPIKE_FAILS):
        self._mu = threading.Lock()
        self.spike_window = int(spike_window)
        self.spike_fails = int(spike_fails)
        self._scans = 0
        self._miners: dict[str, dict] = {}
        self._restoral = {"open": 0, "claimed": 0, "generated": 0,
                          "claims": 0, "completed": 0}

    def observe(self, market: dict) -> None:
        if not isinstance(market, dict):
            return
        try:
            miners, restoral = self._digest(dict(market))
        except (TypeError, ValueError, KeyError, AttributeError):
            return
        with self._mu:
            self._scans += 1
            self._miners = miners
            self._restoral = restoral

    def _digest(self, market: dict) -> tuple[dict, dict]:
        verdicts = {str(m): [int(bool(v)) for v in vs]
                    for m, vs in dict(market.get("verdicts", {})).items()}
        miners = {}
        for who, info in dict(market.get("miners", {})).items():
            service = int(info["service"])
            audited = int(info.get("audited", 0))
            vs = verdicts.get(str(who), [])
            window = vs[-self.spike_window:]
            fails = window.count(0)
            miners[str(who)] = {
                "idle": int(info["idle"]),
                "service": service,
                "lock": int(info.get("lock", 0)),
                "state": str(info.get("state", "")),
                "audited": audited,
                # fake-capacity heuristic: declared service space not
                # evidenced by stored fragments
                "drift": service - audited,
                "fake_capacity": bool(service > 0
                                      and audited * 2 < service),
                "passes": sum(vs),
                "fails": len(vs) - sum(vs),
                "spike": bool(fails >= self.spike_fails),
            }
        r = dict(market.get("restoral", {}))
        restoral = {k: int(r.get(k, 0))
                    for k in ("open", "claimed", "generated",
                              "claims", "completed")}
        return miners, restoral

    # -- reading -------------------------------------------------------------
    def spikes(self) -> tuple:
        with self._mu:
            return tuple(sorted(m for m, v in self._miners.items()
                                if v["spike"]))

    def snapshot(self) -> dict:
        with self._mu:
            miners = {m: dict(v)
                      for m, v in sorted(self._miners.items())}
            restoral = dict(self._restoral)
            scans = self._scans
        return {
            "scans": scans,
            "miners": miners,
            "restoral": restoral,
            "space": {
                "idle": sum(v["idle"] for v in miners.values()),
                "service": sum(v["service"] for v in miners.values()),
                "pledged": sum(v["lock"] for v in miners.values()),
                "audited": sum(v["audited"] for v in miners.values()),
                "drift": sum(v["drift"] for v in miners.values()),
            },
            "spikes": sorted(m for m, v in miners.items()
                             if v["spike"]),
        }


class ChainAnomalyDetector:
    """Edge-triggered ok↔bad state per (class, key) with a bounded
    count-sequenced transition log. Transitions announce FIFO under
    ``_announce_mu`` OUTSIDE the detector lock — a ``chain.anomaly``
    span plus a ``("chain", "anomaly")`` flight note per edge, which
    obs/incident.py turns into one incident per NEW bad edge."""

    CLASSES = ("finality-stall", "deep-reorg", "equivocation",
               "audit-failure-spike")

    def __init__(self, *, log_cap: int = 512):
        self._mu = threading.Lock()
        self._seq = 0
        self._anomalies = 0
        self._state: dict[tuple, str] = {}
        self._log: collections.deque = collections.deque(maxlen=log_cap)
        # whichever thread holds the announce lock drains everything
        self._announce_mu = threading.RLock()
        self._pending: collections.deque = collections.deque()

    def update(self, cls: str, key: str, bad: bool, **detail) -> None:
        to = "bad" if bad else "ok"
        with self._mu:
            old = self._state.get((cls, key), "ok")
            if old == to:
                return
            self._state[(cls, key)] = to
            self._seq += 1
            if bad:
                self._anomalies += 1
            self._log.append((self._seq, cls, key, old, to))
            self._pending.append((cls, key, old, to, dict(detail)))
        self._drain_announcements()

    def _drain_announcements(self) -> None:
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending:
                        return
                    item = self._pending.popleft()
                self._announce(*item)

    def _announce(self, cls: str, key: str, old: str, to: str,
                  detail: dict) -> None:
        with _trace.span("chain.anomaly", sys="chain", cls=cls,
                         key=key, frm=old, to=to):
            pass
        _flight.note("chain", "anomaly", cls=cls, key=key,
                     frm=old, to=to, **detail)

    # -- reading -------------------------------------------------------------
    def transition_log(self) -> tuple:
        with self._mu:
            return tuple(self._log)

    def active(self) -> dict:
        with self._mu:
            out: dict = {}
            for (cls, key), st in sorted(self._state.items()):
                if st == "bad":
                    out.setdefault(cls, []).append(key)
            return out

    def snapshot(self) -> dict:
        with self._mu:
            state = dict(self._state)
            return {
                "seq": self._seq,
                "anomalies": self._anomalies,
                "active": {
                    cls: [k for (c, k), st in sorted(state.items())
                          if c == cls and st == "bad"]
                    for cls in self.CLASSES},
                "transitions": [list(t) for t in self._log],
            }

    def witness(self) -> bytes:
        """Canonical bytes of the transition log + active set. Two
        same-seed sim runs must return identical bytes."""
        with self._mu:
            canon = {
                "transitions": [list(t) for t in self._log],
                "active": sorted([c, k]
                                 for (c, k), st in self._state.items()
                                 if st == "bad"),
            }
        return json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()


class ChainWatch:
    """The chain plane: ConsensusWatch + MarketWatch +
    ChainAnomalyDetector behind a scan/seal API shaped like
    :class:`~cess_tpu.obs.fleet.FleetPlane`. Scans ingest state;
    :meth:`seal_round` runs the detectors over the latest views (and
    folds per-node finality lag into an attached fleet plane's
    straggler windows) — component locks only, never held across the
    announce path."""

    def __init__(self, instance: str = "node", *,
                 lock_horizon: int = 32,
                 stall_lag: int = STALL_LAG,
                 deep_reorg: int = DEEP_REORG,
                 spike_window: int = SPIKE_WINDOW,
                 spike_fails: int = SPIKE_FAILS,
                 fragment_size: int = 8 * 2 ** 20):
        self.instance = str(instance)
        self.stall_lag = int(stall_lag)
        self.deep_reorg = int(deep_reorg)
        self.fragment_size = int(fragment_size)
        self.consensus = ConsensusWatch(lock_horizon=lock_horizon)
        self.market = MarketWatch(spike_window=spike_window,
                                  spike_fails=spike_fails)
        self.anomalies = ChainAnomalyDetector()
        self._mu = threading.Lock()
        self._rounds = 0
        self._fleet = None

    def attach_fleet(self, plane) -> None:
        """Fold per-node finality lag into a fleet plane's straggler
        windows at every seal (the SLO-class fold rides the gossip
        frame itself — see :meth:`self_slo`)."""
        self._fleet = plane

    # -- ingestion -----------------------------------------------------------
    def ingest_state(self, instance: str, state: dict) -> None:
        self.consensus.observe(str(instance), state)

    def ingest_market(self, market: dict) -> None:
        self.market.observe(market)

    def ingest_frame(self, frame) -> None:
        """Chain-health side of one fleet gossip frame (the 3-tuple
        ``(instance, exposition, slo_json)``): the sender folds its
        :func:`node_state` dict into the slo dict under ``"chain"``.
        Anything malformed is dropped whole — a hostile peer cannot
        poison the plane."""
        try:
            inst, _expo, slo_json = frame
            slo = json.loads(slo_json)
        except (TypeError, ValueError):
            return
        if not isinstance(slo, dict):
            return
        chain = slo.get("chain")
        if isinstance(chain, dict):
            self.ingest_state(str(inst), chain)

    def scan_node(self, node, instance: str | None = None) -> None:
        """One full scan of a live node: consensus state plus the
        market ledger from its (replicated) runtime state."""
        inst = self.instance if instance is None else str(instance)
        self.ingest_state(inst, node_state(node))
        self.ingest_market(market_state(
            node.runtime.state, fragment_size=self.fragment_size))

    def self_slo(self, node) -> dict:
        """What the sender folds into its fleet gossip frame's slo
        dict: the raw consensus state under ``"chain"`` plus a
        ``finality_lag`` SLO class so every receiver's FleetBoard
        folds this node's lag into its worst/quorum views."""
        state = node_state(node)
        lag = state["head"] - state["finalized"]
        return {"chain": state,
                "targets": {"finality_lag": {"state": lag_state(lag),
                                             "lag": lag}}}

    # -- sealing -------------------------------------------------------------
    def seal_round(self) -> int:
        with self._mu:
            self._rounds += 1
            rnd = self._rounds
        views = self.consensus.views()
        det = self.anomalies
        for inst in sorted(views):
            v = views[inst]
            det.update("finality-stall", inst,
                       v["lag"] >= self.stall_lag,
                       lag=v["lag"], head=v["head"],
                       finalized=v["finalized"])
            det.update("deep-reorg", inst,
                       v["reorg_depth"] >= self.deep_reorg,
                       depth=v["reorg_depth"], head=v["head"])
        for ev in self.consensus.evidence():
            det.update("equivocation",
                       f"{ev['offender']}@{ev['round']}", True,
                       evidence=ev["kind"], offender=ev["offender"],
                       round=ev["round"])
        market = self.market.snapshot()
        for who, m in market["miners"].items():
            det.update("audit-failure-spike", who, m["spike"],
                       fails=m["fails"], passes=m["passes"])
        plane = self._fleet
        if plane is not None:
            for inst in sorted(views):
                plane.stragglers.observe(inst, "finality_lag",
                                         float(views[inst]["lag"]))
        return rnd

    # -- introspection -------------------------------------------------------
    @property
    def rounds(self) -> int:
        with self._mu:
            return self._rounds

    def metrics(self) -> dict:
        """Flat ``cess_chain_*`` gauges for node/metrics.py. The
        consensus gauges read this node's OWN view when present (a
        live node always scans itself), else the worst across views
        (the sim plane watches every node)."""
        with self._mu:
            rounds = self._rounds
        views = self.consensus.views()
        own = views.get(self.instance)
        if own is None and views:
            own = max(views.values(), key=lambda v: v["lag"])
        consensus = self.consensus.snapshot()
        market = self.market.snapshot()
        anomalies = self.anomalies.snapshot()
        m = {
            "cess_chain_rounds": float(rounds),
            "cess_chain_nodes": float(len(views)),
            "cess_chain_reorgs_total": float(consensus["reorgs"]),
            "cess_chain_reorg_depth_max":
                float(consensus["max_reorg_depth"]),
            "cess_chain_equivocations_total":
                float(len(consensus["equivocations"])),
            "cess_chain_anomalies_total":
                float(anomalies["anomalies"]),
            "cess_chain_stalled_nodes":
                float(len(anomalies["active"]["finality-stall"])),
            "cess_chain_market_miners":
                float(len(market["miners"])),
            "cess_chain_audit_fail_spikes":
                float(len(market["spikes"])),
            "cess_chain_capacity_drift_bytes":
                float(market["space"]["drift"]),
            "cess_chain_restoral_open":
                float(market["restoral"]["open"]),
        }
        if own is not None:
            m["cess_chain_head"] = float(own["head"])
            m["cess_chain_finalized"] = float(own["finalized"])
            m["cess_chain_finality_lag"] = float(own["lag"])
            m["cess_chain_forks"] = float(own["forks"])
            m["cess_chain_lock_age_max"] = float(own["max_lock_age"])
        return m

    def snapshot(self) -> dict:
        """The ``cess_chainStatus`` RPC payload."""
        with self._mu:
            rounds = self._rounds
        return {
            "instance": self.instance,
            "rounds": rounds,
            "consensus": self.consensus.snapshot(),
            "market": self.market.snapshot(),
            "anomalies": self.anomalies.snapshot(),
        }

    def witness(self) -> bytes:
        """THE chain-plane replay witness: consensus views + evidence
        + market ledger + anomaly transition log, canonical JSON
        bytes. Two same-seed sim runs must return identical bytes."""
        canon = {
            "consensus": self.consensus.snapshot(),
            "market": self.market.snapshot(),
            "transitions": [list(t)
                            for t in self.anomalies.transition_log()],
        }
        return json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()
