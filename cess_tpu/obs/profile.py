"""Continuous performance profiling: device-time attribution,
pad/compile ledgers, and a bench-anchored regression watchdog.

The observability stack below this module can say *that* the serving
plane is unhealthy (SLO burn, breaker trips, fleet quorum views) but
not *where device time goes*. This module closes that gap: the
serving plane continuously profiles itself — per-shape stage
breakdowns, padded-row accounts, program-cache compile events — and
compares its live windowed throughput against the newest checked-in
``BENCH_r*.json`` record, so a kernel regression fires an incident
instead of waiting for a human to run ``bench_diff --history``:

- :class:`OpProfiler` — per-(class, bucket-shape, device) accounting
  of every engine dispatch: stage breakdown (queue-wait / h2d /
  dispatch / sync), served vs padded rows, bytes moved, and a
  count-windowed throughput gauge per class. Fed from the existing
  span-attribute seams in ``serve/engine.py`` (``_account_batch``),
  ``serve/stream.py`` (the double-buffered drive loop) and
  ``serve/pool.py`` lanes (the lane index rides the account key).

- :class:`PadLedger` — ranked padded-row accounts per class×bucket,
  split by source (``engine`` coalescing vs ``stream`` ragged tails)
  so ONE number answers "how much padding, end to end". This is the
  before/after evidence table the ragged-batching roadmap item needs.

- :class:`CompileLedger` — program-cache compile events with
  canonicalized shape keys and compile wall time. A recompile storm
  (a shape churn defeating the cache) becomes a visible ranked
  account instead of a mystery latency cliff.

- :class:`PerfWatchdog` — per tracked bench metric, accumulates
  (bytes, busy-seconds) over observation-COUNT windows and
  edge-triggers an ok↔regressed transition when a window's GiB/s
  falls below ``guard`` × the bench baseline. Transitions announce
  exactly like FleetBoard's: a ``perf.regression`` span plus a
  ``("perf", "regression")`` flight note delivered FIFO outside the
  watchdog lock — the ``perf-regression`` incident trigger
  (obs/incident.py), whose bundle embeds both ledgers.

:class:`ProfilePlane` composes all four behind the engine seam and is
what gets armed: ``engine.profile`` / ``node.profile`` on a live node
(``node.cli --profile``, served by the ``cess_profileDump`` RPC and
``cess_profile_*`` gauges on GET /metrics), ``Scenario.profile=True``
in the sim (the snapshot rides ``SimReport``), and
``tools/profile_view.py`` renders a dump.

Zero-cost-when-off contract: this module installs NO hooks. The hot
paths that feed it gate on one attribute load and a None check
(``prof = self.profile`` / ``if prof is not None``), same as the
slo/adaptive/flight seams — a disarmed engine allocates nothing here.

Determinism: profile.py is in the sim-determinism lint family
(cess_tpu/analysis) — no wallclock, no entropy. Every timing is
measured by the CALLER (serve/ owns the clocks) and passed in as an
argument; observations, windows and transition logs are sequenced by
internal counters. Host timings ride snapshots for humans but are
EXCLUDED from :meth:`ProfilePlane.witness` — exactly flight's
``over-objective`` carve-out — so two same-seed replays whose wall
timings differ (but stay on the same side of the decisive guard)
produce byte-identical witnesses (tests/test_profile.py).
"""
from __future__ import annotations

import collections
import glob
import json
import os
import re
import threading

from . import flight as _flight
from . import trace as _trace

_GIB = float(1 << 30)

STATES = ("ok", "regressed")

#: engine request class -> the bench metric its throughput is judged
#: against. The stream driver reports under the pseudo-class
#: ``stream``; everything unlisted is profiled but not watched.
TRACKED_DEFAULT = {
    "encode": "rs_4p8_encode_GiBps_per_chip",
    "stream": "stream_encode_tag_GiBps",
}

_ROUND_RE = re.compile(r"BENCH_r0*(\d+)\.json$")


# -- baseline loading --------------------------------------------------------

def _rows_of(text: str) -> dict:
    """``{metric: value}`` from bench.py JSONL output (one JSON object
    per line; non-JSON lines and rows without a finite value skipped —
    a truncated tail must not wedge the watchdog)."""
    out: dict = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            row = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(row, dict) or "metric" not in row:
            continue
        try:
            val = float(row.get("value"))
        except (TypeError, ValueError):
            continue
        if val == val:                          # NaN never baselines
            out[str(row["metric"])] = val
    return out


def parse_bench_record(path: str) -> dict:
    """``{metric: value}`` from one bench record — either the round
    wrapper ``{"n":..,"cmd":..,"rc":..,"tail": "<JSONL>"}`` the repo
    checks in as ``BENCH_r*.json``, or raw bench.py JSONL."""
    with open(path) as f:
        text = f.read()
    try:
        payload = json.loads(text)
    except ValueError:
        payload = None
    if isinstance(payload, dict) and isinstance(payload.get("tail"), str):
        return _rows_of(payload["tail"])
    return _rows_of(text)


def load_baseline(path: str) -> dict:
    """``{metric: value}`` from a ``bench_diff --baseline-out``
    artifact (``{"source":.., "round":.., "metrics": {m: {"value":
    v, ...}}}``). Raises ValueError when the file is not one."""
    with open(path) as f:
        payload = json.load(f)
    metrics = payload.get("metrics") if isinstance(payload, dict) else None
    if not isinstance(metrics, dict):
        raise ValueError(f"{path}: not a bench baseline artifact")
    out: dict = {}
    for name in sorted(metrics):
        entry = metrics[name]
        val = entry.get("value") if isinstance(entry, dict) else entry
        out[str(name)] = float(val)
    return out


def latest_bench_baseline(root: str = ".") -> dict:
    """``{metric: value}`` from the newest-round ``BENCH_r*.json``
    under ``root`` (the watchdog's default anchor). ``{}`` when the
    directory holds no bench records — an unanchored watchdog stays
    inert rather than guessing."""
    best, best_rnd = None, -1
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m and int(m.group(1)) > best_rnd:
            best_rnd, best = int(m.group(1)), path
    return {} if best is None else parse_bench_record(best)


# -- stage-level accounting --------------------------------------------------

def _new_account() -> dict:
    return {"batches": 0, "requests": 0, "rows": 0, "padded_rows": 0,
            "bytes": 0, "queue_s": 0.0, "h2d_s": 0.0, "dispatch_s": 0.0,
            "sync_s": 0.0}


class OpProfiler:
    """Per-(class, bucket-shape, device) dispatch accounting.

    One account per distinct (request class, bucket row count, device
    lane) triple: batch/request/row/byte counters plus the host-side
    stage breakdown the caller measured (queue-wait, h2d copy,
    dispatch, sync). A per-class deque of the last ``window``
    (bytes, busy-seconds) observations backs the windowed-throughput
    gauge. Counters are replay-deterministic and form the ops third
    of the witness; the ``*_s`` stage sums are host timings and stay
    out of it.
    """

    def __init__(self, *, window: int = 8):
        if window < 1:
            raise ValueError("window must be >= 1")
        self._mu = threading.Lock()
        self._window = window
        self._seq = 0
        self._accounts: dict = {}       # (cls, bucket, device) -> account
        self._recent: dict = {}         # cls -> deque[(bytes, busy_s)]

    def observe(self, cls: str, bucket: int, device: int, *,
                rows: int = 0, padded: int = 0, requests: int = 0,
                nbytes: int = 0, queue_s: float = 0.0,
                h2d_s: float = 0.0, dispatch_s: float = 0.0,
                sync_s: float = 0.0) -> int:
        """Record one dispatch; returns the observation sequence
        number. All timings were measured by the caller."""
        key = (str(cls), int(bucket), int(device))
        with self._mu:
            self._seq += 1
            acct = self._accounts.get(key)
            if acct is None:
                acct = self._accounts[key] = _new_account()
            acct["batches"] += 1
            acct["requests"] += int(requests)
            acct["rows"] += int(rows)
            acct["padded_rows"] += int(padded)
            acct["bytes"] += int(nbytes)
            acct["queue_s"] += float(queue_s)
            acct["h2d_s"] += float(h2d_s)
            acct["dispatch_s"] += float(dispatch_s)
            acct["sync_s"] += float(sync_s)
            recent = self._recent.get(key[0])
            if recent is None:
                recent = self._recent[key[0]] = collections.deque(
                    maxlen=self._window)
            recent.append((int(nbytes),
                           float(h2d_s) + float(dispatch_s)
                           + float(sync_s)))
            return self._seq

    def observations(self) -> int:
        with self._mu:
            return self._seq

    def windowed_gibps(self) -> dict:
        """``{cls: GiB/s over the last window}`` (None while a class's
        busy time is still zero) — the live gauge, not the witness."""
        with self._mu:
            out = {}
            for cls in sorted(self._recent):
                nbytes = sum(b for b, _ in self._recent[cls])
                busy = sum(s for _, s in self._recent[cls])
                out[cls] = None if busy <= 0.0 \
                    else round(nbytes / _GIB / busy, 6)
            return out

    def snapshot(self) -> dict:
        with self._mu:
            accounts = []
            for key in sorted(self._accounts):
                cls, bucket, device = key
                acct = self._accounts[key]
                entry = {"cls": cls, "bucket": bucket, "device": device}
                for field in ("batches", "requests", "rows",
                              "padded_rows", "bytes"):
                    entry[field] = acct[field]
                for field in ("queue_s", "h2d_s", "dispatch_s",
                              "sync_s"):
                    entry[field] = round(acct[field], 6)
                accounts.append(entry)
            snap = {"observations": self._seq, "window": self._window,
                    "accounts": accounts}
        snap["windowed_GiBps"] = self.windowed_gibps()
        return snap

    def canon(self) -> dict:
        """Replay-deterministic view: counters only, every host
        timing excluded."""
        with self._mu:
            return {
                "observations": self._seq,
                "accounts": {
                    f"{cls}|{bucket}|d{device}": {
                        field: self._accounts[(cls, bucket, device)][field]
                        for field in ("batches", "requests", "rows",
                                      "padded_rows", "bytes")}
                    for cls, bucket, device in sorted(self._accounts)},
            }


class PadLedger:
    """Ranked padded-row accounts per class×bucket, split by source.

    The engine's bucket coalescing (``engine``) and the stream
    driver's ragged tails (``stream``) feed the SAME ledger, so
    ``total()`` is the end-to-end pad bill. Fully count-based —
    the ledger is entirely inside the witness.
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._accounts: dict = {}       # (cls, bucket) -> account

    def add(self, cls: str, bucket: int, served: int, padded: int, *,
            source: str = "engine") -> None:
        key = (str(cls), int(bucket))
        with self._mu:
            acct = self._accounts.get(key)
            if acct is None:
                acct = self._accounts[key] = {
                    "batches": 0, "served": 0, "padded": 0,
                    "sources": {}}
            acct["batches"] += 1
            acct["served"] += int(served)
            acct["padded"] += int(padded)
            src = str(source)
            acct["sources"][src] = acct["sources"].get(src, 0) \
                + int(padded)

    def ranked(self) -> tuple:
        """((cls, bucket, account), ...) worst pad bill first; ties
        break on the key so the ranking replays bit-identically."""
        with self._mu:
            items = [(cls, bucket, dict(acct, sources=dict(
                acct["sources"])))
                for (cls, bucket), acct in self._accounts.items()]
        items.sort(key=lambda it: (-it[2]["padded"], it[0], it[1]))
        return tuple(items)

    def total(self) -> dict:
        """End-to-end pad bill: served/padded row totals plus the
        per-source padded split."""
        with self._mu:
            out = {"served": 0, "padded": 0, "sources": {}}
            for acct in self._accounts.values():
                out["served"] += acct["served"]
                out["padded"] += acct["padded"]
                for src, n in acct["sources"].items():
                    out["sources"][src] = out["sources"].get(src, 0) + n
            return out

    def snapshot(self) -> dict:
        ranked = self.ranked()
        return {
            "total": self.total(),
            "ranked": [{"cls": cls, "bucket": bucket, **acct}
                       for cls, bucket, acct in ranked],
        }

    def canon(self) -> dict:
        with self._mu:
            return {f"{cls}|{bucket}": {
                "batches": acct["batches"], "served": acct["served"],
                "padded": acct["padded"],
                "sources": dict(sorted(acct["sources"].items()))}
                for (cls, bucket), acct in sorted(self._accounts.items())}


def _keystr(key) -> str:
    """Canonical text for a program-cache key (nested tuples of
    strs/ints/bools/bytes) — stable across replays, JSON-safe."""
    if isinstance(key, (tuple, list)):
        return "(" + ",".join(_keystr(k) for k in key) + ")"
    if isinstance(key, bytes):
        return key.hex()
    if isinstance(key, str):
        return key
    return repr(key)


class CompileLedger:
    """Program-cache compile events: canonicalized shape keys, build
    counts, compile wall time. Build counts replay identically (cache
    behavior is deterministic) and go in the witness; wall times are
    host timings and do not."""

    def __init__(self, *, max_events: int = 256):
        self._mu = threading.Lock()
        self._seq = 0
        self._accounts: dict = {}       # keystr -> {builds, wall_s}
        self._events: collections.deque = collections.deque(
            maxlen=max_events)

    def record(self, key, wall_s: float) -> None:
        ks = _keystr(key)
        with self._mu:
            self._seq += 1
            acct = self._accounts.get(ks)
            if acct is None:
                acct = self._accounts[ks] = {"builds": 0, "wall_s": 0.0}
            acct["builds"] += 1
            acct["wall_s"] += float(wall_s)
            self._events.append((self._seq, ks, round(float(wall_s), 6)))

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "builds": self._seq,
                "programs": {ks: {"builds": acct["builds"],
                                  "wall_s": round(acct["wall_s"], 6)}
                             for ks, acct in sorted(
                                 self._accounts.items())},
                "events": list(self._events),
            }

    def canon(self) -> dict:
        with self._mu:
            return {"builds": self._seq,
                    "programs": {ks: self._accounts[ks]["builds"]
                                 for ks in sorted(self._accounts)}}


# -- the watchdog ------------------------------------------------------------

class PerfWatchdog:
    """Bench-anchored regression watchdog.

    Per tracked metric, (bytes, busy-seconds) accumulate over
    observation-COUNT windows; when a window closes, its GiB/s is
    compared against ``guard`` × the bench baseline and the metric's
    ok↔regressed state machine steps EDGE-TRIGGERED — a persistent
    regression yields one transition, not one per window.

    Transitions append ``(seq, metric, from, to, window)`` to a
    bounded deterministic log and announce exactly like FleetBoard's:
    enqueued under the same ``_mu`` hold that recorded them,
    delivered FIFO under ``_announce_mu`` OUTSIDE the watchdog lock —
    a ``perf.regression`` span on the armed tracer, a ``("perf",
    "regression")`` flight note (the ``perf-regression`` incident
    trigger), then listener callbacks. The log carries counts only:
    the measured GiB/s is a host timing and never enters the witness.
    """

    def __init__(self, baseline: dict, *, guard: float = 0.5,
                 window: int = 8, max_transitions: int = 256):
        if not 0.0 < guard <= 1.0:
            raise ValueError("guard must be in (0, 1]")
        if window < 1:
            raise ValueError("window must be >= 1")
        if max_transitions < 1:
            raise ValueError("max_transitions must be >= 1")
        self._mu = threading.Lock()
        self._guard = float(guard)
        self._window = int(window)
        self._baseline = {str(k): float(v)
                          for k, v in sorted(dict(baseline).items())}
        self._seq = 0
        self._acc: dict = {}            # metric -> {n, bytes, secs}
        self._windows: dict = {}        # metric -> closed-window count
        self._state: dict = {}          # metric -> "ok" | "regressed"
        self._last: dict = {}           # metric -> last window GiB/s
        self._regressions = 0
        self._transitions: collections.deque = collections.deque(
            maxlen=max_transitions)
        self._listeners: list = []
        # same serialization contract as FleetBoard: FIFO delivery,
        # whichever thread holds the announce lock drains everything
        self._announce_mu = threading.RLock()
        self._pending_announce: collections.deque = collections.deque()

    def add_listener(self, fn) -> None:
        """Register ``fn(metric, old, new, window)`` — called on
        every transition, outside the watchdog lock."""
        with self._mu:
            self._listeners.append(fn)

    def observe(self, metric: str, nbytes: int, busy_s: float) -> None:
        """Fold one observation into ``metric``'s open window. A
        metric with no baseline is ignored — the watchdog only judges
        what the bench record anchors."""
        metric = str(metric)
        base = self._baseline.get(metric)
        if base is None:
            return
        fired = False
        with self._mu:
            self._seq += 1
            acc = self._acc.get(metric)
            if acc is None:
                acc = self._acc[metric] = {"n": 0, "bytes": 0,
                                           "secs": 0.0}
            acc["n"] += 1
            acc["bytes"] += int(nbytes)
            acc["secs"] += float(busy_s)
            if acc["n"] < self._window:
                return
            widx = self._windows[metric] = \
                self._windows.get(metric, 0) + 1
            value = None if acc["secs"] <= 0.0 \
                else acc["bytes"] / _GIB / acc["secs"]
            self._acc[metric] = {"n": 0, "bytes": 0, "secs": 0.0}
            self._last[metric] = value
            # zero busy time means the device never blocked: that is
            # "fast", not a regression
            new = "regressed" if value is not None \
                and value < self._guard * base else "ok"
            old = self._state.get(metric, "ok")
            if new != old:
                self._state[metric] = new
                if new == "regressed":
                    self._regressions += 1
                self._transitions.append(
                    (self._seq, metric, old, new, widx))
                self._pending_announce.append((metric, old, new, widx))
                fired = True
        if fired:
            self._drain_announcements()

    def _drain_announcements(self) -> None:
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending_announce:
                        return
                    item = self._pending_announce.popleft()
                self._announce(*item)

    def _announce(self, metric: str, old: str, new: str,
                  widx: int) -> None:
        # observable exactly like a fleet transition: a span on the
        # armed tracer (WHEN throughput collapsed, relative to faults
        # and breaker trips), a journal note (window index is
        # count-sequenced, so it is replay-canonical), a callback
        with _trace.span("perf.regression", sys="perf", metric=metric,
                         frm=old, to=new, window=widx):
            pass
        _flight.note("perf", "regression", metric=metric, frm=old,
                     to=new, window=widx)
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(metric, old, new, widx)

    # -- introspection -------------------------------------------------------
    def state(self, metric: str) -> str:
        with self._mu:
            return self._state.get(str(metric), "ok")

    def regressed(self) -> bool:
        with self._mu:
            return any(s == "regressed" for s in self._state.values())

    def transition_log(self) -> tuple:
        """(seq, metric, from, to, window) per transition, in firing
        order — the watchdog's share of the replay witness."""
        with self._mu:
            return tuple(self._transitions)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "guard": self._guard,
                "window": self._window,
                "observations": self._seq,
                "baseline": dict(self._baseline),
                "states": {m: self._state.get(m, "ok")
                           for m in sorted(self._baseline)},
                "windows": dict(sorted(self._windows.items())),
                "last_GiBps": {m: None if v is None else round(v, 6)
                               for m, v in sorted(self._last.items())},
                "regressions": self._regressions,
                "transitions": list(self._transitions),
            }

    def canon(self) -> dict:
        with self._mu:
            return {"observations": self._seq,
                    "windows": dict(sorted(self._windows.items())),
                    "transitions": list(self._transitions)}


# -- composition -------------------------------------------------------------

class ProfilePlane:
    """Everything above behind one seam.

    ``make_engine(..., profile=ProfilePlane(...))`` arms it: the
    engine feeds :meth:`on_batch` from ``_account_batch``, the stream
    driver feeds :meth:`on_stream`, the program cache feeds
    :meth:`compile_event`. Without a ``baseline`` the watchdog is
    None — profiling without judging is valid (a sim world has no
    hardware to hold to a bench number).
    """

    def __init__(self, *, baseline: dict | None = None,
                 guard: float = 0.5, window: int = 8,
                 tracked: dict | None = None):
        self.ops = OpProfiler(window=window)
        self.pads = PadLedger()
        self.compiles = CompileLedger()
        self.tracked = dict(TRACKED_DEFAULT if tracked is None
                            else tracked)
        self.watchdog = None if not baseline else PerfWatchdog(
            baseline, guard=guard, window=window)

    # -- feeds (each a single seam the serve layer None-checks) --------------
    def on_batch(self, cls: str, bucket: int, device: int, *,
                 rows: int, padded: int, requests: int = 1,
                 nbytes: int = 0, queue_s: float = 0.0,
                 dispatch_s: float = 0.0, sync_s: float = 0.0) -> None:
        """One engine dispatch: ``bucket`` is the padded device row
        count, ``rows`` the real rows served, timings measured by the
        engine."""
        cls = str(cls)
        self.ops.observe(cls, bucket, device, rows=rows, padded=padded,
                         requests=requests, nbytes=nbytes,
                         queue_s=queue_s, dispatch_s=dispatch_s,
                         sync_s=sync_s)
        self.pads.add(cls, bucket, rows, padded, source="engine")
        wd = self.watchdog
        if wd is not None:
            metric = self.tracked.get(cls)
            if metric is not None:
                wd.observe(metric, nbytes, dispatch_s + sync_s)

    def on_stream(self, *, batch: int, rows: int, nbytes: int = 0,
                  device: int = 0, h2d_s: float = 0.0,
                  dispatch_s: float = 0.0) -> None:
        """One StreamingIngest drive step: ``batch`` segments
        submitted of which ``rows`` are real (the rest is the ragged
        tail's padding) — the stream side of the unified pad bill."""
        padded = max(int(batch) - int(rows), 0)
        self.ops.observe("stream", batch, device, rows=rows,
                         padded=padded, requests=1, nbytes=nbytes,
                         h2d_s=h2d_s, dispatch_s=dispatch_s)
        self.pads.add("stream", batch, rows, padded, source="stream")
        wd = self.watchdog
        if wd is not None:
            metric = self.tracked.get("stream")
            if metric is not None:
                wd.observe(metric, nbytes, h2d_s + dispatch_s)

    def compile_event(self, key, wall_s: float) -> None:
        """One program-cache build (a cache MISS — hits never get
        here); ``wall_s`` measured by the cache."""
        self.compiles.record(key, wall_s)

    # -- surfaces ------------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``cess_profileDump`` payload: everything, host timings
        included (they are for humans; the witness excludes them)."""
        wd = self.watchdog
        return {
            "ops": self.ops.snapshot(),
            "pads": self.pads.snapshot(),
            "compiles": self.compiles.snapshot(),
            "tracked": dict(sorted(self.tracked.items())),
            "watchdog": None if wd is None else wd.snapshot(),
        }

    def ledgers(self) -> dict:
        """The two ledgers an incident bundle embeds."""
        return {"pads": self.pads.snapshot(),
                "compiles": self.compiles.snapshot()}

    def metrics(self) -> dict:
        """Flat ``cess_profile_*`` gauges for GET /metrics."""
        pads = self.pads.total()
        compiles = self.compiles.canon()
        out = {
            "cess_profile_observations": self.ops.observations(),
            "cess_profile_served_rows_total": pads["served"],
            "cess_profile_pad_rows_total": pads["padded"],
            "cess_profile_compile_builds": compiles["builds"],
        }
        for src in sorted(pads["sources"]):
            out[f"cess_profile_pad_rows_{src}"] = pads["sources"][src]
        wd = self.watchdog
        out["cess_profile_watchdog_armed"] = 0 if wd is None else 1
        if wd is not None:
            snap = wd.snapshot()
            out["cess_profile_regressions_total"] = snap["regressions"]
            out["cess_profile_regressed"] = sum(
                1 for s in snap["states"].values() if s == "regressed")
        return out

    def witness(self) -> bytes:
        """Canonical bytes of the replay-deterministic view: counter
        accounts, the full pad ledger, compile build counts and the
        watchdog transition log — every host timing excluded. Two
        same-seed runs must agree byte-for-byte."""
        wd = self.watchdog
        canon = {
            "ops": self.ops.canon(),
            "pads": self.pads.canon(),
            "compiles": self.compiles.canon(),
            "watchdog": None if wd is None else wd.canon(),
        }
        return json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()
