"""Prometheus histogram primitives for the /metrics exposition.

The engine and stream stats export latency *percentiles* as gauges —
fine for a glance, wrong for aggregation (you cannot average p99s
across nodes or scrape intervals). A real Prometheus histogram is the
mergeable form: fixed bucket bounds, cumulative ``_bucket{le=...}``
counts, ``_sum`` and ``_count`` — the server derives any quantile over
any window. This module provides the counter (:class:`Histogram`) and
the text-exposition renderer (:func:`render_histogram`);
``node/metrics.py`` emits the families beside the existing gauges with
correct ``# TYPE ... histogram`` declarations.

Thread note: observations come from the engine batcher and stream
driver threads while the RPC thread renders — every access goes
through the histogram's own lock, and rendering works from one
consistent snapshot so the cumulative-bucket invariant (nondecreasing,
``+Inf`` == ``_count``) holds in every scrape (tests/test_metrics.py).
"""
from __future__ import annotations

import bisect
import math
import threading

# engine/stream latency bounds (seconds): sub-ms device dispatches up
# through multi-second degraded/backpressure tails
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Fixed-bound histogram: ``observe`` is O(log buckets), snapshots
    are consistent (taken under the lock), and same-bound histograms
    merge (the engine sums per-driver stream histograms into one
    exposition family)."""

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_mu")

    def __init__(self, bounds=LATENCY_BUCKETS_S):
        bs = tuple(float(b) for b in bounds)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])) \
                or not all(math.isfinite(b) for b in bs):
            raise ValueError(f"bucket bounds must be finite and "
                             f"strictly increasing, got {bounds!r}")
        self.bounds = bs
        self._counts = [0] * (len(bs) + 1)   # last = above every bound
        self._sum = 0.0
        self._count = 0
        self._mu = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        # Prometheus le is inclusive: first bound >= value
        i = bisect.bisect_left(self.bounds, value)
        with self._mu:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def merge(self, other: "Histogram") -> "Histogram":
        """Add ``other``'s observations into this histogram (bounds
        must match exactly)."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different "
                             f"bounds: {self.bounds} vs {other.bounds}")
        with other._mu:
            counts = list(other._counts)
            total_sum, total_n = other._sum, other._count
        with self._mu:
            for i, n in enumerate(counts):
                self._counts[i] += n
            self._sum += total_sum
            self._count += total_n
        return self

    @property
    def count(self) -> int:
        with self._mu:
            return self._count

    @classmethod
    def from_cumulative(cls, buckets, total_sum: float) -> "Histogram":
        """Rebuild a histogram from its wire form — the CUMULATIVE
        ``[(le_bound, count_le)...]`` list :meth:`snapshot` produces
        (and a federator parses back out of ``_bucket{le=...}``
        samples). The last entry must be the ``+Inf`` bucket; counts
        must be nondecreasing. Inverse of :meth:`snapshot`, so
        cross-node federation can reuse :meth:`merge`."""
        pairs = [(float(b), int(n)) for b, n in buckets]
        if len(pairs) < 2 or not math.isinf(pairs[-1][0]):
            raise ValueError("cumulative buckets must end with +Inf")
        if any(n2 < n1 for (_, n1), (_, n2) in zip(pairs, pairs[1:])):
            raise ValueError("cumulative bucket counts must be "
                             "nondecreasing")
        h = cls(tuple(b for b, _ in pairs[:-1]))
        prev = 0
        with h._mu:
            for i, (_, acc) in enumerate(pairs):
                h._counts[i] = acc - prev
                prev = acc
            h._count = pairs[-1][1]
            h._sum = float(total_sum)
        return h

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 <= q <= 1) by linear
        interpolation inside the owning bucket — the same estimate
        ``histogram_quantile`` computes server-side, so a FleetBoard
        reading a federated histogram agrees with the dashboards.
        Observations above the last finite bound clamp to that bound
        (the +Inf bucket has no width to interpolate over); an empty
        histogram reports 0.0."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q!r}")
        snap = self.snapshot()
        total = snap["count"]
        if total == 0:
            return 0.0
        target = q * total
        lo, prev_acc = 0.0, 0
        for bound, acc in snap["buckets"]:
            if acc >= target and acc > prev_acc:
                if math.isinf(bound):
                    return lo
                frac = (target - prev_acc) / (acc - prev_acc)
                return lo + (bound - lo) * frac
            if not math.isinf(bound):
                lo = bound
            prev_acc = acc
        return lo

    def snapshot(self) -> dict:
        """One consistent view: ``buckets`` is the CUMULATIVE
        ``[(le_bound, count_le)...]`` list ending with ``(inf, count)``
        — exactly the wire semantics of ``_bucket{le=...}``."""
        with self._mu:
            counts = list(self._counts)
            total_sum, total_n = self._sum, self._count
        buckets, acc = [], 0
        for bound, n in zip(self.bounds, counts):
            acc += n
            buckets.append((bound, acc))
        buckets.append((math.inf, acc + counts[-1]))
        return {"buckets": buckets, "sum": total_sum, "count": total_n}


def counter_delta(prev: float, cur: float) -> float:
    """The increment between two scrapes of a MONOTONIC counter,
    clamped for restarts: a counter can only move backwards because
    the process restarted and began again at zero, so the true
    increment since the previous scrape is at least ``cur`` (what
    accumulated after the restart) — never the negative difference a
    naive ``cur - prev`` would report. This is the federation-side
    half of Prometheus's ``rate()`` reset handling."""
    prev, cur = float(prev), float(cur)
    if cur >= prev:
        return cur - prev
    return cur


def format_le(bound: float) -> str:
    """Prometheus ``le`` label value: ``+Inf`` for the overflow
    bucket, shortest exact decimal otherwise."""
    if math.isinf(bound):
        return "+Inf"
    return f"{bound:g}"


def escape_label(value: str) -> str:
    """Exposition-format label-value escaping (format 0.0.4): inside
    the double quotes, backslash, double-quote and newline must be
    escaped — tenant names are caller-supplied strings, and an
    unescaped ``"`` would truncate the label and corrupt every sample
    after it on the scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_labels(labels: dict | None) -> str:
    """``{k="v",...}`` with escaped values (sorted: deterministic
    exposition), or ``""`` for an unlabeled sample."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render_histogram(name: str, hist: Histogram,
                     labels: dict | None = None,
                     type_line: bool = True) -> list[str]:
    """Text-exposition lines for one histogram family: the TYPE
    declaration, cumulative buckets, ``_sum`` and ``_count``.

    labels: extra labels on every sample (the per-tenant families —
    ``le`` is merged in on the bucket lines). type_line=False skips
    the ``# TYPE`` declaration: a labeled family renders one label-set
    per call, but the exposition format allows exactly ONE TYPE line
    per family, so the caller emits it for the first set only."""
    snap = hist.snapshot()
    base = dict(labels or {})
    lines = [f"# TYPE {name} histogram"] if type_line else []
    for bound, n in snap["buckets"]:
        lines.append(f"{name}_bucket"
                     f"{format_labels({**base, 'le': format_le(bound)})}"
                     f" {n}")
    tail = format_labels(base)
    lines.append(f"{name}_sum{tail} {snap['sum']}")
    lines.append(f"{name}_count{tail} {snap['count']}")
    return lines
