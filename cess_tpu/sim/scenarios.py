"""The scenario library: chaos campaigns as DATA, not code.

A :class:`Scenario` is a frozen description — world shape, a timeline
of ``(round, action, *args)`` rows, seeded fault sites, SLO targets
and the invariant checkers to run every virtual round. The
interpreter (:func:`run_scenario`) is the only code; adding a
scenario means adding a row to :data:`SCENARIOS`, and the replay
tests automatically cover it (every scenario must produce
bit-identical witnesses for two same-seed runs).

Timeline actions refer to nodes by ROLE, not index — ``"miner:1"``
resolves to miner m1's seed-drawn home node, ``"validator:2"`` to
node 2, ``"tail:0"`` to the last node (the dormant-spare convention
for join actions) — so one scenario runs unchanged at 40, 100 or
1000 nodes.

The witness (:meth:`SimReport.witness`) bundles the event queue's
fired log, every alive node's finalized prefix, the SLO board's
transition log, the fault plan's fired log, and — when armed — the
fleet plane's and chain watch's witnesses: independent deterministic
streams that must ALL match across same-seed replays.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib

from ..obs import flight as _flight
from ..obs import trace
from ..obs.incident import IncidentReporter
from ..obs.slo import SloBoard, SloTarget
from ..resilience import faults as _faults
from .invariants import InvariantViolation, run_checks
from .world import StorageProfile, World

# seeded baseline pin fraction for scenario runs: 1/16 of healthy
# round traces retained alongside every anomalous one
_BASELINE_RATE = 0.0625


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One chaos campaign, fully declarative.

    - ``world``: ``(key, value)`` pairs of :class:`World` kwargs; the
      ``"storage"`` value is itself ``(key, value)`` pairs for
      :class:`StorageProfile`; ``"dormant_tail"`` reserves that many
      trailing nodes as offline spares for ``join`` actions.
    - ``timeline``: ``(round, action, *args)`` rows, applied at the
      START of their round, in order.
    - ``faults``: ``(site, rate, kind)`` rows armed as one seeded
      :class:`~cess_tpu.resilience.faults.FaultPlan` on the world's
      virtual clock.
    - ``slo``: ``(cls, p99_s)`` targets for the round's board.
    - ``checks`` run after EVERY round; ``final_checks`` once at the
      end (convergence properties that only hold after healing).
    - ``pool``: route the world's gateway encodes/tags through a
      device-pool submission engine (serve/pool.py) for the run, so
      chaos campaigns exercise the real multi-lane serving plane;
      the pool snapshot rides :attr:`SimReport.pool` and lane
      breaker trips land in the armed flight recorder's journal.
    - ``fleet``: arm a :class:`~cess_tpu.obs.fleet.FleetPlane` as
      ``world.fleet`` and run one count-sequenced fleet scrape round
      per virtual round: every alive node contributes a head-lag
      derived SLO state + straggler sample, and every
      :data:`_FLEET_FEDERATE_EVERY`-th round its full /metrics
      exposition. The plane rides :attr:`SimReport.fleet` and its
      witness joins :meth:`SimReport.witness` as the fifth stream.
    - ``profile``: arm a :class:`~cess_tpu.obs.profile.ProfilePlane`
      on the ``pool`` engine (requires ``pool=True`` — the plane
      accounts engine dispatches), so chaos campaigns leave the
      per-shape stage breakdowns and the unified pad ledger behind;
      the snapshot rides :attr:`SimReport.profile`. Unanchored (no
      bench baseline inside a sim world), so the watchdog stays
      inert — profiling without judging.
    - ``chainwatch``: arm a
      :class:`~cess_tpu.obs.chainwatch.ChainWatch` as
      ``world.chainwatch`` and run one chain scan per virtual round:
      every alive node's consensus state (head/finalized/forks/vote
      locks/claimed blocks) plus the market ledger from the lowest
      alive node's runtime. The anomaly detector's triggers land in
      the armed incident reporter (the bundle embeds the chain
      snapshot), per-node finality lag folds into an armed ``fleet``
      plane (SLO class via :func:`_fleet_scrape`, straggler samples
      at seal), and the watch's witness joins
      :meth:`SimReport.witness` as the sixth stream.
    - ``remediate``: arm a
      :class:`~cess_tpu.serve.remediate.RemediationPlane` as
      ``world.remediation``: it listens on the run's flight recorder,
      binds the ``pool`` engine / miners / lowest node as action
      seams, and ticks once per virtual round AFTER the scrapes (so
      the round's detector edges are decided in the same round and
      the ``remediation-*`` checkers see post-decision state). Its
      action-journal witness joins :meth:`SimReport.witness` as the
      seventh stream.
    - ``custody``: arm a
      :class:`~cess_tpu.obs.custody.CustodyPlane` as ``world.custody``:
      its ledger fills continuously from the run recorder's
      ``("custody", ...)`` lineage notes (gateway dispatch, miner
      transfer, TEE verdict, repair completion), and one
      :func:`_custody_scrape` per virtual round feeds holder
      liveness + the open restoral-order set, cross-checks the
      MarketWatch when ``chainwatch`` rides too, and seals the
      erasure-margin fold (the at-risk/lost detector edges land in
      the armed incident reporter — the bundle embeds the segment's
      full custody timeline). With ``remediate`` the plane also
      binds as the remediation plane's repair-target feed
      (``bind_custody``), closing the proactive-repair loop. Its
      witness joins :meth:`SimReport.witness` as the eighth stream.
    """

    name: str
    rounds: int
    world: tuple = ()
    timeline: tuple = ()
    faults: tuple = ()
    slo: tuple = (("round", 4.0), ("upload", 4.0))
    checks: tuple = ("finalized-prefix", "vote-locks")
    final_checks: tuple = ()
    # False = no engine; True = pool over all visible devices; an int
    # caps the lane count (make_engine(pool=N))
    pool: bool | int = False
    fleet: bool = False
    profile: bool = False
    chainwatch: bool = False
    remediate: bool = False
    # with ``pool``: build the engine on the regenerating codec
    # (ops/regen.py, rs_backend="regen") so storm_repair rescuers run
    # symbol-mode repairs and the fold programs ride the lane caches
    regen: bool = False
    custody: bool = False


def resolve_ref(world: World, ref: str) -> int:
    """``"role:ordinal"`` -> node index (see module doc)."""
    kind, _, tail = ref.partition(":")
    k = int(tail)
    if kind in ("node", "validator"):
        return k
    if kind == "tail":
        return world.n - 1 - k
    if kind == "spare":
        # k-th plain node: not a validator, not a role home — safe to
        # churn without silently taking a miner/gateway/TEE down
        homes = set(getattr(world, "role_homes", {}).values())
        spares = [i for i in range(world.n_validators, world.n)
                  if i not in homes]
        return spares[k]
    name = {"miner": f"m{k}", "gateway": f"gw{k}", "tee": "tee0"}[kind]
    return world.role_homes[name]


def _seeded_blob(seed: bytes, label: str, size: int) -> bytes:
    """Deterministic file contents from a SHA-256 stream."""
    out = bytearray()
    n = 0
    while len(out) < size:
        out += hashlib.sha256(b"cess-sim-blob:" + seed + b"|"
                              + label.encode() + b"|"
                              + n.to_bytes(4, "little")).digest()
        n += 1
    return bytes(out[:size])


@dataclasses.dataclass
class _Upload:
    round: int
    owner: str
    gw: object
    calc_sent: bool = False


@dataclasses.dataclass
class SimReport:
    """What a scenario run leaves behind: the world (for further
    inspection) and the four witness streams."""

    scenario: str
    seed: bytes
    world: World
    board: SloBoard
    plan: "_faults.FaultPlan | None"
    rounds_run: int
    uploads_active: int
    # the flight-recorder layer (ISSUE 9): the run's FlightRecorder
    # (pinned traces + journal) and its IncidentReporter (bundles) —
    # reporter.witness() is the postmortem determinism contract,
    # separate from the four run streams below
    recorder: "_flight.FlightRecorder | None" = None
    reporter: "IncidentReporter | None" = None
    # the device-pool serving plane (ISSUE 10): the pool's end-of-run
    # snapshot when the scenario ran ``pool=True`` — informational
    # (per-lane batch/requeue counters and breaker states), NOT part
    # of the witness: lane timing is wall-clock, outputs are
    # bit-identical to the single-device engine by construction
    pool: "dict | None" = None
    # the fleet observability plane (ISSUE 12): the run's FleetPlane
    # when the scenario ran ``fleet=True`` — its witness (federated
    # snapshot + FleetBoard transition log + stitched trace set) IS
    # part of the replay contract, as the fifth witness stream
    fleet: "object | None" = None
    # the continuous-profiling plane (ISSUE 13): the plane's
    # end-of-run snapshot when the scenario ran ``profile=True`` —
    # informational like ``pool`` (stage sums are wall-clock; the
    # plane's OWN witness() determinism contract is exercised
    # directly against the live engine in tests/test_profile.py)
    profile: "dict | None" = None
    # the chain-plane watch (ISSUE 14): the run's ChainWatch when the
    # scenario ran ``chainwatch=True`` — its witness (consensus views
    # + equivocation evidence + market ledger + anomaly transition
    # log) IS part of the replay contract, as the sixth witness stream
    chainwatch: "object | None" = None
    # the remediation plane (ISSUE 16): the run's RemediationPlane
    # when the scenario ran ``remediate=True`` — its action-journal
    # witness (same seed => byte-identical action log) IS part of the
    # replay contract, as the seventh witness stream
    remediation: "object | None" = None
    # the custody/durability plane (ISSUE 20): the run's CustodyPlane
    # when the scenario ran ``custody=True`` — its witness (flat
    # count-sequenced ledger log + sealed margins + detector
    # transitions) IS part of the replay contract, as the eighth
    # witness stream
    custody: "object | None" = None

    def witness(self) -> tuple:
        """Everything that must be bit-identical across two same-seed
        runs of the same scenario."""
        return (self.world.queue.fired_log(),
                self.world.finalized_prefix(),
                self.board.transition_log(),
                self.plan.fired_log() if self.plan is not None else (),
                self.fleet.witness() if self.fleet is not None else b"",
                self.chainwatch.witness()
                if self.chainwatch is not None else b"",
                self.remediation.witness()
                if self.remediation is not None else b"",
                self.custody.witness()
                if self.custody is not None else b"")


def _build_world(scenario: Scenario, seed, n_nodes: int | None) -> World:
    kwargs = dict(scenario.world)
    storage_pairs = kwargs.pop("storage", None)
    if storage_pairs is not None:
        kwargs["storage"] = StorageProfile(**dict(storage_pairs))
    if n_nodes is not None:
        kwargs["n_nodes"] = n_nodes
    n = kwargs.get("n_nodes", 100)
    tail = kwargs.pop("dormant_tail", 0)
    if tail:
        kwargs["dormant"] = tuple(range(n - tail, n))
    return World(seed, **kwargs)


def _drive_uploads(world: World, pending: dict, board: SloBoard,
                   rnd: int) -> int:
    """Advance in-flight uploads one lifecycle step per round (the
    scheduler's calculate_end fires via a root extrinsic, as in the
    live storage tests) and feed activation latency to the SLO board.
    Returns how many files went active this round."""
    active = 0
    for fh in sorted(pending):
        rec = pending[fh]
        f = rec.gw.node.runtime.file_bank.file(fh)
        if f is None:
            continue
        if f.state == "calculate" and not rec.calc_sent:
            rec.gw.node.submit_extrinsic("root", "file_bank.calculate_end",
                                         fh)
            rec.calc_sent = True
        elif f.state == "active":
            board.observe("upload", latency_s=float(rnd - rec.round + 1),
                          tenant=rec.owner)
            del pending[fh]
            active += 1
    return active


def _apply_action(world: World, pending: dict, rnd: int,
                  action: str, args: tuple) -> None:
    if action in ("crash", "leave", "restart", "join"):
        getattr(world, action)(resolve_ref(world, args[0]))
    elif action == "stripe":
        world.stripe_partition(args[0])
    elif action == "heal":
        world.heal()
    elif action == "upload":
        gw_ord, owner, size, count = (args + (1,))[:4]
        gw = world.gateways[gw_ord]
        for j in range(count):
            label = f"r{rnd}g{gw_ord}u{j}"
            data = _seeded_blob(world.seed, label, size)
            fh = gw.upload(owner, "photos", f"{label}.bin", data)
            pending[fh] = _Upload(round=rnd, owner=owner, gw=gw)
    elif action == "drop_fragment":
        # victim by fragment ROW of the first active file — the row ->
        # miner mapping is on-chain data, so the scenario stays valid
        # whatever the deal-assignment draw picked
        row = args[0]
        rt = world.gateways[0].node.runtime
        for (fh,), f in sorted(rt.state.iter_prefix("file_bank", "file")):
            if f.state != "active":
                continue
            agent = world.agents[f.miners[row]]
            frag = f.segments[0].fragment_hashes[row]
            if frag not in agent.store:
                continue
            del agent.store[frag]
            agent.tags.pop(frag, None)
            agent.node.submit_extrinsic(
                agent.account, "file_bank.generate_restoral_order",
                fh, frag)
            world.queue.mark(f"drop_fragment:{agent.account}")
            return
        raise LookupError(f"drop_fragment: no active file with a "
                          f"stored row-{row} fragment")
    elif action == "storm_kill":
        # mass miner failure: drop EVERY active-file fragment the
        # victim ordinals custody, open their restoral orders via the
        # (alive) gateway node, then crash the victims' home nodes —
        # the restoral market floods with concurrent orders at once
        start, count = args
        rt = world.gateways[0].node.runtime
        frag_file: dict[bytes, bytes] = {}
        for (fh,), f in sorted(rt.state.iter_prefix("file_bank", "file")):
            if f.state != "active":
                continue
            for seg in f.segments:
                for h in seg.fragment_hashes:
                    frag_file[h] = fh
        owner = {frag: acct for (acct, frag), _e
                 in rt.state.iter_prefix("file_bank", "frag_of_miner")}
        gw_node = world.gateways[0].node
        for j in range(start, start + count):
            victim = world.agents[f"m{j}"]
            dropped = 0
            for h in sorted(frag_file):
                if owner.get(h) != victim.account:
                    continue
                victim.store.pop(h, None)
                victim.tags.pop(h, None)
                gw_node.submit_extrinsic(
                    victim.account, "file_bank.generate_restoral_order",
                    frag_file[h], h)
                dropped += 1
            world.crash(world.role_homes[victim.account])
            world.queue.mark(f"storm_kill:{victim.account}:{dropped}")
    elif action == "storm_repair":
        # surviving miners fan the open orders across the pool engine:
        # first pass binds each alive rescuer to the scenario engine
        # (symbol mode when it carries the regenerating codec) and
        # warms the restoral patterns per lane, then every rescuer
        # sweeps the market — concurrent claims are the storm load
        eng = getattr(world.pipeline, "engine", None)
        repaired = 0
        for rescuer in world.miners:
            if not world.alive[world.role_homes[rescuer.account]]:
                continue
            if eng is not None and rescuer.engine is None:
                rescuer.attach_engine(eng)
                if hasattr(eng.codec, "fold_symbol"):
                    rescuer.set_repair_mode("symbols")
                rescuer.warm_restoral()
            rt = rescuer.node.runtime
            for (frag,), order in sorted(
                    rt.state.iter_prefix("file_bank", "restoral")):
                if order.miner or order.origin_miner == rescuer.account:
                    continue
                if rescuer.try_repair(frag, world.miners,
                                      world.gateways):
                    repaired += 1
        world.queue.mark(f"storm_repair:{repaired}")
    elif action == "repair_contend":
        # every OTHER miner sees the same open orders and races: all
        # reconstruct, all claim — the chain pays exactly ONE (the
        # restoral-single-winner invariant)
        repaired = 0
        for rescuer in world.miners:
            rt = rescuer.node.runtime
            for (frag,), order in sorted(
                    rt.state.iter_prefix("file_bank", "restoral")):
                if order.miner or order.origin_miner == rescuer.account:
                    continue         # claimed on this view / victim
                if rescuer.try_repair(frag, world.miners,
                                      world.gateways):
                    repaired += 1
        world.queue.mark(f"repair_contend:{repaired}")
    elif action == "attrition":
        # one seeded SILENT miner death (the durability drill's slow
        # attrition): the victim's fragments vanish and its home node
        # crashes, but — unlike storm_kill — nobody files restoral
        # orders. Detecting the decay is the custody plane's job (the
        # margin fold over holder liveness), and the proactive-repair
        # policy must file the orders itself. Victim drawn seeded from
        # the first active file's still-alive assigned miners
        rt = world.gateways[0].node.runtime
        holders: list[str] = []
        for (_fh,), f in sorted(rt.state.iter_prefix("file_bank", "file")):
            if f.state != "active":
                continue
            holders = sorted(set(f.miners))
            break
        alive_holders = [a for a in holders
                         if world.alive[world.role_homes[a]]]
        if not alive_holders:
            raise LookupError("attrition: no alive assigned miner "
                              "to kill")
        victim_acct = alive_holders[
            world.u64("attrition", rnd) % len(alive_holders)]
        victim = world.agents[victim_acct]
        dropped = len(victim.store)
        victim.store.clear()
        victim.tags.clear()
        world.crash(world.role_homes[victim_acct])
        world.queue.mark(f"attrition:{victim_acct}:{dropped}")
    elif action == "equivocate":
        _equivocate(world, args[0])
    elif action == "perf_edge":
        # scripted perf-watchdog edge: the live PerfWatchdog grades
        # HOST timings against a bench anchor, so a real edge inside a
        # sim world would be nondeterministic — the scenario scripts
        # the transition itself through the same journal note the
        # watchdog emits (obs/profile.py), and everything downstream
        # (incident trigger, remediation policy) reacts identically
        metric, to = args
        _flight.note("perf", "regression", metric=metric,
                     frm="regressed" if to == "ok" else "ok",
                     to=to, window=rnd)
        world.queue.mark(f"perf_edge:{metric}:{to}")
    else:
        raise ValueError(f"unknown scenario action {action!r}")


def _equivocate(world: World, ref: str) -> None:
    """A seeded double-signer. The slot claim signs (slot, author) but
    NOT the block contents, so re-issuing the same claim over
    different contents is exactly the BABE equivocation shape: forge
    a twin of the validator's newest unfinalized canonical block
    (mutated state root, same claim) and deliver it to every alive
    node. The twin's claim verifies, it lands as a side branch (equal
    weight — never adopted), and every chain watch now sees two
    distinct blocks signed by one author for one slot."""
    from ..node.network import Block

    want = f"v{resolve_ref(world, ref)}"
    src = next(i for i in range(world.n) if world.alive[i])
    node = world.nodes[src]
    header = None
    for h in reversed(node.chain):
        if h.claim is None or h.number <= node.finalized:
            continue
        header = h
        if h.author == want:
            break
    if header is None:
        raise LookupError(f"equivocate: no unfinalized canonical "
                          f"block to double-sign (finalized="
                          f"#{node.finalized})")
    twin = dataclasses.replace(
        header, state_root=hashlib.sha256(
            b"cess-sim-equivocation:" + header.state_root).digest())
    blk = Block(header=twin, extrinsics=())
    for i in range(world.n):
        if not world.alive[i]:
            continue
        try:
            world.nodes[i].import_block(blk)
        except ValueError:
            continue    # other partition / finalized past it: no view
    world.queue.mark(
        f"equivocate:{header.author}@{header.claim.slot}")


# every node's SLO state + straggler sample feeds the fleet plane
# each round; full /metrics expositions federate every N-th round
# (render_metrics walks runtime state, so scraping 100 nodes every
# round would dominate the run without observing anything new)
_FLEET_FEDERATE_EVERY = 4


def _fleet_scrape(world: World, plane, rnd: int) -> None:
    """One count-sequenced fleet scrape round over the world. Each
    alive node contributes a deterministic SLO snapshot derived from
    its head lag behind the best alive chain (lagging <=1 slot of
    chain is healthy, <=4 is warn, beyond burns — virtual-chain
    state, never host timing), and the same lag feeds its straggler
    window; every ``_FLEET_FEDERATE_EVERY``-th round the node's full
    /metrics exposition federates too. Crashed nodes skip the round —
    their last reported state stands, exactly like a silent peer."""
    from ..node.metrics import render_metrics

    heads = {i: world.nodes[i].chain[-1].number
             for i in range(world.n) if world.alive[i]}
    if not heads:
        return
    best = max(heads.values())
    federate = rnd % _FLEET_FEDERATE_EVERY == 0
    watch = world.chainwatch
    for i in sorted(heads):
        inst = f"n{i:03d}"
        lag = float(best - heads[i])
        state = "ok" if lag <= 1 else ("warn" if lag <= 4
                                       else "burning")
        targets = {"head": {"state": state}}
        if watch is not None:
            # chain-plane fold (obs/chainwatch.py): the node's
            # finality lag joins the same scrape as an SLO class, so
            # the FleetBoard's worst/quorum views flip when a quorum
            # of nodes stops finalizing — the sim-side analog of the
            # "chain" section riding live fleet gossip frames.
            # Graded against the BEST alive head (the head-lag
            # convention above): a stalled quorum keeps authoring
            # somewhere, so best - finalized grows for everyone
            from ..obs import chainwatch as _chainwatch

            flag = int(best) - world.nodes[i].finalized
            targets["finality_lag"] = {
                "state": _chainwatch.lag_state(flag), "lag": flag}
        plane.ingest(
            inst,
            exposition=render_metrics(world.nodes[i])
            if federate else None,
            slo={"targets": targets})
        plane.stragglers.observe(inst, "head_lag", lag)
    plane.seal_round()


def _chainwatch_scrape(world: World, watch, rnd: int) -> None:
    """One chain-plane scan round over the world (obs/chainwatch.py):
    every alive node contributes its consensus state (the same
    :func:`~cess_tpu.obs.chainwatch.node_state` dict a live node's
    gossip frame carries), the lowest alive node's runtime feeds the
    market ledger (chain state is replicated — one copy suffices),
    and the seal runs the anomaly detectors. Crashed nodes skip the
    scan — their last reported view stands, like a silent peer."""
    from ..obs import chainwatch as _chainwatch

    alive = [i for i in range(world.n) if world.alive[i]]
    if not alive:
        return
    for i in alive:
        watch.ingest_state(f"n{i:03d}",
                           _chainwatch.node_state(world.nodes[i]))
    watch.ingest_market(_chainwatch.market_state(
        world.nodes[alive[0]].runtime.state,
        fragment_size=watch.fragment_size))
    watch.seal_round()


def _custody_scrape(world: World, plane, rnd: int) -> None:
    """One custody observation round (obs/custody.py). The ledger
    itself fills continuously from the armed recorder's
    ``("custody", ...)`` lineage notes — this helper feeds only the
    per-round facts no seam carries: holder liveness from the world's
    role homes, the open restoral-order set from the (replicated)
    chain state of the lowest alive node, and the MarketWatch
    cross-check when a chain watch rides the same run. The seal folds
    the erasure margins and runs the at-risk/lost detectors, whose
    edges land in the armed incident reporter."""
    homes = getattr(world, "role_homes", {})
    plane.observe_alive({acct: bool(world.alive[idx])
                         for acct, idx in homes.items()})
    alive = [i for i in range(world.n) if world.alive[i]]
    if alive:
        st = world.nodes[alive[0]].runtime.state
        plane.observe_restorals(tuple(
            frag for (frag,), _o
            in sorted(st.iter_prefix("file_bank", "restoral"))))
    watch = world.chainwatch
    if watch is not None:
        plane.cross_check_market(watch.market.snapshot())
    plane.seal_round()


def _pool_engine(world: World, profile: bool = False,
                 regen: bool = False, lanes=True):
    """A device-pool submission engine matched to the world's storage
    pipeline: same RS geometry, same PoDR2 key (a mismatched key would
    tag with different secrets than the direct path), all visible
    devices (``lanes=N`` caps the pool width — the repair storm's
    per-lane AOT warm sweep scales with lane count, and a lane trip +
    sibling drain needs few lanes, not all of them), breakers enabled
    so lane faults trip and drain. With ``profile``, an unanchored
    ProfilePlane rides along (no bench baseline inside a sim world —
    ledgers fill, watchdog inert)."""
    from ..resilience import ResilienceConfig
    from ..serve import make_engine

    plane = None
    if profile:
        from ..obs.profile import ProfilePlane

        plane = ProfilePlane()
    pipe = world.pipeline
    return make_engine(pipe.config.k, pipe.config.m,
                       rs_backend="regen" if regen else "jax",
                       podr2_key=pipe.podr2_key,
                       resilience=ResilienceConfig(), pool=lanes,
                       profile=plane)


def run_scenario(scenario: Scenario, seed, *, n_nodes: int | None = None,
                 tracer=None, strict: bool = True,
                 flight=None) -> SimReport:
    """Build the world, arm faults + tracer + flight recorder,
    interpret the timeline, check invariants every round. Raises
    :class:`~cess_tpu.sim.invariants.InvariantViolation` on the first
    round whose checks fail (``strict=False`` collects instead); the
    raised exception carries ``.incidents`` (the bundles snapshotted
    before the unwind) and ``.reporter``.

    flight: an :class:`~cess_tpu.obs.flight.FlightRecorder` to arm for
    the run; default builds one seeded from the scenario seed (so
    retention replays bit-identically) with the scenario's SLO targets
    as pin objectives."""
    seed_b = seed if isinstance(seed, bytes) else str(seed).encode()
    if scenario.profile and not scenario.pool:
        raise ValueError("Scenario.profile=True requires pool=True "
                         "(the profile plane accounts engine "
                         "dispatches)")
    world = _build_world(scenario, seed_b, n_nodes)
    pool_snap: dict = {}
    profile_snap: dict = {}
    profile_plane = None
    # tiny windows: scenario rounds produce a handful of observations
    # per class, and the transition log must be able to flip on them
    board = SloBoard(tuple(SloTarget(cls, p99_s=p99)
                           for cls, p99 in scenario.slo),
                     fast_window=4, slow_window=16, eval_every=2)
    recorder = flight if flight is not None else _flight.FlightRecorder(
        seed_b, baseline_rate=_BASELINE_RATE,
        objectives=dict(scenario.slo))
    plan = None
    reporter = None
    fleet_plane = None
    chain_watch = None
    remediation = None
    custody_plane = None
    stack = contextlib.ExitStack()
    try:
        with stack:
            if scenario.faults:
                plan = _faults.FaultPlan.seeded(
                    seed_b, {site: (rate, kind)
                             for site, rate, kind in scenario.faults},
                    horizon=256, clock=world.clock)
                stack.enter_context(_faults.armed(plan))
            if tracer is not None:
                stack.enter_context(trace.armed(tracer))
                tracer.attach_flight(recorder)
                stack.callback(tracer.attach_flight, None)
            stack.enter_context(_flight.armed(recorder))
            if scenario.pool:
                # route the storage pipeline through a device-pool
                # engine for the run: gateway encode/tag submits place
                # across mesh lanes, faulted lanes drain to siblings,
                # and every breaker trip is journaled by the armed
                # recorder. Submits are synchronous from the single
                # sim thread, so placement (and the fault plan's
                # per-site ordinals) replay deterministically; the
                # snapshot is captured before the engine closes.
                eng = _pool_engine(world, profile=scenario.profile,
                                   regen=scenario.regen,
                                   lanes=scenario.pool)
                profile_plane = eng.profile
                stack.callback(eng.close)
                stack.callback(lambda: pool_snap.update(
                    eng.pool.snapshot()))
                if profile_plane is not None:
                    stack.callback(lambda: profile_snap.update(
                        profile_plane.snapshot()))
                stack.callback(setattr, world.pipeline, "engine", None)
                world.pipeline.engine = eng
            if scenario.fleet:
                # the fleet observability plane (obs/fleet.py): armed
                # as world.fleet so the fleet-consistency checker can
                # recompute its global views from the ingested
                # per-node states; one scrape round per virtual round
                from ..obs.fleet import FleetPlane

                fleet_plane = FleetPlane("sim")
                world.fleet = fleet_plane
            if scenario.chainwatch:
                # the chain-plane watch (obs/chainwatch.py): armed as
                # world.chainwatch; one scan + detector seal per
                # virtual round, folding per-node finality lag into
                # the fleet plane's straggler windows when one rides
                from ..constants import FRAGMENT_SIZE
                from ..obs.chainwatch import ChainWatch

                chain_watch = ChainWatch("sim",
                                         fragment_size=FRAGMENT_SIZE)
                if fleet_plane is not None:
                    chain_watch.attach_fleet(fleet_plane)
                world.chainwatch = chain_watch
            if scenario.custody:
                # the custody/durability plane (obs/custody.py): armed
                # as world.custody, its ledger fed by the recorder's
                # ("custody", ...) lineage notes; one scrape + margin
                # seal per virtual round (see _custody_scrape)
                from ..obs.custody import CustodyPlane

                custody_plane = CustodyPlane("sim")
                recorder.add_listener(custody_plane.on_note)
                world.custody = custody_plane
            if scenario.remediate:
                # the remediation plane (serve/remediate.py): armed as
                # world.remediation, fed by the run's flight recorder,
                # acting through whatever seams the scenario built —
                # the pool engine's breakers, the storage miners, the
                # lowest node's extrinsic surface, the custody plane's
                # repair targets
                from ..serve.remediate import RemediationPlane

                remediation = RemediationPlane(seed_b)
                if scenario.pool:
                    remediation.bind_engine(world.pipeline.engine)
                remediation.bind_miners(
                    getattr(world, "miners", ()) or ())
                remediation.bind_node(world.nodes[0])
                if custody_plane is not None:
                    remediation.bind_custody(custody_plane)
                recorder.add_listener(remediation.on_note)
                world.remediation = remediation
            # each bundle embeds the scenario identity + the live
            # witness streams — everything a replay needs
            reporter = IncidentReporter(
                recorder, board=board, plan=plan,
                stitcher=None if fleet_plane is None
                else fleet_plane.stitcher,
                profile=profile_plane,
                chainwatch=chain_watch,
                remediation=remediation,
                custody=custody_plane,
                context=lambda: {
                    "scenario": scenario.name,
                    "seed": seed_b.hex(),
                    "witness": (
                        world.queue.fired_log(),
                        world.finalized_prefix(),
                        board.transition_log(),
                        plan.fired_log() if plan is not None else ()),
                })
            pending: dict[bytes, _Upload] = {}
            active = 0
            for rnd in range(scenario.rounds):
                # one scenario round = ONE connected trace: actions,
                # authoring, gossip, agent reactions and invariant
                # checks all hang off this root span
                with trace.span("sim.round", sys="sim",
                                scenario=scenario.name, round=rnd):
                    for row in scenario.timeline:
                        if row[0] == rnd:
                            _apply_action(world, pending, rnd,
                                          row[1], tuple(row[2:]))
                    world.run_round()
                    active += _drive_uploads(world, pending, board, rnd)
                    board.observe("round",
                                  latency_s=float(world.last_round_slots))
                    if chain_watch is not None:
                        # scan BEFORE the fleet scrape: the watch's
                        # straggler fold must land in the same fleet
                        # round the plane seals below
                        _chainwatch_scrape(world, chain_watch, rnd)
                    if fleet_plane is not None:
                        _fleet_scrape(world, fleet_plane, rnd)
                    if custody_plane is not None:
                        # seal the margin fold BEFORE the remediation
                        # tick: an at-risk edge decided this round is
                        # acted on this round, and the custody-*
                        # checkers judge post-decision state
                        _custody_scrape(world, custody_plane, rnd)
                    if remediation is not None:
                        # decide + apply the round's detector edges
                        # BEFORE the checks: the remediation-*
                        # invariants judge post-decision state
                        remediation.tick()
                    run_checks(world, scenario.checks,
                               context=f"{scenario.name}:round{rnd}",
                               strict=strict)
            if fleet_plane is not None:
                # stitch the run's own evidence: the armed tracer's
                # ring (every sim.round trace) and the recorder's
                # pins — overlapping spans dedup by (trace, span) id
                if tracer is not None:
                    fleet_plane.stitcher.add_dump(
                        "sim", tracer.finished())
                fleet_plane.stitcher.add_pins("sim", recorder.pinned())
            run_checks(world, scenario.final_checks,
                       context=f"{scenario.name}:final", strict=strict)
    except InvariantViolation as e:
        # the bundle was built by the strict-raise's journal note
        # BEFORE the unwind; surface it on the exception so callers
        # (and pytest failure output) hold the postmortem directly
        e.reporter = reporter
        e.incidents = [] if reporter is None else reporter.bundles()
        raise
    return SimReport(scenario=scenario.name, seed=seed_b, world=world,
                     board=board, plan=plan, rounds_run=scenario.rounds,
                     uploads_active=active, recorder=recorder,
                     reporter=reporter, pool=pool_snap or None,
                     fleet=fleet_plane, profile=profile_snap or None,
                     chainwatch=chain_watch, remediation=remediation,
                     custody=custody_plane)


# -- the library --------------------------------------------------------------
SCENARIOS: dict[str, Scenario] = {
    # miners and plain nodes churn while a file upload is in flight;
    # lossy fragment transfers force the retry policy to earn its keep
    "miner_churn": Scenario(
        name="miner_churn", rounds=14,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 4),)),
               ("dormant_tail", 1)),
        timeline=(
            (1, "upload", 0, "alice", 20_000),
            (3, "crash", "miner:3"),
            (5, "restart", "miner:3"),
            (6, "join", "tail:0"),
            (8, "leave", "spare:0"),
            (10, "crash", "spare:1"),
            (12, "restart", "spare:1"),
        ),
        faults=(("offchain.fetch", 0.12, "drop"),),
        checks=("finalized-prefix", "vote-locks"),
        final_checks=("storage-convergence", "audit-soundness"),
    ),
    # the classic split-brain: stripe the world in two (validators
    # 4/3 — neither side can finalize), let both sides author, heal,
    # and demand one head + one state root at the end
    "partition_heal": Scenario(
        name="partition_heal", rounds=12,
        world=(("n_validators", 7),),
        timeline=(
            (4, "stripe", 2),
            (9, "heal",),
        ),
        checks=("finalized-prefix", "vote-locks"),
        final_checks=("heads-converged",),
    ),
    # miners m1/m2 store corrupted fragment bytes while reporting
    # clean transfers; the PoDR2 service audit must fail whichever the
    # deal assigned (the 3-row assignment always includes one of them)
    "adversarial_audit": Scenario(
        name="adversarial_audit", rounds=30,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 4),
                            ("adversarial_miners", (1, 2))))),
        timeline=(
            (1, "upload", 0, "alice", 20_000),
        ),
        checks=("finalized-prefix", "vote-locks"),
        final_checks=("audit-soundness",),
    ),
    # every tenant piles onto gateway 0 while gateway 1 idles: the
    # upload SLO breaches and recovers — the transition log is the
    # scenario's whole point
    "gateway_hotspot": Scenario(
        name="gateway_hotspot", rounds=14,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 4), ("n_gateways", 2)))),
        timeline=(
            (1, "upload", 0, "alice", 20_000, 2),
            (3, "upload", 0, "alice", 20_000, 2),
            (6, "upload", 1, "alice", 20_000),
        ),
        slo=(("round", 4.0), ("upload", 2.0)),
        checks=("finalized-prefix", "vote-locks"),
        final_checks=("storage-convergence",),
    ),
    # the hotspot again, served by the REAL multi-lane plane (ISSUE
    # 10): gateway encodes/tags route through a device-pool engine
    # while a seeded fault kills every dispatch on lane 0 — the lane's
    # breakers trip, work drains to siblings, uploads still activate
    # and storage still converges; the pool snapshot rides the report.
    # profile=True (ISSUE 13): the same run leaves the per-shape
    # stage breakdowns + unified pad ledger behind on SimReport
    "gateway_hotspot_pool": Scenario(
        name="gateway_hotspot_pool", rounds=14, pool=True, profile=True,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 4), ("n_gateways", 2)))),
        timeline=(
            (1, "upload", 0, "alice", 20_000, 2),
            (3, "upload", 0, "alice", 20_000, 2),
            (6, "upload", 1, "alice", 20_000),
        ),
        faults=(("engine.dispatch.d0", 1.0, "raise"),),
        slo=(("round", 4.0), ("upload", 2.0)),
        checks=("finalized-prefix", "vote-locks"),
        final_checks=("storage-convergence",),
    ),
    # the hotspot observed by the FLEET plane (ISSUE 12): every round
    # each alive node reports a head-lag SLO state + straggler sample
    # and periodically its full /metrics exposition; a 4-way stripe
    # partition mid-run makes lagging groups drift — the FleetBoard's
    # worst and quorum views both flip to warn and recover after the
    # heal, the MAD detector flags the laggards (fleet-outlier
    # incident bundles), the fleet-consistency checker re-derives the
    # global views from the ingested per-node states every round, and
    # the plane's witness joins the replay contract
    "gateway_hotspot_fleet": Scenario(
        name="gateway_hotspot_fleet", rounds=14, fleet=True,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 4), ("n_gateways", 2)))),
        timeline=(
            (1, "upload", 0, "alice", 20_000, 2),
            (3, "upload", 0, "alice", 20_000, 2),
            (4, "stripe", 4),
            (6, "upload", 1, "alice", 20_000),
            (9, "heal",),
        ),
        slo=(("round", 4.0), ("upload", 2.0)),
        checks=("finalized-prefix", "vote-locks", "fleet-consistency"),
        final_checks=("storage-convergence",),
    ),
    # the byzantine chain-plane campaign (ISSUE 14): a 4-way stripe
    # stalls finality (no group holds 4 of 5 validators), and mid-
    # partition a seeded double-signer re-issues a slot claim over
    # forged contents — the chain watch's equivocation detector
    # records offences-shaped evidence and fires the `equivocation`
    # incident, growing finality lag fires `finality-stall`, the
    # fleet quorum finality_lag view flips to warn and recovers
    # after the heal, and the watch's witness joins the replay
    # contract as the sixth stream
    "equivocating_validator": Scenario(
        name="equivocating_validator", rounds=14, fleet=True,
        chainwatch=True,
        world=(("n_validators", 5),),
        timeline=(
            (3, "stripe", 4),
            (6, "equivocate", "validator:1"),
            (9, "heal",),
        ),
        checks=("finalized-prefix", "vote-locks",
                "fleet-consistency"),
        final_checks=("heads-converged",),
    ),
    # the repair plane's mass-failure drill (ISSUE 15): a wide
    # RS(2, 2) storage plane takes 6 uploads, then TWO miners die at
    # once — every fragment they custody floods the restoral market in
    # one round. The surviving miners bind to the pool engine's
    # REGENERATING codec (ops/regen.py), warm the per-lane repair +
    # fold programs, and sweep the market concurrently in symbol mode
    # (one fragment-sized aggregate ingressed per repair instead of k
    # fragments), while a seeded fault trips every repair-class
    # dispatch on lane 0 mid-storm — the lane's breaker opens (the
    # armed incident reporter captures the bundle), repairs drain
    # through the sibling lanes, and the market still pays exactly one
    # winner per fragment. The repair-* invariants pin it: every
    # completion exactly once with verified bytes, fleet ingress below
    # the whole-fragment baseline, no order left open at the end.
    "repair_storm": Scenario(
        name="repair_storm", rounds=14, pool=3, regen=True,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 6), ("k", 2), ("m", 2)))),
        timeline=(
            (1, "upload", 0, "alice", 16_000, 3),
            (2, "upload", 0, "alice", 16_000, 3),
            (9, "storm_kill", 1, 2),
            (10, "storm_repair"),
            (11, "storm_repair"),
        ),
        faults=(("engine.dispatch.repair.d0", 1.0, "raise"),),
        checks=("finalized-prefix", "vote-locks",
                "repair-exactly-once"),
        final_checks=("restoral-single-winner", "repair-exactly-once",
                      "repair-ingress-bound", "repair-drained",
                      "storage-convergence"),
    ),
    # the autopilot drill (ISSUE 16): a scripted perf-watchdog edge
    # degrades the encode class mid-run — the remediation plane's
    # perf-pin policy latches the codec breaker held (the class now
    # runs the reference backend) within one observation round, the
    # recovery edge releases it, and a second regression later in the
    # run fires again so its incident bundle embeds a non-empty
    # action-journal tail. The remediation-* invariants run every
    # round: each matched edge must have a journaled decision, each
    # engagement must be visibly latched on the live monitor, and the
    # plane's action-journal witness joins the replay contract as the
    # seventh stream (bit-identical across same-seed runs at any n)
    "perf_regression_autopilot": Scenario(
        name="perf_regression_autopilot", rounds=14, pool=True,
        remediate=True,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 4),))),
        timeline=(
            (1, "upload", 0, "alice", 20_000),
            (3, "perf_edge", "encode", "regressed"),
            (7, "perf_edge", "encode", "ok"),
            (9, "perf_edge", "decode", "regressed"),
            (11, "perf_edge", "decode", "ok"),
        ),
        checks=("finalized-prefix", "vote-locks",
                "remediation-coverage", "remediation-effective"),
        final_checks=("storage-convergence",),
    ),
    # a miner loses a fragment; TWO non-assigned rescuers race the
    # restoral order — both reconstruct, the market pays exactly one
    "restoral_auction": Scenario(
        name="restoral_auction", rounds=14,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 5),))),
        timeline=(
            (1, "upload", 0, "alice", 20_000),
            (8, "drop_fragment", 0),
            (9, "repair_contend"),
        ),
        checks=("finalized-prefix", "vote-locks"),
        final_checks=("restoral-single-winner", "storage-convergence"),
    ),
    # the durability drill (ISSUE 20): miners die SILENTLY, one at a
    # time — no restoral order filed, no alarm raised by the dying
    # side. The custody plane's ledger (fed by the dispatch/transfer/
    # verdict/repair lineage notes) folds erasure margins over holder
    # liveness every round: each death drops the first file's margin
    # to the at-risk threshold, the `custody.at_risk` edge fires
    # BEFORE any fragment set crosses below k, the remediation
    # plane's custody-repair policy files the restoral order itself
    # and pumps a symbol-mode rebuild until the margin-recovered edge
    # releases it. custody-ledger-consistent re-derives every margin
    # from raw world storage each round; custody-proactive fails the
    # run if any segment ever crosses below k while the autopilot
    # rides. Same seed => byte-identical custody witness at any n
    "miner_attrition": Scenario(
        name="miner_attrition", rounds=14, custody=True,
        remediate=True,
        world=(("n_validators", 5),
               ("storage", (("n_miners", 6), ("k", 2), ("m", 2)))),
        timeline=(
            (1, "upload", 0, "alice", 16_000),
            (5, "attrition",),
            (9, "attrition",),
        ),
        checks=("finalized-prefix", "vote-locks",
                "custody-ledger-consistent", "custody-proactive",
                "remediation-coverage"),
        final_checks=("storage-convergence",),
    ),
}
