"""Deterministic discrete-event simulation of the full CESS stack.

A seeded :class:`World` drives hundreds of real
:class:`~cess_tpu.node.network.Node` replicas — consensus, finality,
the storage/audit pipeline and its offchain agents — over a virtual
clock and a SHA-256-tie-broken event queue: no threads, no sockets,
no wall-clock sleeps, and the same seed replays the same world
bit-identically (event log, finalized prefixes, SLO transitions,
fired faults). Scenarios live in :mod:`.scenarios` as data; the
per-round safety properties live in :mod:`.invariants`.
"""
from .clock import US, EventQueue, SimClock
from .invariants import CHECKERS, InvariantViolation, run_checks
from .scenarios import (SCENARIOS, Scenario, SimReport, resolve_ref,
                        run_scenario)
from .world import StorageProfile, World, topology_edges

__all__ = [
    "US", "EventQueue", "SimClock",
    "CHECKERS", "InvariantViolation", "run_checks",
    "SCENARIOS", "Scenario", "SimReport", "resolve_ref", "run_scenario",
    "StorageProfile", "World", "topology_edges",
]
