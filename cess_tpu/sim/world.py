"""The :class:`World` builder and event-driven network driver.

A world is N full :class:`~cess_tpu.node.network.Node` replicas (the
first ``n_validators`` hold session keys and vote) connected by a
seeded topology with per-link virtual latency and loss, driven by one
:class:`~cess_tpu.sim.clock.EventQueue` — no threads, no sockets, no
wall-clock sleeps. Block and vote gossip become queue events delivered
after the link's virtual latency; a lost delivery is simply never
scheduled, and the receiver catches up through the same
``sync_from`` path the live stack uses when an import hits an unknown
parent.

Everything a world does is a pure function of its seed: topology
edges, link latencies, loss draws, role placement and event
tie-breaking all come from SHA-256 streams over ``(seed, site,
counter)`` — the :meth:`FaultPlan.seeded` idiom at network scale.

Fork choice at the authoring seam is the SAME code the in-process
driver uses (:func:`cess_tpu.node.network.author_race`), so behavior
proven here is behavior of the production stack, not of a model.
"""
from __future__ import annotations

import dataclasses
import functools
import hashlib
import heapq

from .. import constants
from ..node import offchain as _offchain
from ..node.chain_spec import ChainSpec, ValidatorGenesis
from ..node.network import Node, author_race
from ..obs import trace
from .clock import US, EventQueue, SimClock

D = constants.DOLLARS

TOPOLOGIES = ("chain", "ring", "random-degree", "clustered")


def _u64(seed: bytes, *parts) -> int:
    """Deterministic 64-bit draw from a SHA-256 stream over the seed
    and a site label — the only entropy source in this package."""
    label = "|".join(str(p) for p in parts).encode()
    h = hashlib.sha256(b"cess-sim:" + seed + b"|" + label).digest()
    return int.from_bytes(h[:8], "little")


def _unit(seed: bytes, *parts) -> float:
    return _u64(seed, *parts) / 2.0 ** 64


def topology_edges(kind: str, n: int, seed: bytes, degree: int = 4,
                   clusters: int = 4) -> tuple[tuple[int, int], ...]:
    """Seeded topology generator. Every generator yields a CONNECTED
    graph (chain/ring backbones; clusters bridged in a cycle) so a
    fresh world is partitioned only when a scenario says so."""
    if n < 2:
        raise ValueError(f"a world needs >= 2 nodes, got {n}")
    edges: set[tuple[int, int]] = set()

    def link(a: int, b: int) -> None:
        if a != b:
            edges.add((min(a, b), max(a, b)))

    if kind == "chain":
        for i in range(n - 1):
            link(i, i + 1)
    elif kind == "ring":
        for i in range(n):
            link(i, (i + 1) % n)
    elif kind == "random-degree":
        # ring backbone for connectivity + seed-drawn extra links until
        # each node has roughly the requested degree
        for i in range(n):
            link(i, (i + 1) % n)
        for i in range(n):
            for k in range(max(0, degree - 2)):
                link(i, _u64(seed, "edge", i, k) % n)
    elif kind == "clustered":
        if clusters < 1:
            raise ValueError("clustered topology needs clusters >= 1")
        groups: list[list[int]] = [[] for _ in range(clusters)]
        for i in range(n):
            groups[i * clusters // n].append(i)
        for g in groups:
            for a, b in zip(g, g[1:]):
                link(a, b)
            if len(g) > 2:
                link(g[-1], g[0])
        for c in range(clusters):     # bridge clusters in a cycle
            if groups[c] and groups[(c + 1) % clusters]:
                link(groups[c][0], groups[(c + 1) % clusters][0])
    else:
        raise ValueError(f"unknown topology {kind!r}; "
                         f"pick one of {TOPOLOGIES}")
    return tuple(sorted(edges))


class World:
    """Build and drive one simulated network.

    ``latency_ms=(lo, hi)`` bounds per-link latency; every link's
    actual latency is drawn from the seed. ``loss`` is the per-delivery
    loss probability per link (block and vote gossip both). Nodes
    listed in ``dormant`` are built but start offline (scenario
    ``join`` brings them up). The storage plane (gateway, miners, TEE,
    validator OCWs) is attached when a :class:`StorageProfile` is
    given; adversarial miner ordinals store CORRUPTED fragment bytes —
    the audit pipeline must catch them (invariant: audit soundness).
    """

    SLOT_US = US                     # one virtual second per slot
    MAX_LATENCY_S = 0.4              # < half a slot: a slot's gossip
    # (delivery + triggered vote hop) always drains inside the slot

    def __init__(self, seed, n_nodes: int = 100, n_validators: int = 7,
                 topology: str = "random-degree", degree: int = 4,
                 clusters: int = 4, latency_ms=(2.0, 120.0),
                 loss: float = 0.0, chain_id: str = "sim",
                 dormant: tuple = (), storage=None):
        if n_validators < 2 or n_validators > n_nodes:
            raise ValueError(f"need 2 <= n_validators <= n_nodes, got "
                             f"{n_validators}/{n_nodes}")
        self.seed = seed if isinstance(seed, bytes) else str(seed).encode()
        self.n = n_nodes
        self.n_validators = n_validators
        self.clock = SimClock()
        self.queue = EventQueue(self.seed, clock=self.clock)
        self.storage = storage

        endowed = [("alice", 1_000_000_000 * D)]
        if storage is not None:
            endowed += storage.endowments()
        spec_kwargs = dict(
            name="sim", chain_id=chain_id, endowed=tuple(endowed),
            validators=tuple(ValidatorGenesis(f"v{i}", 4_000_000 * D)
                             for i in range(n_validators)),
            era_blocks=1000, epoch_blocks=1000, sudo="alice")
        if storage is not None:
            spec_kwargs.update(storage.spec_overrides())
        self.spec = ChainSpec(**spec_kwargs)
        self.nodes = [
            Node(self.spec, f"sim{i}",
                 {f"v{i}": self.spec.session_key(f"v{i}")}
                 if i < n_validators else {})
            for i in range(n_nodes)]
        self._idx = {node.name: i for i, node in enumerate(self.nodes)}

        self.edges = topology_edges(topology, n_nodes, self.seed,
                                    degree=degree, clusters=clusters)
        lo, hi = latency_ms
        lo_us = int(lo * 1000)
        hi_us = min(int(hi * 1000), int(self.MAX_LATENCY_S * US))
        self.latency_us = {
            e: lo_us + int(_unit(self.seed, "lat", *e) * (hi_us - lo_us))
            for e in self.edges}
        self.loss = float(loss)
        self._loss_ordinal: dict[tuple[int, int], int] = {}

        self.alive = [i not in dormant for i in range(n_nodes)]
        self.groups: dict[int, int] | None = None   # node -> partition
        self.slot = 0
        self.last_round_slots = 0
        self.agents: dict[str, object] = {}
        # the fleet observability plane (obs/fleet.py): armed by
        # fleet=True scenarios; None keeps every fleet hook a single
        # attribute load + None check (the zero-cost-off contract)
        self.fleet = None
        # the chain-plane watch (obs/chainwatch.py): armed by
        # chainwatch=True scenarios under the same zero-cost contract
        self.chainwatch = None
        # the custody/durability plane (obs/custody.py): armed by
        # custody=True scenarios under the same zero-cost contract
        self.custody = None
        if storage is not None:
            storage.install(self)

    # -- seeded draws ---------------------------------------------------------
    def u64(self, *parts) -> int:
        return _u64(self.seed, *parts)

    def unit(self, *parts) -> float:
        return _unit(self.seed, *parts)

    def _lost(self, src: int, dst: int) -> bool:
        if not self.loss:
            return False
        n = self._loss_ordinal.get((src, dst), 0)
        self._loss_ordinal[(src, dst)] = n + 1
        return self.unit("loss", src, dst, n) < self.loss

    # -- live graph -----------------------------------------------------------
    def neighbors(self) -> dict[int, list[int]]:
        adj: dict[int, list[int]] = {i: [] for i in range(self.n)
                                     if self.alive[i]}
        for a, b in self.edges:
            if not (self.alive[a] and self.alive[b]):
                continue
            if self.groups is not None \
                    and self.groups.get(a) != self.groups.get(b):
                continue
            adj[a].append(b)
            adj[b].append(a)
        return adj

    def components(self) -> list[list[int]]:
        """Connected components of alive nodes, each sorted, ordered by
        smallest member — a deterministic iteration order."""
        adj = self.neighbors()
        seen: set[int] = set()
        comps = []
        for start in sorted(adj):
            if start in seen:
                continue
            stack, comp = [start], []
            seen.add(start)
            while stack:
                i = stack.pop()
                comp.append(i)
                for j in adj[i]:
                    if j not in seen:
                        seen.add(j)
                        stack.append(j)
            comps.append(sorted(comp))
        return comps

    def path_latency_us(self, src: int) -> dict[int, int]:
        """Shortest virtual path latency from ``src`` to every node it
        can reach (Dijkstra over link latencies) — the gossip arrival
        model: floods take the fastest path."""
        adj = self.neighbors()
        if src not in adj:
            return {}
        dist = {src: 0}
        heap = [(0, src)]
        while heap:
            d, i = heapq.heappop(heap)
            if d > dist.get(i, 1 << 62):
                continue
            for j in adj[i]:
                e = (min(i, j), max(i, j))
                nd = d + self.latency_us[e]
                if nd < dist.get(j, 1 << 62):
                    dist[j] = nd
                    heapq.heappush(heap, (nd, j))
        return dist

    # -- event handlers -------------------------------------------------------
    def _comp_of(self, i: int) -> list[int]:
        for comp in self.components():
            if i in comp:
                return comp
        return [i]

    def _deliver_block(self, src: int, dst: int, block) -> None:
        if not self.alive[dst]:
            return                   # crashed while the bytes flew
        node = self.nodes[dst]
        with trace.span("sim.deliver", sys="sim",
                        block=block.header.number, to=node.name):
            try:
                node.import_block(block)
            except ValueError:
                # unknown parent / finality conflict: the live stack's
                # answer is catch-up sync from the sender
                if self.alive[src]:
                    node.sync_from(self.nodes[src])
            node.finality.apply_pending()
        self._gossip_votes(dst)

    def _deliver_votes(self, dst: int, votes: tuple) -> None:
        if not self.alive[dst]:
            return
        gadget = self.nodes[dst].finality
        for v in votes:
            gadget.on_vote(v)
        gadget.apply_pending()

    def _gossip_votes(self, src: int) -> None:
        """``src`` casts votes for its best chain and re-offers its
        own unfinalized votes (the healing re-gossip discipline of
        ``Network.exchange_votes``), delivered to every reachable node
        after the path latency — lossy like any other delivery."""
        node = self.nodes[src]
        if not node.keystore:
            return
        votes = tuple(node.finality.cast_votes()
                      + node.finality.own_unfinalized_votes())
        if not votes:
            return
        lat = self.path_latency_us(src)
        for dst in sorted(lat):
            if dst == src:
                continue
            if self._lost(src, dst):
                self.queue.mark(f"lost:votes:{src}->{dst}")
                continue
            self.queue.push_at_us(
                self.clock.now_us() + lat[dst],
                f"votes:{src}->{dst}:{len(votes)}",
                lambda d=dst, vs=votes: self._deliver_votes(d, vs))

    # -- slots ----------------------------------------------------------------
    def _author_component(self, slot: int, comp: list[int]) -> int:
        members = [self.nodes[i] for i in comp]
        for node in members:
            node.queue_heartbeats()
        # component-wide tx gossip snapshot: union of member pools in
        # index order, deduped by identity (Network's discipline)
        txs, seen = [], set()
        for node in members:
            for tx in node.tx_pool:
                if id(tx) not in seen:
                    seen.add(id(tx))
                    txs.append(tx)
        txs = tuple(txs)
        candidates = []
        for node in members:
            blk = node.try_author(slot, extrinsics=txs)
            if blk is not None:
                candidates.append((node, blk))
        winner, best, losers = author_race(candidates)
        if winner is None:
            return 0
        for loser, _ in losers:
            loser.abort_proposal(requeue=False)
        included = {id(tx) for tx in best.extrinsics}
        for node in members:
            node.tx_pool[:] = [tx for tx in node.tx_pool
                               if id(tx) not in included]
        winner.commit_proposal()
        src = self._idx[winner.name]
        self.queue.mark(f"author:{slot}:{src}:#{best.header.number}")
        lat = self.path_latency_us(src)
        for dst in sorted(lat):
            if dst == src:
                continue
            if self._lost(src, dst):
                self.queue.mark(
                    f"lost:#{best.header.number}:{src}->{dst}")
                continue
            self.queue.push_at_us(
                self.clock.now_us() + lat[dst],
                f"deliver:#{best.header.number}:{src}->{dst}",
                lambda s=src, d=dst, b=best: self._deliver_block(s, d, b))
        self._gossip_votes(src)
        return 1

    def _run_slot(self, slot: int) -> int:
        # a heal's explicit exchange may have advanced virtual time
        # past this slot's nominal boundary; never run time backwards
        t_us = max(slot * self.SLOT_US, self.clock.now_us())
        self.queue.run_until_us(t_us)
        produced = 0
        for comp in self.components():
            produced += self._author_component(slot, comp)
        # a slot's whole gossip cascade lands before the next slot
        # (latency is clamped under half a slot)
        self.queue.run_until_us(t_us + self.SLOT_US)
        return produced

    def run_round(self, max_slots: int = 16) -> int:
        """Advance slots until at least one component produces a block
        (a round). Returns blocks produced; records how many slots the
        round took (the liveness signal the SLO board watches)."""
        produced = 0
        slots = 0
        while produced == 0:
            if slots >= max_slots:
                break
            self.slot += 1
            slots += 1
            produced += self._run_slot(self.slot)
        self.last_round_slots = slots
        return produced

    def run_rounds(self, count: int) -> int:
        total = 0
        for _ in range(count):
            total += self.run_round()
        return total

    # -- churn / partitions ---------------------------------------------------
    def crash(self, i: int) -> None:
        """Fail-stop: state kept (a restart resumes from it)."""
        self.alive[i] = False
        self.queue.mark(f"crash:{i}")

    def leave(self, i: int) -> None:
        self.alive[i] = False
        self.queue.mark(f"leave:{i}")

    def restart(self, i: int) -> None:
        """Crash-restart (or first join of a dormant node): come back
        up and catch up from the best alive neighbor."""
        self.alive[i] = True
        self.queue.mark(f"restart:{i}")
        adj = self.neighbors()
        peers = [j for j in adj.get(i, ()) if self.alive[j]]
        if not peers:
            return
        best = max(peers, key=lambda j: (
            self.nodes[j].chain[-1].number, -j))
        self.nodes[i].sync_from(self.nodes[best])
        self.nodes[i].finality.apply_pending()
        self._gossip_votes(i)

    join = restart

    def set_partition(self, groups) -> None:
        """``groups``: iterable of node-index groups; links crossing
        group boundaries go dead until :meth:`heal`."""
        mapping: dict[int, int] = {}
        for g, members in enumerate(groups):
            for i in members:
                mapping[i] = g
        self.groups = mapping
        self.queue.mark(
            "partition:" + ":".join(
                ",".join(str(i) for i in sorted(members))
                for members in groups))

    def stripe_partition(self, k: int = 2) -> None:
        """Partition into k interleaved stripes (node i -> group i%k),
        splitting validators about evenly across the sides."""
        self.set_partition([[i for i in range(self.n) if i % k == g]
                            for g in range(k)])

    def heal(self) -> None:
        """Reconnect everything and run the explicit catch-up exchange
        the live partition test uses: everyone syncs the best head,
        the best head syncs everyone (so both sides' justifications
        and blocks meet), then validators re-offer their votes."""
        self.groups = None
        self.queue.mark("heal")
        alive = [i for i in range(self.n) if self.alive[i]]
        if not alive:
            return
        ref = max(alive, key=lambda i: (
            self.nodes[i]._weight(self.nodes[i].chain[-1].hash()), -i))
        ref_node = self.nodes[ref]
        # pull each DISTINCT competing head into the reference node
        # (one sync per branch, not per node)
        seen_heads = {ref_node.chain[-1].hash()}
        for i in alive:
            h = self.nodes[i].chain[-1].hash()
            if i != ref and h not in seen_heads:
                seen_heads.add(h)
                ref_node.sync_from(self.nodes[i])
        for i in alive:
            if i != ref:
                self.nodes[i].sync_from(ref_node)
                self.nodes[i].finality.apply_pending()
        for i in alive:
            self._gossip_votes(i)
        self.queue.run_until_us(
            self.clock.now_us() + self.SLOT_US)

    # -- views ----------------------------------------------------------------
    def alive_nodes(self) -> list[Node]:
        return [n for i, n in enumerate(self.nodes) if self.alive[i]]

    def validator_indices(self) -> list[int]:
        return list(range(self.n_validators))

    def finalized_prefix(self) -> tuple[tuple[int, bytes], ...]:
        """(finalized height, hash at that height) per alive node — the
        consensus half of the replay witness."""
        out = []
        for i, node in enumerate(self.nodes):
            if not self.alive[i]:
                continue
            f = node.finalized
            out.append((f, node.chain[f].hash()))
        return tuple(out)


# -- the storage plane --------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class StorageProfile:
    """How much storage plane to bolt onto a world: miners, gateways,
    one TEE, TEE-certified fillers, and a validator OCW per validator
    (challenge proposals need a 2/3 match). ``adversarial_miners``
    names miner ordinals that STORE corrupted fragment bytes while
    reporting clean transfers — the attack the audit must catch."""

    n_miners: int = 4
    n_gateways: int = 1
    # space accounting is in PROTOCOL units (FRAGMENT_SIZE = 8 MiB per
    # filler) however small the test payloads are: alice's 1 GiB
    # purchase needs >= 128 fillers of unsold idle space world-wide
    fillers_per_miner: int = 40
    buy_gib: int = 1
    segment_size: int = 16 * 1024
    adversarial_miners: tuple = ()
    # RS geometry of the storage pipeline. The default matches the
    # live storage-net tests; the repair storm widens to (2, 2) so a
    # batch miner kill leaves every segment k-recoverable.
    k: int = 2
    m: int = 1

    def endowments(self) -> list[tuple[str, int]]:
        out = [("tee0", 1_000 * D), ("stash0", 10_000_000 * D)]
        out += [(f"gw{j}", 1_000_000 * D) for j in range(self.n_gateways)]
        out += [(f"m{j}", 10_000 * D) for j in range(self.n_miners)]
        return out

    def spec_overrides(self) -> dict:
        # the tight audit cadence the live storage-net tests run under;
        # fragment_count tracks the profile's RS geometry so deals
        # assign one distinct miner per row (k + m = 3 at the defaults
        # == constants.FRAGMENT_COUNT: zero change unless overridden)
        return {"audit_challenge_life": 6, "audit_verify_life": 8,
                "fragment_count": self.k + self.m}

    def _place_roles(self, world: World) -> dict[str, int]:
        """Seed-drawn home nodes for every storage role, preferring
        non-validator nodes (validators host the OCWs)."""
        pool = [i for i in range(world.n_validators, world.n)
                if world.alive[i]]
        if len(pool) < self.n_miners + self.n_gateways + 1:
            pool = [i for i in range(world.n) if world.alive[i]]
        homes: dict[str, int] = {}
        for name in ([f"gw{j}" for j in range(self.n_gateways)]
                     + [f"m{j}" for j in range(self.n_miners)]
                     + ["tee0"]):
            pick = pool[world.u64("role", name) % len(pool)]
            homes[name] = pick
            if len(pool) > 1:
                pool.remove(pick)
        return homes

    def install(self, world: World) -> None:
        from ..chain.attestation import issue_cert, issue_report
        from ..crypto.rsa import generate_rsa_keypair
        from ..models.pipeline import PipelineConfig, StoragePipeline
        from ..node.offchain import (MinerAgent, OssGateway, TeeAgent,
                                     ValidatorOcw)
        from ..ops import podr2

        cfg = PipelineConfig(k=self.k, m=self.m,
                             segment_size=self.segment_size)
        key = podr2.Podr2Key.generate(7)
        pipe = StoragePipeline(cfg, podr2_key=key)
        world.pipeline = pipe
        homes = self._place_roles(world)
        world.role_homes = homes

        kp = _sim_rsa_keypair(1024, 5)
        signer_kp = _sim_rsa_keypair(1024, 6)
        mr = b"\x02" * 32
        for node in world.nodes:
            node.runtime.apply_extrinsic("root",
                                         "tee_worker.update_whitelist", mr)
            node.runtime.apply_extrinsic("root",
                                         "tee_worker.pin_ias_signer",
                                         kp.public)
            node.runtime.fund("sminer_reward_pool", 10_000 * D)
        cert = issue_cert(kp, "ias-signer", signer_kp.public)
        report, rsig = issue_report(signer_kp, mr, b"tee-pk", "tee0")
        tee_node = world.nodes[homes["tee0"]]
        # BLS-less TEE: verdicts go unsealed (empty bls_pk is accepted
        # at registration) — pure-Python pairings would dominate the
        # simulation's run time for no extra coverage here
        tee_node.submit_extrinsic("tee0", "tee_worker.register", "stash0",
                                  b"tp", b"tee-pk", report, rsig, (cert,),
                                  b"", b"")
        for j in range(self.n_miners):
            m = f"m{j}"
            world.nodes[homes[m]].submit_extrinsic(
                m, "sminer.regnstk", m, b"p" + m.encode(), 2000 * D)
        world.run_rounds(2)

        gws = [OssGateway(world.nodes[homes[f"gw{j}"]], f"gw{j}", pipe)
               for j in range(self.n_gateways)]
        tee = TeeAgent(tee_node, "tee0", key, cfg.blocks_per_fragment)
        miners = []
        for j in range(self.n_miners):
            cls = AdversarialMiner if j in self.adversarial_miners \
                else MinerAgent
            miners.append(cls(world.nodes[homes[f"m{j}"]], f"m{j}",
                              gws, pipe, clock=world.clock))
        for m in miners:
            m.setup_fillers(tee, self.fillers_per_miner)
        world.run_rounds(2)
        alice_node = world.nodes[homes["gw0"]]
        alice_node.submit_extrinsic("alice", "storage_handler.buy_space",
                                    self.buy_gib)
        for j in range(self.n_gateways):
            alice_node.submit_extrinsic("alice", "oss.authorize", f"gw{j}")
        world.run_rounds(1)
        gws[0].node.submit_extrinsic("gw0", "file_bank.create_bucket",
                                     "alice", "photos")
        world.run_rounds(1)

        for m in miners:
            world.nodes[world._idx[m.node.name]].offchain_agents.append(m)
        tee_node.offchain_agents.append(tee)
        for i in range(world.n_validators):
            world.nodes[i].offchain_agents.append(
                ValidatorOcw(f"v{i}", world.spec.session_key(f"v{i}")))
        world.agents = {a.account: a for a in miners}
        world.agents.update({g.account: g for g in gws})
        world.agents["tee0"] = tee
        world.gateways = gws
        world.miners = miners
        world.tee = tee


@functools.lru_cache(maxsize=8)
def _sim_rsa_keypair(bits: int, seed: int):
    """Seeded RSA keygen is deterministic but prime-search slow; every
    same-seed world shares the pair."""
    from ..crypto.rsa import generate_rsa_keypair

    return generate_rsa_keypair(bits, seed=seed)


class AdversarialMiner(_offchain.MinerAgent):
    """Serves audits from CORRUPTED storage: the fetched fragment
    passes the transfer integrity check, then every block gets a byte
    flipped before it lands on disk — the transfer report looks clean,
    the stored bytes do not match the on-chain hash, and the next
    service audit's proof folds over the corrupt bytes. Audit
    soundness demands the TEE verdict comes back service=False."""

    def _transfer(self, gw, frag_hash):
        blob = super()._transfer(gw, frag_hash)
        if blob is None:
            return None
        # corrupt EVERY 64-byte PoDR2 block: challenges sample a block
        # subset, and a single flipped byte escapes rounds that don't
        # draw its block — whole-fragment corruption makes the audit
        # failure deterministic, which the soundness invariant needs
        return bytes(b ^ 0xA5 for b in blob)
