"""Virtual time: the clock and the seeded event queue under every
simulated world (cess_tpu/sim).

The live stack waits with ``time.sleep`` / ``threading.Event.wait``;
the simulation replaces both with seams that ADVANCE a monotonic
virtual clock instead of blocking, so a thousand-node world runs as
fast as its events execute and two runs of the same seed see the same
timeline down to the microsecond.

Determinism contract (the same one :class:`resilience.FaultPlan`
makes): event order is a pure function of (seed, schedule). Ties at
the same virtual microsecond are broken by a SHA-256 counter stream
over the seed — not by insertion order the caller happened to use, so
reordering *independent* ``push`` calls in the driver cannot silently
change the world's behavior; the witness (:meth:`EventQueue.fired_log`)
would move and the replay test would catch it.

No wall clock, no ``random``: everything in this package is derived
from hashes over the seed (enforced by the ``sim-determinism``
cesslint family).
"""
from __future__ import annotations

import hashlib
import heapq

US = 1_000_000          # microseconds per virtual second


class SimClock:
    """Monotonic virtual time in integer microseconds.

    The three seams mirror the wall-clock idioms the serving stack
    uses, but advance virtual time instead of blocking:

    - :meth:`sleep` — ``time.sleep`` shape (injectable into
      :class:`~cess_tpu.resilience.faults.FaultPlan` and agent retry
      backoff);
    - :meth:`wait` — ``threading.Event.wait`` shape: consumes the
      timeout, returns ``False`` (a virtual wait never observes the
      event firing mid-wait — the event queue owns interleaving);
    - :meth:`deadline` — ``now + seconds`` arithmetic for timeout
      bookkeeping.
    """

    def __init__(self, start_us: int = 0):
        self._now_us = int(start_us)

    def now_us(self) -> int:
        return self._now_us

    def now(self) -> float:
        """Virtual seconds since the epoch of this world."""
        return self._now_us / US

    def advance_to_us(self, t_us: int) -> None:
        if t_us < self._now_us:
            raise ValueError(
                f"virtual time is monotonic: {t_us} < {self._now_us}")
        self._now_us = int(t_us)

    def sleep(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError(f"cannot sleep {seconds!r}s")
        self._now_us += int(round(seconds * US))

    def wait(self, timeout: float) -> bool:
        self.sleep(timeout)
        return False

    def deadline(self, seconds: float) -> float:
        return self.now() + seconds


class EventQueue:
    """Seeded discrete-event queue over a :class:`SimClock`.

    Events are ``(virtual time, name, thunk)``; :meth:`run_until_us`
    pops them in ``(time, sha256(seed, counter))`` order, advances the
    clock to each event's timestamp, and appends ``(time_us, name)``
    to the fired log — the replayable, diffable witness of the whole
    world's timeline.
    """

    def __init__(self, seed, clock: SimClock | None = None):
        self.seed = seed if isinstance(seed, bytes) else str(seed).encode()
        self.clock = clock if clock is not None else SimClock()
        # heap entries: (time_us, tiebreak, seq, name, fn) — seq makes
        # the order total even on a (practically impossible) hash tie
        # and never compares the un-orderable thunks
        self._heap: list[tuple[int, bytes, int, str, object]] = []
        self._seq = 0
        self._log: list[tuple[int, str]] = []

    def _tiebreak(self, seq: int) -> bytes:
        return hashlib.sha256(b"cess-sim:" + self.seed + b"|"
                              + seq.to_bytes(8, "little")).digest()[:8]

    def push(self, delay_s: float, name: str, fn) -> None:
        """Schedule ``fn`` at ``now + delay_s`` (virtual)."""
        self.push_at_us(self.clock.now_us() + int(round(delay_s * US)),
                        name, fn)

    def push_at_us(self, at_us: int, name: str, fn) -> None:
        if at_us < self.clock.now_us():
            raise ValueError(f"cannot schedule {name!r} in the past "
                             f"({at_us} < {self.clock.now_us()})")
        heapq.heappush(
            self._heap,
            (int(at_us), self._tiebreak(self._seq), self._seq, name, fn))
        self._seq += 1

    def mark(self, name: str) -> None:
        """Append a synthetic entry to the fired log — for actions the
        driver performs at slot boundaries (authoring, churn, heal)
        that are part of the witness but not queue events."""
        self._log.append((self.clock.now_us(), name))

    def run_until_us(self, t_us: int) -> int:
        """Fire every event scheduled strictly before ``t_us`` (events
        pushed while draining included), then advance the clock to
        ``t_us``. Returns the number of events fired."""
        fired = 0
        while self._heap and self._heap[0][0] < t_us:
            at, _, _, name, fn = heapq.heappop(self._heap)
            self.clock.advance_to_us(at)
            self._log.append((at, name))
            fn()
            fired += 1
        if t_us > self.clock.now_us():
            self.clock.advance_to_us(t_us)
        return fired

    def drain(self, max_events: int = 1_000_000) -> int:
        """Fire everything left, in order; guard against runaway
        self-scheduling loops."""
        fired = 0
        while self._heap:
            if fired >= max_events:
                raise RuntimeError(f"event queue did not drain within "
                                   f"{max_events} events")
            at, _, _, name, fn = heapq.heappop(self._heap)
            self.clock.advance_to_us(at)
            self._log.append((at, name))
            fn()
            fired += 1
        return fired

    def fired_log(self) -> tuple[tuple[int, str], ...]:
        """(time_us, name) per fired event/mark, in firing order — the
        replay-determinism witness (same seed => bit-identical log)."""
        return tuple(self._log)

    def __len__(self) -> int:
        return len(self._heap)
