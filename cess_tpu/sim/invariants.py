"""Invariant checkers: what must hold in EVERY virtual round.

Each checker takes a :class:`~cess_tpu.sim.world.World` and returns a
list of violation strings (empty = invariant holds). They read the
same internals the live tests pin — ``Node.finalized``,
``FinalityGadget.locked_rounds``, the on-chain event log, agent
fragment stores — so a regression in the production stack surfaces
here as a named invariant breaking inside a replayable world, not as
a flaky thread test.

The four core invariants (ISSUE 8):

- ``finalized-prefix``: all honest alive nodes agree on one finalized
  prefix (no two conflicting finalized blocks anywhere);
- ``vote-locks``: no own-vote lock (the GRANDPA-style safety lock) is
  held past the LOCK_HORIZON liveness backstop;
- ``audit-soundness``: a miner holding corrupt service bytes never
  passes a service audit (corrupt fragment => challenge failure);
- ``storage-convergence``: once a file is active, every honest alive
  assigned miner holds bytes matching the on-chain fragment hash.

Plus supporting checks scenarios opt into: ``heads-converged``
(post-heal: one head, one state root), ``restoral-single-winner``
(the restoral market pays exactly one rescuer per broken fragment)
and ``fleet-consistency`` (ISSUE 12: the fleet plane's global views
must be re-derivable from the per-node states it ingested — worst-of
and quorum recomputed from scratch must match the FleetBoard,
federated counters must be nonnegative, and no stitched span may
reference a parent uid outside its trace).

The ``remediation`` family (ISSUE 16) judges the control loop itself:
``remediation-coverage`` (every detector edge the policy table
matched has a journaled fire/suppress decision by an enabled policy)
and ``remediation-effective`` (every engagement is visibly latched on
its seam, and a still-regressed perf metric is never left without an
active or cooldown-fresh engagement).

The ``custody`` family (ISSUE 20) judges the durability plane:
``custody-ledger-consistent`` (every sealed erasure margin the
custody plane folds from its lineage ledger must re-derive from RAW
world storage — ledger holder identity checked against the holder's
actual fragment bytes and node liveness — and every active on-chain
file's segments must be in the ledger: a deleted byte nobody noted,
or a segment the ledger never saw, breaks it) and
``custody-proactive`` (while the remediation plane rides, no segment
may ever cross below k healthy fragments — the at-risk edge plus the
proactive-repair policy must hold the margin — and every active
at-risk key must have reached the remediation plane's evidence map).
"""
from __future__ import annotations

from ..crypto.hashing import fragment_hash
from ..obs import flight as _flight


class InvariantViolation(AssertionError):
    """Raised when a per-round invariant fails; the message carries
    every violation string so the seed + round fully localize it."""


def check_finalized_prefix(world) -> list[str]:
    views = []
    for i, node in enumerate(world.nodes):
        if not world.alive[i]:
            continue
        views.append((node.finalized, i, node))
    if not views:
        return []
    _, ref_i, ref = max(views)
    out = []
    for f, i, node in views:
        # ref's chain covers height f (ref.finalized >= f), and two
        # finalized prefixes may never disagree at any common height
        if node.chain[f].hash() != ref.chain[f].hash():
            out.append(
                f"finalized-prefix: node {i} finalized "
                f"#{f}={node.chain[f].hash().hex()[:12]} but node "
                f"{ref_i} has {ref.chain[f].hash().hex()[:12]} there")
    return out


def check_vote_locks(world) -> list[str]:
    out = []
    for i in world.validator_indices():
        if not world.alive[i]:
            continue
        node = world.nodes[i]
        head = node.chain[-1].number
        gadget = node.finality
        for account in node.keystore:
            for rnd in gadget.locked_rounds(account, head):
                if head - rnd > gadget.LOCK_HORIZON:
                    out.append(
                        f"vote-locks: node {i} account {account} still "
                        f"locked by round {rnd} at head #{head} "
                        f"(horizon {gadget.LOCK_HORIZON})")
    return out


def _ref_runtime(world):
    alive = [i for i in range(world.n) if world.alive[i]]
    if not alive:
        return None
    ref = max(alive, key=lambda i: (world.nodes[i].finalized, -i))
    return world.nodes[ref].runtime


def check_audit_soundness(world) -> list[str]:
    storage = getattr(world, "storage", None)
    if storage is None:
        return []
    rt = _ref_runtime(world)
    if rt is None:
        return []
    adversarial = {f"m{j}" for j in storage.adversarial_miners}
    latest: dict[str, dict] = {}
    for e in rt.state.events_of("audit", "VerifyResult"):
        d = dict(e.data)
        latest[d["miner"]] = d
    out = []
    for acct, d in latest.items():
        if acct not in adversarial:
            continue
        agent = world.agents.get(acct)
        if agent is None:
            continue
        corrupt_now = any(fragment_hash(blob) != h
                          for h, blob in agent.store.items())
        if corrupt_now and d["service"]:
            out.append(
                f"audit-soundness: adversarial miner {acct} holds "
                f"corrupt service bytes but its latest verify verdict "
                f"passed the service audit")
    return out


def check_storage_convergence(world) -> list[str]:
    storage = getattr(world, "storage", None)
    if storage is None:
        return []
    rt = _ref_runtime(world)
    if rt is None:
        return []
    adversarial = {f"m{j}" for j in storage.adversarial_miners}
    homes = getattr(world, "role_homes", {})
    # fragment -> current on-chain owner. The file's row->miner tuple
    # is NOT authoritative after a restoral: completion moves single
    # fragments in frag_of_miner, and the row only flips once the
    # origin holds none of that row's fragments
    owner = {frag: acct for (acct, frag), _entry
             in rt.state.iter_prefix("file_bank", "frag_of_miner")}
    out = []
    for (fh,), f in rt.state.iter_prefix("file_bank", "file"):
        if f.state != "active":
            continue
        for seg in f.segments:
            for h in seg.fragment_hashes:
                acct = owner.get(h)
                if acct is None or acct in adversarial:
                    continue          # corruption is audit's job
                if rt.file_bank.restoral_order(h) is not None:
                    continue          # loss reported; repair in flight
                agent = world.agents.get(acct)
                home = homes.get(acct)
                if agent is None or home is None \
                        or not world.alive[home]:
                    continue
                blob = agent.store.get(h)
                if blob is None:
                    # only ACTIVE files count: active means every
                    # assigned miner reported its transfer, so a hole
                    # with no restoral order is real divergence
                    out.append(
                        f"storage-convergence: miner {acct} lost "
                        f"fragment {h.hex()[:12]} of active file "
                        f"{fh.hex()[:12]} with no restoral order open")
                elif fragment_hash(blob) != h:
                    out.append(
                        f"storage-convergence: miner {acct} holds "
                        f"corrupt bytes for fragment {h.hex()[:12]} "
                        f"of active file {fh.hex()[:12]}")
    return out


def check_heads_converged(world) -> list[str]:
    heads = {}
    roots = set()
    for i, node in enumerate(world.nodes):
        if not world.alive[i]:
            continue
        heads.setdefault(node.chain[-1].hash(), []).append(i)
        roots.add(node.runtime.state.state_root())
    if len(heads) > 1:
        parts = "; ".join(
            f"{h.hex()[:12]}:{nodes}" for h, nodes in sorted(
                heads.items(), key=lambda kv: kv[1]))
        return [f"heads-converged: {len(heads)} distinct heads ({parts})"]
    if len(roots) > 1:
        return [f"heads-converged: one head but {len(roots)} state roots"]
    return []


def check_restoral_single_winner(world) -> list[str]:
    rt = _ref_runtime(world)
    if rt is None or getattr(world, "storage", None) is None:
        return []
    winners: dict[bytes, set[str]] = {}
    for e in rt.state.events_of("file_bank", "RestoralComplete"):
        d = dict(e.data)
        winners.setdefault(d["fragment_hash"], set()).add(d["miner"])
    out = []
    for frag, miners in winners.items():
        if len(miners) > 1:
            out.append(
                f"restoral-single-winner: fragment {frag.hex()[:12]} "
                f"paid {sorted(miners)} — the market must pay exactly "
                f"one rescuer")
    return out


def check_repair_exactly_once(world) -> list[str]:
    """Every fragment the restoral market completed was recovered
    EXACTLY once — one completion event, one winner — and the winner
    (when its home is still alive) holds bytes re-hashing to the
    on-chain identity. Double completion means double pay; a winner
    without verified bytes means the market paid for a repair that
    never happened."""
    rt = _ref_runtime(world)
    if rt is None or getattr(world, "storage", None) is None:
        return []
    homes = getattr(world, "role_homes", {})
    completions: dict[bytes, list[str]] = {}
    for e in rt.state.events_of("file_bank", "RestoralComplete"):
        d = dict(e.data)
        completions.setdefault(d["fragment_hash"], []).append(d["miner"])
    out = []
    for frag, accounts in sorted(completions.items()):
        if len(accounts) != 1:
            out.append(
                f"repair-exactly-once: fragment {frag.hex()[:12]} "
                f"completed {len(accounts)} times by "
                f"{sorted(set(accounts))}")
            continue
        agent = world.agents.get(accounts[0])
        home = homes.get(accounts[0])
        if agent is None or home is None or not world.alive[home]:
            continue
        blob = agent.store.get(frag)
        if blob is None:
            out.append(
                f"repair-exactly-once: winner {accounts[0]} of "
                f"fragment {frag.hex()[:12]} no longer holds it")
        elif fragment_hash(blob) != frag:
            out.append(
                f"repair-exactly-once: winner {accounts[0]} holds "
                f"corrupt bytes for fragment {frag.hex()[:12]}")
    return out


def check_repair_ingress_bound(world) -> list[str]:
    """When symbol-mode repairs ran, fleet-wide repair ingress must
    beat the whole-fragment baseline of k bytes per recovered byte —
    if the regenerating path silently stopped engaging (every repair
    fell back), this trips instead of the saving quietly vanishing."""
    storage = getattr(world, "storage", None)
    if storage is None:
        return []
    miners = getattr(world, "miners", ())
    if not any(getattr(m, "repair_mode", "") == "symbols"
               for m in miners):
        return []
    recovered = sum(m.repair_recovered_bytes for m in miners)
    ingress = sum(m.repair_ingress_bytes for m in miners)
    if recovered == 0:
        return []
    if ingress >= storage.k * recovered:
        return [
            f"repair-ingress-bound: {ingress} ingress bytes for "
            f"{recovered} recovered — not below the whole-fragment "
            f"baseline of {storage.k} bytes/byte (regenerating repair "
            f"never engaged?)"]
    return []


def check_repair_drained(world) -> list[str]:
    """Storm final check: the restoral market fully drained — no
    order still open anywhere on the reference chain view."""
    rt = _ref_runtime(world)
    if rt is None or getattr(world, "storage", None) is None:
        return []
    out = []
    for (frag,), order in sorted(
            rt.state.iter_prefix("file_bank", "restoral")):
        out.append(
            f"repair-drained: restoral order for fragment "
            f"{frag.hex()[:12]} still open "
            f"(claimed by {order.miner or 'nobody'})")
    return out


def check_fleet_consistency(world) -> list[str]:
    """Global fleet state must be DERIVABLE from per-node states: the
    FleetBoard's worst/quorum views recomputed from the node states it
    holds must match what it reports, every federated counter must be
    nonnegative (reset clamping can never produce a negative
    cumulative), and the stitched trace set must be internally
    consistent (every resolved parent uid exists in its trace)."""
    plane = getattr(world, "fleet", None)
    if plane is None:
        return []
    from ..obs import fleet as _fleet

    out = []
    board = plane.board.snapshot()
    for cls, view in board["classes"].items():
        states = [view["nodes"][i] for i in sorted(view["nodes"])]
        if not states:
            continue
        worst = max(states,
                    key=lambda s: _fleet._SEVERITY.get(s, 0))
        if view["worst"] != worst:
            out.append(
                f"fleet-consistency: class {cls} worst view "
                f"{view['worst']!r} but per-node states derive "
                f"{worst!r}")
        quorum = _fleet._quorum_state(states)
        if view["quorum"] != quorum:
            out.append(
                f"fleet-consistency: class {cls} quorum view "
                f"{view['quorum']!r} but per-node states derive "
                f"{quorum!r}")
    fed = plane.federator.snapshot()
    for key, value in fed["counters"].items():
        if value < 0:
            out.append(
                f"fleet-consistency: federated counter {key} is "
                f"negative ({value}) — reset clamping failed")
    for t in plane.stitcher.traces():
        uids = {s["uid"] for s in t["spans"]}
        for s in t["spans"]:
            parent = s["parent_uid"]
            if parent is not None and parent not in uids:
                out.append(
                    f"fleet-consistency: stitched span {s['uid']} "
                    f"resolves parent {parent} outside its trace "
                    f"{t['trace_id']}")
    return out


def check_remediation_coverage(world) -> list[str]:
    """ISSUE 16: every detector edge the remediation policy table
    matched (trigger + guard) must have a journaled DECISION — a fire
    or an explicit suppression — by an ENABLED policy. An edge that
    matched a disabled row, or matched and was silently dropped, is
    the autopilot sleeping through its alarm."""
    plane = getattr(world, "remediation", None)
    if plane is None:
        return []
    out = []
    pols = {p.name: p for p in plane.policies()}
    decided = {e["edge"] for e in plane.journal()
               if e["event"] in ("fire", "suppress")}
    count = plane.count
    for edge in plane.edge_log():
        if edge["tick"] >= count:
            continue          # arrived after the round's decision tick
        p = pols.get(edge["policy"])
        if p is not None and not p.enabled:
            out.append(
                f"remediation-coverage: edge #{edge['id']} "
                f"({edge['policy']}:{edge['key']}) matched a DISABLED "
                f"policy — no decision will ever be journaled")
        elif edge["id"] not in decided:
            out.append(
                f"remediation-coverage: edge #{edge['id']} "
                f"({edge['policy']}:{edge['key']}) has no journaled "
                f"fire/suppress decision")
    return out


def check_remediation_effective(world) -> list[str]:
    """ISSUE 16: a fired policy must MEASURABLY hold — every live pin
    engagement is visibly latched on its monitor (``state ==
    "held"``), every live repair-mode engagement shows on the miner,
    and a perf metric the detectors still grade ``regressed`` has an
    active (or cooldown-fresh) engagement covering it. Fires when the
    world was tampered behind the plane's back (someone released its
    hold) — and on a world where the responsible policy is disabled,
    because nothing ever engaged."""
    plane = getattr(world, "remediation", None)
    if plane is None:
        return []
    snap = plane.snapshot()
    out = []
    if not snap["dry_run"]:
        for ekey, e in sorted(snap["engaged"].items()):
            pname, _, key = ekey.partition(":")
            if e["action"] in ("pin-reference", "quarantine-lane"):
                mons = plane._pin_monitors(key) \
                    if e["action"] == "pin-reference" \
                    else plane._lane_monitors(key)
                for mon in mons:
                    if mon.state != "held":
                        out.append(
                            f"remediation-effective: {ekey} is "
                            f"engaged but monitor "
                            f"{getattr(mon, 'name', '?')} is "
                            f"{mon.state!r}, not held")
            elif e["action"] == "flip-repair-mode":
                miner = plane._miners.get(key)
                if miner is not None \
                        and miner.repair_mode != "fragments":
                    out.append(
                        f"remediation-effective: {ekey} is engaged "
                        f"but miner {key} still runs "
                        f"{miner.repair_mode!r}")
    perf_pols = [p for p in plane.policies()
                 if tuple(p.trigger) == ("perf", "regression")]
    for metric, state in sorted(snap["health"]["perf"].items()):
        if state != "regressed" or not perf_pols:
            continue
        covered = False
        for p in perf_pols:
            ekey = f"{p.name}:{metric}"
            if ekey in snap["engaged"]:
                covered = True
                break
            if any(e["policy"] == p.name and e["key"] == metric
                   and snap["count"] - e["tick"] <= max(p.cooldown, 1)
                   for e in snap["journal"]):
                covered = True          # cooldown-fresh decision
                break
        if not covered:
            out.append(
                f"remediation-effective: perf metric {metric} is "
                f"still regressed with no active or recent "
                f"remediation engagement")
    return out


def check_custody_ledger_consistent(world) -> list[str]:
    """ISSUE 20: the custody plane's erasure-margin fold must agree
    with a raw re-derivation from world storage. The plane's side is
    :meth:`~cess_tpu.obs.custody.CustodyPlane.fold_margins` — the
    LIVE fold over the ledger view (sealed margins go stale the
    moment the remediation tick repairs something between seal and
    check). The raw side replaces only the step the ledger cannot see
    from notes: a fragment counts healthy iff its ledger holder
    actually HOLDS matching bytes on an alive node (gateway custody —
    no holder yet — counts healthy on both sides). Deleting a miner's
    bytes behind the seams' back makes the two sides disagree. The
    coverage half: every active on-chain file's segments must be in
    the ledger — an upload the dispatch seam never noted is lineage
    lost before it started."""
    plane = getattr(world, "custody", None)
    if plane is None:
        return []
    out = []
    view = plane.ledger.view()
    folded = plane.fold_margins()
    for key in sorted(view["segments"]):
        seg = view["segments"][key]
        raw_good = 0
        for fh in seg["frags"]:
            if fh in view["lost"]:
                continue
            holder = view["holder"].get(fh)
            if holder is None:
                raw_good += 1        # still gateway custody
                continue
            home = world.role_homes.get(holder)
            if home is not None and not world.alive[home]:
                continue
            agent = world.agents.get(holder)
            blob = None if agent is None \
                else agent.store.get(bytes.fromhex(fh))
            if blob is None or fragment_hash(blob) != bytes.fromhex(fh):
                continue
            v = view["verdicts"].get(holder)
            if v is not None and not v["service"]:
                continue
            raw_good += 1
        raw_margin = raw_good - seg["k"]
        if folded.get(key) != raw_margin:
            out.append(
                f"custody-ledger-consistent: segment {key} folds "
                f"margin {folded.get(key)} from the ledger but raw "
                f"world storage derives {raw_margin}")
    alive = [i for i in range(world.n) if world.alive[i]]
    if alive:
        st = world.nodes[alive[0]].runtime.state
        for (fh,), f in sorted(st.iter_prefix("file_bank", "file")):
            if f.state != "active":
                continue
            for idx in range(len(f.segments)):
                key = f"{fh.hex()}:{idx}"
                if key not in view["segments"]:
                    out.append(
                        f"custody-ledger-consistent: active segment "
                        f"{key} is on chain but absent from the "
                        f"custody ledger")
    return out


def check_custody_proactive(world) -> list[str]:
    """ISSUE 20: the point of the durability plane — while the
    remediation plane rides, proactive repair must hold every erasure
    margin, so a ``lost`` edge (margin < 0: some fragment set crossed
    below k) is the drill failing by definition. Fires on a world
    where the custody-repair policy was disabled behind the plane's
    back (at-risk decays to lost with nobody rebuilding). The second
    half catches an unplugged listener: every ACTIVE at-risk key must
    have reached the remediation plane's custody evidence map."""
    plane = getattr(world, "custody", None)
    rem = getattr(world, "remediation", None)
    if plane is None or rem is None:
        return []
    out = []
    for (_seq, cls, key, _old, to) in plane.detector.transition_log():
        if cls == "lost" and to == "bad":
            out.append(
                f"custody-proactive: segment {key} crossed below k "
                f"healthy fragments while the remediation plane was "
                f"armed — proactive repair failed to hold the margin")
    evidence = rem.snapshot()["health"].get("custody", {})
    for key in plane.detector.active().get("at_risk", ()):
        if key not in evidence:
            out.append(
                f"custody-proactive: at-risk segment {key} never "
                f"reached the remediation plane's evidence map — the "
                f"custody listener is unplugged")
    return out


CHECKERS = {
    "finalized-prefix": check_finalized_prefix,
    "vote-locks": check_vote_locks,
    "audit-soundness": check_audit_soundness,
    "storage-convergence": check_storage_convergence,
    "heads-converged": check_heads_converged,
    "restoral-single-winner": check_restoral_single_winner,
    "repair-exactly-once": check_repair_exactly_once,
    "repair-ingress-bound": check_repair_ingress_bound,
    "repair-drained": check_repair_drained,
    "fleet-consistency": check_fleet_consistency,
    "remediation-coverage": check_remediation_coverage,
    "remediation-effective": check_remediation_effective,
    "custody-ledger-consistent": check_custody_ledger_consistent,
    "custody-proactive": check_custody_proactive,
}


def run_checks(world, names, *, context: str = "",
               strict: bool = True) -> list[str]:
    """Run the named checkers; raise :class:`InvariantViolation` with
    every violation (or return them when ``strict=False``)."""
    violations = []
    for name in names:
        violations.extend(f"[{context}] {v}" if context else v
                          for v in CHECKERS[name](world))
    if violations:
        # black-box journal first: when strict mode raises, the
        # incident trigger has already captured the evidence by the
        # time the exception unwinds the scenario
        _flight.note("sim", "invariant", context=context,
                     violations=list(violations))
        if strict:
            raise InvariantViolation("\n".join(violations))
    return violations
