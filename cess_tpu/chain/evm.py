"""EVM capability boundary: accounts, contracts, real execution.

The reference embeds the Frontier EVM stack + Wasm contracts
(/root/reference/runtime/src/lib.rs:1310-1380,1524-1528: Contracts,
Ethereum, EVM, DynamicFee, BaseFee; node-side Frontier DB + RPC
workers, node/src/service.rs:56-81,392-429). This module is the same
boundary with a framework-native engine behind it
(cess_tpu/chain/evm_interp.py): deploy runs INIT code and stores the
returned runtime code; call/query execute the core opcode set with gas
metering; contract storage lives in the chain KV; LOG0-4 entries are
archived per block for eth_getLogs. Anything beyond the engine's
surface (inter-contract CALL/CREATE) fails with ``evm.NotSupported`` —
a typed capability refusal, not an AttributeError.

Gas bounds block work: every call carries a gas limit capped at
GAS_CAP, so a looping contract burns its gas and reverts — block
production can never stall (tested in tests/test_evm.py).
"""
from __future__ import annotations

import hashlib

from . import evm_interp
from .evm_interp import EvmError, EvmRevert
from .state import DispatchError, State

PALLET = "evm"
GAS_CAP = 5_000_000       # per-call ceiling (block-stall bound)
DEFAULT_GAS = 1_000_000
MAX_CODE = 64 * 1024


def eth_address(who: str) -> bytes:
    """Deterministic 20-byte EVM address for a native account."""
    return hashlib.sha256(b"evm-addr:" + who.encode()).digest()[:20]


class Evm:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    # -- accounts (pallet-evm deposit/withdraw analog) -----------------------
    def deposit(self, who: str, amount: int) -> None:
        """Move native balance into the EVM domain ledger."""
        if not isinstance(amount, int) or amount <= 0:
            raise DispatchError("evm.InvalidAmount")
        self.balances.reserve(who, amount)
        bal = self.state.get(PALLET, "balance", who, default=0)
        self.state.put(PALLET, "balance", who, bal + amount)
        self.state.deposit_event(PALLET, "Deposited", who=who,
                                 amount=amount)

    def withdraw(self, who: str, amount: int) -> None:
        bal = self.state.get(PALLET, "balance", who, default=0)
        if not isinstance(amount, int) or amount <= 0 or amount > bal:
            raise DispatchError("evm.InvalidAmount")
        self.state.put(PALLET, "balance", who, bal - amount)
        self.balances.unreserve(who, amount)
        self.state.deposit_event(PALLET, "Withdrawn", who=who,
                                 amount=amount)

    def balance(self, who: str) -> int:
        return self.state.get(PALLET, "balance", who, default=0)

    # -- storage bridge -------------------------------------------------------
    def _sload(self, addr: bytes):
        return lambda k: self.state.get(PALLET, "storage", addr, k,
                                        default=0)

    def _sstore(self, addr: bytes):
        def store(k: int, v: int) -> None:
            if v == 0:
                self.state.delete(PALLET, "storage", addr, k)
            else:
                self.state.put(PALLET, "storage", addr, k, v)
        return store

    def storage_at(self, address: bytes, key: int) -> int:
        return self.state.get(PALLET, "storage", address, key, default=0)

    # -- contracts -----------------------------------------------------------
    def deploy(self, who: str, code: bytes,
               gas_limit: int = DEFAULT_GAS) -> bytes:
        """Run INIT ``code``; its RETURN data becomes the contract's
        runtime code at a CREATE-style address (hash of deployer +
        nonce). Reverts/exceptional halts fail the dispatch."""
        if not isinstance(code, bytes) or not code or len(code) > MAX_CODE:
            raise DispatchError("evm.InvalidCode")
        gas_limit = self._check_gas(gas_limit)
        nonce = self.state.get(PALLET, "nonce", who, default=0)
        self.state.put(PALLET, "nonce", who, nonce + 1)
        addr = hashlib.sha256(b"evm-create:" + who.encode()
                              + nonce.to_bytes(8, "little")).digest()[:20]
        try:
            res = evm_interp.execute(
                code, calldata=b"", caller=eth_address(who), address=addr,
                gas_limit=gas_limit,
                sload=self._sload(addr), sstore=self._sstore(addr))
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e
        runtime = res.output
        if len(runtime) > MAX_CODE:
            raise DispatchError("evm.InvalidCode", "runtime too large")
        self.state.put(PALLET, "code", addr, runtime)
        self._archive_logs(res.logs)
        self.state.deposit_event(PALLET, "Deployed", who=who,
                                 address=addr, code_len=len(runtime),
                                 gas_used=res.gas_used)
        return addr

    def code_at(self, address: bytes) -> bytes | None:
        return self.state.get(PALLET, "code", address)

    def _check_gas(self, gas_limit) -> int:
        if not isinstance(gas_limit, int) or gas_limit <= 0:
            raise DispatchError("evm.InvalidGas")
        return min(gas_limit, GAS_CAP)

    def call(self, who: str, address: bytes, calldata: bytes,
             gas_limit: int = DEFAULT_GAS) -> bytes:
        """Execute a contract call; storage writes + logs commit with
        the surrounding dispatch transaction."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes):
            raise DispatchError("evm.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        try:
            res = evm_interp.execute(
                code, calldata=calldata, caller=eth_address(who),
                address=address, gas_limit=gas_limit,
                sload=self._sload(address), sstore=self._sstore(address))
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e
        self._archive_logs(res.logs)
        self.state.deposit_event(PALLET, "Called", who=who,
                                 address=address, out_len=len(res.output),
                                 gas_used=res.gas_used)
        return res.output

    def query(self, address: bytes, calldata: bytes,
              caller: str = "", gas_limit: int = DEFAULT_GAS) -> bytes:
        """Read-only call (eth_call analog): same engine, storage reads
        come from chain state, writes go to a throwaway overlay, no
        events or logs are archived."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes):
            raise DispatchError("evm.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        overlay: dict[int, int] = {}
        base = self._sload(address)

        def sload(k: int) -> int:
            return overlay[k] if k in overlay else base(k)

        try:
            res = evm_interp.execute(
                code, calldata=calldata, caller=eth_address(caller),
                address=address, gas_limit=gas_limit,
                sload=sload, sstore=overlay.__setitem__)
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e
        return res.output

    # -- logs (eth_getLogs backing store) ------------------------------------
    def _archive_logs(self, logs) -> None:
        if not logs:
            return
        block = self.state.block
        seq = self.state.get(PALLET, "log_seq", block, default=0)
        for lg in logs:
            self.state.put(PALLET, "logs", block, seq,
                           (lg.address, tuple(lg.topics), lg.data))
            seq += 1
        self.state.put(PALLET, "log_seq", block, seq)

    def logs_in_range(self, from_block: int, to_block: int,
                      address: bytes | None = None) -> list[dict]:
        """O(blocks in range + matches) via the per-block log_seq
        index — never a scan of the whole archive."""
        out = []
        for blk in range(max(0, from_block), to_block + 1):
            n = self.state.get(PALLET, "log_seq", blk, default=0)
            for seq in range(n):
                addr, topics, data = self.state.get(PALLET, "logs",
                                                    blk, seq)
                if address is not None and addr != address:
                    continue
                out.append({"blockNumber": blk, "logIndex": seq,
                            "address": addr, "topics": list(topics),
                            "data": data})
        return out
