"""EVM capability boundary: accounts, contracts, real execution.

The reference embeds the Frontier EVM stack + Wasm contracts
(/root/reference/runtime/src/lib.rs:1310-1380,1524-1528: Contracts,
Ethereum, EVM, DynamicFee, BaseFee; node-side Frontier DB + RPC
workers, node/src/service.rs:56-81,392-429). This module is the same
boundary with a framework-native engine behind it
(cess_tpu/chain/evm_interp.py): deploy runs INIT code and stores the
returned runtime code; call/query execute the core opcode set with gas
metering; contract storage lives in the chain KV; LOG0-4 entries are
archived per block for eth_getLogs. Inter-contract CALL / STATICCALL /
DELEGATECALL and CREATE/CREATE2 execute through the recursive hosts
below (depth-capped, commit-on-success overlays; query() routes ALL
writes — inner frames included — into throwaway session overlays).

Value model (pallet-evm's EVMCurrencyAdapter role): the EVM domain
holds its own balance ledger keyed by 20-byte address, backed 1:1 by
a pot account (EVM_POT) on the native side — deposit moves native
tokens into the pot and credits eth_address(who); withdraw debits the
caller's EVM address and pays out of the pot, so ANY address holding
EVM balance (contracts included, once swept to a user) is always
covered by pot funds. Value-carrying calls and CREATE move EVM-domain
balance inside the frame overlays, so a reverted frame's transfers
unwind with its storage writes.

Precompiles 0x1-0x4 (ecrecover via crypto/secp256k1.py, sha256,
ripemd160, identity) are serviced by the call host at mainnet-shaped
gas prices.

Gas bounds block work: every call carries a gas limit capped at
GAS_CAP, so a looping contract burns its gas and reverts — block
production can never stall (tested in tests/test_evm.py).
"""
from __future__ import annotations

import hashlib

from . import evm_interp
from ..crypto import secp256k1
from .evm_interp import EvmError, EvmRevert
from .overlay import ChainedOverlay
from .state import DispatchError, State

PALLET = "evm"
GAS_CAP = 5_000_000       # per-call ceiling (block-stall bound)
DEFAULT_GAS = 1_000_000
MAX_CODE = 64 * 1024

# native account backing the EVM domain ledger; the ':' makes it
# unsignable (runtime._check_shape rejects colon signers), so nobody
# can transact AS the pot
EVM_POT = "evm:pot"

# base-fee market (the pallet_base_fee / pallet_dynamic_fee role,
# ref runtime/src/lib.rs:1527-1528): EIP-1559-style — the per-block
# base fee moves up to 1/8 toward demand, measured against a gas
# target of half the block's practical capacity. The fee is what Eth
# tooling reads via eth_gasPrice / eth_feeHistory; execution costs
# stay weight-fee denominated (the boundary's documented scope).
INITIAL_BASE_FEE = 10 ** 9          # 1 gwei
MIN_BASE_FEE = 7
GAS_TARGET_PER_BLOCK = GAS_CAP // 2
FEE_HISTORY_MAX = 1024


def eth_address(who: str) -> bytes:
    """Deterministic 20-byte EVM address for a native account."""
    return hashlib.sha256(b"evm-addr:" + who.encode()).digest()[:20]


def create_address(creator: bytes, nonce: int) -> bytes:
    """CREATE-style address: hash of creator address + account nonce
    (sha256 in place of keccak/RLP, per the interpreter's documented
    hash deviation)."""
    return hashlib.sha256(b"evm-create:" + creator
                          + nonce.to_bytes(8, "little")).digest()[:20]


def create2_address(creator: bytes, salt: bytes, init: bytes) -> bytes:
    """EIP-1014-shaped: predictable from (creator, salt, init) alone,
    so factories and counterfactual deployments work."""
    return hashlib.sha256(b"evm-create2:" + creator + salt
                          + hashlib.sha256(init).digest()).digest()[:20]


def next_base_fee(base: int, gas_used: int,
                  target: int = GAS_TARGET_PER_BLOCK) -> int:
    """EIP-1559 update rule: up to +-1/8 per block toward demand."""
    delta = base * (gas_used - target) // target // 8
    return max(MIN_BASE_FEE, base + delta)


# -- precompiles 0x1-0x4 (mainnet gas shape) --------------------------------

def _pc_ecrecover(data: bytes):
    data = data.ljust(128, b"\0")
    h, v, r, s = (data[0:32], int.from_bytes(data[32:64], "big"),
                  int.from_bytes(data[64:96], "big"),
                  int.from_bytes(data[96:128], "big"))
    addr = secp256k1.recover_address(h, v, r, s)
    # invalid signature: SUCCESS with empty output (mainnet semantics)
    return addr.rjust(32, b"\0") if addr is not None else b""


# resolved ONCE at import: hashlib's ripemd160 exists only when the
# OpenSSL build ships the legacy provider; a per-call failure swallowed
# by the call host would be a consensus split between nodes that differ
# in that build detail. Both paths produce identical digests (standard
# algorithm; cross-checked in tests/test_evm.py).
try:
    hashlib.new("ripemd160", b"")
    def _ripemd160(data: bytes) -> bytes:
        return hashlib.new("ripemd160", data).digest()
except ValueError:
    from ..crypto.ripemd160 import digest as _ripemd160


def _pc_ripemd160(data: bytes) -> bytes:
    return _ripemd160(data).rjust(32, b"\0")


PRECOMPILES = {
    1: (_pc_ecrecover, lambda d: 3000),
    2: (lambda d: hashlib.sha256(d).digest(),
        lambda d: 60 + 12 * ((len(d) + 31) // 32)),
    3: (_pc_ripemd160, lambda d: 600 + 120 * ((len(d) + 31) // 32)),
    4: (lambda d: d, lambda d: 15 + 3 * ((len(d) + 31) // 32)),
}


class Evm:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    # -- accounts (pallet-evm deposit/withdraw analog) -----------------------
    def deposit(self, who: str, amount: int) -> None:
        """Move native balance into the EVM domain: tokens go to the
        pot, the credit lands on eth_address(who)."""
        if not isinstance(amount, int) or amount <= 0:
            raise DispatchError("evm.InvalidAmount")
        self.balances.transfer(who, EVM_POT, amount)
        addr = eth_address(who)
        self._credit(addr, amount)
        self.state.deposit_event(PALLET, "Deposited", who=who,
                                 amount=amount)

    def withdraw(self, who: str, amount: int) -> None:
        addr = eth_address(who)
        bal = self.balance_of(addr)
        if not isinstance(amount, int) or amount <= 0 or amount > bal:
            raise DispatchError("evm.InvalidAmount")
        self.state.put(PALLET, "balance", addr, bal - amount)
        self.balances.transfer(EVM_POT, who, amount)
        self.state.deposit_event(PALLET, "Withdrawn", who=who,
                                 amount=amount)

    def balance_of(self, address: bytes) -> int:
        return self.state.get(PALLET, "balance", address, default=0)

    def balance(self, who) -> int:
        """EVM-domain balance; accepts a native account name or a
        20-byte address (eth_getBalance serves both)."""
        if isinstance(who, str):
            who = eth_address(who)
        return self.balance_of(who)

    def _credit(self, address: bytes, amount: int) -> None:
        self.state.put(PALLET, "balance", address,
                       self.balance_of(address) + amount)

    # -- storage bridge -------------------------------------------------------
    def _sload(self, addr: bytes):
        return lambda k: self.state.get(PALLET, "storage", addr, k,
                                        default=0)

    def _sstore(self, addr: bytes):
        def store(k: int, v: int) -> None:
            if v == 0:
                self.state.delete(PALLET, "storage", addr, k)
            else:
                self.state.put(PALLET, "storage", addr, k, v)
        return store

    def storage_at(self, address: bytes, key: int) -> int:
        return self.state.get(PALLET, "storage", address, key, default=0)

    # -- world overlay ---------------------------------------------------------
    # Frame-chained view of ALL EVM-domain state: storage slots
    # ("s", addr, slot), balances ("b", addr), code ("c", addr) and
    # creator nonces ("n", addr) — one overlay per call frame, so a
    # reverted frame's value transfers and CREATEs unwind exactly like
    # its storage writes (see chain/overlay.py).
    def _root_get(self, key):
        tag = key[0]
        if tag == "s":
            return self.state.get(PALLET, "storage", key[1], key[2],
                                  default=0)
        if tag == "b":
            return self.balance_of(key[1])
        if tag == "c":
            return self.state.get(PALLET, "code", key[1], default=b"")
        return self.state.get(PALLET, "nonce", key[1], default=0)

    def _root_put(self, key, value) -> None:
        tag = key[0]
        if tag == "s":
            self._sstore(key[1])(key[2], value)
        elif tag == "b":
            self.state.put(PALLET, "balance", key[1], value)
        elif tag == "c":
            self.state.put(PALLET, "code", key[1], value)
        else:
            self.state.put(PALLET, "nonce", key[1], value)

    MAX_CALL_DEPTH = 8

    class _World(ChainedOverlay):
        def __init__(self, evm: "Evm", parent=None):
            super().__init__(root_get=evm._root_get,
                             root_put=evm._root_put, parent=parent)
            self.evm = evm

        def hooks(self, a: bytes):
            return (lambda k: self.get(("s", a, k)),
                    lambda k, v: self.put(("s", a, k), v))

        def balance(self, a: bytes) -> int:
            return self.get(("b", a))

        def transfer(self, frm: bytes, to: bytes, amount: int) -> bool:
            # the < 0 guard is load-bearing: a negative amount passes
            # 'have < amount' and MINTS balance (review-reproduced
            # pot-drain via negative-value deploy)
            if not isinstance(amount, int) or amount < 0:
                return False
            if amount == 0:
                return True
            have = self.balance(frm)
            if have < amount:
                return False
            self.put(("b", frm), have - amount)
            self.put(("b", to), self.balance(to) + amount)
            return True

        def code(self, a: bytes) -> bytes:
            return self.get(("c", a))

        def set_code(self, a: bytes, code: bytes) -> None:
            self.put(("c", a), code)

        def next_nonce(self, a: bytes) -> int:
            n = self.get(("n", a))
            self.put(("n", a), n + 1)
            return n

    def code_at(self, address: bytes) -> bytes | None:
        """None = no code entry at all; b"" = a contract whose init
        returned empty runtime code (a real, distinct account state —
        mainnet treats it as a plain account that accepts calls and
        value, so conflating the two made its balance unreachable)."""
        return self.state.get(PALLET, "code", address)

    def _check_gas(self, gas_limit) -> int:
        if not isinstance(gas_limit, int) or gas_limit <= 0:
            raise DispatchError("evm.InvalidGas")
        return min(gas_limit, GAS_CAP)

    @staticmethod
    def _fail(name: str, detail: str, gas_used: int) -> DispatchError:
        """Failed executions consumed metered work the fee side charges
        for; the error carries the gas so the runtime can count it
        toward the base-fee market AFTER the dispatch rolls back (a
        _note_gas here would be undone with the transaction)."""
        err = DispatchError(name, detail)
        err.evm_gas_used = gas_used
        return err

    def _env(self) -> dict:
        return {"number": self.state.block,
                "timestamp": self.state.get(
                    "system", "now_ms", default=0) // 1000,
                "chainid": self.state.get("system", "chain_id", default=0),
                "basefee": self.base_fee(),
                "gasprice": self.base_fee(),
                "coinbase": eth_address(self.state.get(
                    "system", "author", default="") or "")}

    # -- recursive hosts ------------------------------------------------------
    def _exec_args(self, world: "Evm._World", addr: bytes,
                   caller: bytes, origin: bytes, static: bool,
                   depth: int) -> dict:
        """The per-frame hook bundle every execute() call shares."""
        sload, sstore = world.hooks(addr)
        return dict(
            caller=caller, address=addr, origin=origin,
            sload=sload, sstore=sstore, static=static,
            balance=world.balance, extcode=world.code, env=self._env(),
            call_host=self._host(addr, caller, origin, static, depth,
                                 world),
            create_host=self._create_host(addr, origin, static, depth,
                                          world))

    def _host(self, frame_addr: bytes, frame_caller: bytes,
              origin: bytes, static: bool, depth: int,
              world: "Evm._World"):
        """call_host closure for one frame (see _World for the commit
        discipline): precompile dispatch, plain value transfers to
        codeless accounts, and recursive execution with value."""
        def call_host(kind, to, data, fwd_gas, value):
            if not isinstance(value, int) or value < 0:
                return 0, b"", 0, []
            pc_id = int.from_bytes(to, "big")
            if pc_id in PRECOMPILES:
                fn, cost = PRECOMPILES[pc_id]
                c = cost(data)
                if c > fwd_gas:
                    return 0, b"", fwd_gas, []
                if value and kind == "call":
                    # mainnet moves CALL value to the precompile
                    # address like any other account; DELEGATECALL's
                    # apparent value rides along without a transfer
                    # (review-reproduced drain otherwise)
                    child = Evm._World(self, parent=world)
                    if not child.transfer(frame_addr, to, value):
                        return 0, b"", 0, []
                    child.commit()
                try:
                    return 1, fn(data), c, []
                except Exception:
                    return 0, b"", fwd_gas, []
            if depth >= self.MAX_CALL_DEPTH:
                return 0, b"", 0, []
            child = Evm._World(self, parent=world)
            if kind == "call" and value:
                if not child.transfer(frame_addr, to, value):
                    return 0, b"", 0, []   # insufficient balance
            code = child.code(to)
            if not code:
                # codeless account: a plain value transfer, success
                child.commit()
                return 1, b"", 0, []
            if kind == "delegate":
                # callee code, THIS frame's storage/identity/caller
                inner_addr, inner_caller = frame_addr, frame_caller
            else:
                inner_addr, inner_caller = to, frame_addr
            inner_static = static or kind == "static"
            try:
                res = evm_interp.execute(
                    code, calldata=data, gas_limit=fwd_gas,
                    value=value,
                    **self._exec_args(child, inner_addr, inner_caller,
                                      origin, inner_static, depth + 1))
            except EvmRevert as e:
                return 0, e.data, e.gas_used, []
            except EvmError:
                return 0, b"", fwd_gas, []
            child.commit()              # into the PARENT frame's world
            return 1, res.output, res.gas_used, res.logs
        return call_host

    def _create_host(self, frame_addr: bytes, origin: bytes,
                     static: bool, depth: int, world: "Evm._World"):
        """CREATE/CREATE2 from bytecode: run init in a child world at
        the derived address; commit code+writes only on success."""
        def create_host(init, value, salt, fwd_gas):
            if depth >= self.MAX_CALL_DEPTH or static \
                    or len(init) > MAX_CODE:
                return 0, b"", 0, []
            if value and world.balance(frame_addr) < value:
                # mainnet: insufficient-balance CREATE fails BEFORE the
                # nonce bump (geth create() order)
                return 0, b"", 0, []
            # the nonce bump lands in the PARENT world, so it persists
            # even when init reverts and the child overlay is discarded
            # (mainnet semantics): a retried create gets a FRESH
            # address instead of deterministically reusing the old one
            nonce = world.next_nonce(frame_addr)
            child = Evm._World(self, parent=world)
            if salt is None:
                new = create_address(frame_addr, nonce)
            else:
                new = create2_address(frame_addr, salt, init)
            if child.code(new):
                return 0, b"", fwd_gas, []   # address collision
            if value and not child.transfer(frame_addr, new, value):
                return 0, b"", 0, []
            try:
                res = evm_interp.execute(
                    init, calldata=b"", gas_limit=fwd_gas, value=value,
                    **self._exec_args(child, new, frame_addr, origin,
                                      False, depth + 1))
            except EvmRevert as e:
                return 0, e.data, e.gas_used, []
            except EvmError:
                return 0, b"", fwd_gas, []
            if len(res.output) > MAX_CODE:
                return 0, b"", fwd_gas, []
            child.set_code(new, res.output)
            child.commit()
            return (int.from_bytes(new, "big"), b"", res.gas_used,
                    res.logs)
        return create_host

    # -- contracts -----------------------------------------------------------
    def deploy(self, who: str, code: bytes,
               gas_limit: int = DEFAULT_GAS, value: int = 0) -> bytes:
        """Run INIT ``code``; its RETURN data becomes the contract's
        runtime code at a CREATE-style address (hash of deployer
        address + nonce). ``value`` endows the new contract from the
        deployer's EVM balance. Reverts/exceptional halts fail the
        dispatch."""
        if not isinstance(code, bytes) or not code or len(code) > MAX_CODE:
            raise DispatchError("evm.InvalidCode")
        if not isinstance(value, int) or value < 0:
            raise DispatchError("evm.InvalidAmount")
        gas_limit = self._check_gas(gas_limit)
        caller = eth_address(who)
        nonce = self.state.get(PALLET, "nonce", caller, default=0)
        self.state.put(PALLET, "nonce", caller, nonce + 1)
        addr = create_address(caller, nonce)
        world = Evm._World(self)
        if value and not world.transfer(caller, addr, value):
            raise DispatchError("evm.InsufficientBalance")
        try:
            res = evm_interp.execute(
                code, calldata=b"", gas_limit=gas_limit, value=value,
                **self._exec_args(world, addr, caller, caller, False, 0))
        except EvmRevert as e:
            raise self._fail("evm.Reverted", e.data.hex(), e.gas_used) from e
        except EvmError as e:
            raise self._fail("evm.ExecutionFailed", str(e), gas_limit) from e
        runtime = res.output
        if len(runtime) > MAX_CODE:
            raise DispatchError("evm.InvalidCode", "runtime too large")
        world.set_code(addr, runtime)
        world.commit()
        self._note_gas(res.gas_used)   # deploys count toward the market
        self._archive_logs(res.logs)
        self.state.put(PALLET, "last_exec", (res.gas_used, addr))
        self.state.deposit_event(PALLET, "Deployed", who=who,
                                 address=addr, code_len=len(runtime),
                                 gas_used=res.gas_used)
        return addr

    def call(self, who: str, address: bytes, calldata: bytes,
             gas_limit: int = DEFAULT_GAS, value: int = 0) -> bytes:
        """Execute a contract call; storage writes + logs + value
        moves commit with the surrounding dispatch transaction."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes) \
                or not isinstance(value, int) or value < 0:
            raise DispatchError("evm.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        caller = eth_address(who)
        world = Evm._World(self)           # root: commits to chain
        if value and not world.transfer(caller, address, value):
            raise DispatchError("evm.InsufficientBalance")
        if not code:
            # empty runtime code (init returned b""): a plain account
            # per mainnet — the call is a pure value transfer, so
            # balance parked there stays reachable (the inner call_host
            # already behaved this way; the top-level entry now agrees)
            world.commit()
            self.state.put(PALLET, "last_exec", (0, None))
            self.state.deposit_event(PALLET, "Called", who=who,
                                     address=address, out_len=0,
                                     gas_used=0)
            return b""
        try:
            res = evm_interp.execute(
                code, calldata=calldata, gas_limit=gas_limit,
                value=value,
                **self._exec_args(world, address, caller, caller,
                                  False, 0))
        except EvmRevert as e:
            raise self._fail("evm.Reverted", e.data.hex(), e.gas_used) from e
        except EvmError as e:
            raise self._fail("evm.ExecutionFailed", str(e), gas_limit) from e
        world.commit()
        self._note_gas(res.gas_used)
        self._archive_logs(res.logs)
        self.state.put(PALLET, "last_exec", (res.gas_used, None))
        self.state.deposit_event(PALLET, "Called", who=who,
                                 address=address, out_len=len(res.output),
                                 gas_used=res.gas_used)
        return res.output

    def query(self, address: bytes, calldata: bytes,
              caller: str = "", gas_limit: int = DEFAULT_GAS,
              value: int = 0) -> bytes:
        """Read-only call (eth_call analog): same engine, storage reads
        come from chain state, writes go to a throwaway overlay, no
        events or logs are archived."""
        return self._simulate(address, calldata, caller, gas_limit,
                              value).output

    def estimate(self, address: bytes | None, calldata: bytes,
                 caller: str = "", value: int = 0) -> int:
        """eth_estimateGas: simulate at the cap, report gas consumed
        (the schedule is deterministic, so the measure is exact; a
        failed simulation raises like eth_estimateGas errors do)."""
        if address is None:      # deploy estimate
            world = Evm._World(self)
            caller_w = eth_address(caller)
            addr = create_address(caller_w, 2 ** 62)  # scratch address
            # mirror deploy(): endow BEFORE init runs, so SELFBALANCE
            # and underfunding behave exactly as they will on-chain
            if value and not world.transfer(caller_w, addr, value):
                raise DispatchError("evm.InsufficientBalance")
            try:
                res = evm_interp.execute(
                    calldata, calldata=b"", gas_limit=GAS_CAP,
                    value=value,
                    **self._exec_args(world, addr, caller_w, caller_w,
                                      False, 0))
            except EvmRevert as e:
                raise DispatchError("evm.Reverted", e.data.hex()) from e
            except EvmError as e:
                raise DispatchError("evm.ExecutionFailed", str(e)) from e
            return res.gas_used
        return self._simulate(address, calldata, caller, GAS_CAP,
                              value).gas_used

    def _simulate(self, address: bytes, calldata: bytes, caller: str,
                  gas_limit: int, value: int):
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes):
            raise DispatchError("evm.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        # a root world that is NEVER committed: every write in this
        # simulation — inner frames included — is thrown away
        world = Evm._World(self)
        caller_w = eth_address(caller)
        if value and not world.transfer(caller_w, address, value):
            raise DispatchError("evm.InsufficientBalance")
        if not code:
            # empty-code account: eth_call/estimate see a successful
            # no-op transfer (mirrors call() above)
            return evm_interp.ExecResult(output=b"", gas_used=0, logs=[])
        try:
            return evm_interp.execute(
                code, calldata=calldata, gas_limit=gas_limit,
                value=value,
                **self._exec_args(world, address, caller_w, caller_w,
                                  False, 0))
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e

    # -- base-fee market -----------------------------------------------------
    def _note_gas(self, gas_used: int) -> None:
        self.state.put(PALLET, "block_gas",
                       self.state.get(PALLET, "block_gas", default=0)
                       + gas_used)

    def base_fee(self) -> int:
        return self.state.get(PALLET, "base_fee",
                              default=INITIAL_BASE_FEE)

    def on_initialize(self) -> None:
        """Roll the fee market: last block's demand moves the base fee
        (runtime hook, called once per block before dispatches)."""
        used = self.state.get(PALLET, "block_gas", default=0)
        base = self.base_fee()
        self.state.put(PALLET, "fee_hist", self.state.block - 1,
                       (base, used))
        stale = self.state.block - 1 - FEE_HISTORY_MAX
        if stale >= 0:
            self.state.delete(PALLET, "fee_hist", stale)
        self.state.put(PALLET, "base_fee", next_base_fee(base, used))
        self.state.put(PALLET, "block_gas", 0)

    def fee_history(self, count: int, newest: int) -> dict:
        """eth_feeHistory shape: per-block base fees + gas-used ratios
        for up to ``count`` blocks ending at ``newest``."""
        count = max(0, min(count, FEE_HISTORY_MAX))
        oldest = max(0, newest - count + 1)
        fees, ratios = [], []
        for n in range(oldest, newest + 1):
            base, used = self.state.get(PALLET, "fee_hist", n,
                                        default=(INITIAL_BASE_FEE, 0))
            fees.append(base)
            # RPC read path only (eth_feeHistory's gasUsedRatio is a
            # float by spec); never written back to consensus state
            # cesslint: disable=consensus-float
            ratios.append(round(used / GAS_CAP, 6))
        # trailing entry = block newest+1's base fee (eth_feeHistory
        # shape): the recorded one for historical windows, the live one
        # only when the window ends at the head
        nxt = self.state.get(PALLET, "fee_hist", newest + 1)
        fees.append(nxt[0] if nxt is not None else self.base_fee())
        return {"oldestBlock": oldest, "baseFeePerGas": fees,
                "gasUsedRatio": ratios}

    # -- logs (eth_getLogs backing store) ------------------------------------
    def _archive_logs(self, logs) -> None:
        if not logs:
            return
        block = self.state.block
        seq = self.state.get(PALLET, "log_seq", block, default=0)
        for lg in logs:
            self.state.put(PALLET, "logs", block, seq,
                           (lg.address, tuple(lg.topics), lg.data))
            seq += 1
        self.state.put(PALLET, "log_seq", block, seq)

    def log_seq(self, block: int) -> int:
        return self.state.get(PALLET, "log_seq", block, default=0)

    def logs_in_range(self, from_block: int, to_block: int,
                      address: bytes | None = None) -> list[dict]:
        """O(blocks in range + matches) via the per-block log_seq
        index — never a scan of the whole archive."""
        out = []
        for blk in range(max(0, from_block), to_block + 1):
            n = self.state.get(PALLET, "log_seq", blk, default=0)
            for seq in range(n):
                addr, topics, data = self.state.get(PALLET, "logs",
                                                    blk, seq)
                if address is not None and addr != address:
                    continue
                out.append({"blockNumber": blk, "logIndex": seq,
                            "address": addr, "topics": list(topics),
                            "data": data})
        return out

    def log_at(self, block: int, seq: int):
        return self.state.get(PALLET, "logs", block, seq)
