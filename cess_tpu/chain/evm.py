"""EVM capability boundary: accounts, contracts, real execution.

The reference embeds the Frontier EVM stack + Wasm contracts
(/root/reference/runtime/src/lib.rs:1310-1380,1524-1528: Contracts,
Ethereum, EVM, DynamicFee, BaseFee; node-side Frontier DB + RPC
workers, node/src/service.rs:56-81,392-429). This module is the same
boundary with a framework-native engine behind it
(cess_tpu/chain/evm_interp.py): deploy runs INIT code and stores the
returned runtime code; call/query execute the core opcode set with gas
metering; contract storage lives in the chain KV; LOG0-4 entries are
archived per block for eth_getLogs. Inter-contract CALL / STATICCALL /
DELEGATECALL execute through the recursive host below (depth-capped,
commit-on-success overlays; query() routes ALL writes — inner frames
included — into throwaway session overlays). Still out of scope:
value-carrying calls and CREATE from bytecode — those fail cleanly
(the call pushes 0), per the boundary's documented contract.

Gas bounds block work: every call carries a gas limit capped at
GAS_CAP, so a looping contract burns its gas and reverts — block
production can never stall (tested in tests/test_evm.py).
"""
from __future__ import annotations

import hashlib

from . import evm_interp
from .evm_interp import EvmError, EvmRevert
from .overlay import ChainedOverlay
from .state import DispatchError, State

PALLET = "evm"
GAS_CAP = 5_000_000       # per-call ceiling (block-stall bound)
DEFAULT_GAS = 1_000_000
MAX_CODE = 64 * 1024

# base-fee market (the pallet_base_fee / pallet_dynamic_fee role,
# ref runtime/src/lib.rs:1527-1528): EIP-1559-style — the per-block
# base fee moves up to 1/8 toward demand, measured against a gas
# target of half the block's practical capacity. The fee is what Eth
# tooling reads via eth_gasPrice / eth_feeHistory; execution costs
# stay weight-fee denominated (the boundary's documented scope).
INITIAL_BASE_FEE = 10 ** 9          # 1 gwei
MIN_BASE_FEE = 7
GAS_TARGET_PER_BLOCK = GAS_CAP // 2
FEE_HISTORY_MAX = 1024


def eth_address(who: str) -> bytes:
    """Deterministic 20-byte EVM address for a native account."""
    return hashlib.sha256(b"evm-addr:" + who.encode()).digest()[:20]


def next_base_fee(base: int, gas_used: int,
                  target: int = GAS_TARGET_PER_BLOCK) -> int:
    """EIP-1559 update rule: up to +-1/8 per block toward demand."""
    delta = base * (gas_used - target) // target // 8
    return max(MIN_BASE_FEE, base + delta)


class Evm:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    # -- accounts (pallet-evm deposit/withdraw analog) -----------------------
    def deposit(self, who: str, amount: int) -> None:
        """Move native balance into the EVM domain ledger."""
        if not isinstance(amount, int) or amount <= 0:
            raise DispatchError("evm.InvalidAmount")
        self.balances.reserve(who, amount)
        bal = self.state.get(PALLET, "balance", who, default=0)
        self.state.put(PALLET, "balance", who, bal + amount)
        self.state.deposit_event(PALLET, "Deposited", who=who,
                                 amount=amount)

    def withdraw(self, who: str, amount: int) -> None:
        bal = self.state.get(PALLET, "balance", who, default=0)
        if not isinstance(amount, int) or amount <= 0 or amount > bal:
            raise DispatchError("evm.InvalidAmount")
        self.state.put(PALLET, "balance", who, bal - amount)
        self.balances.unreserve(who, amount)
        self.state.deposit_event(PALLET, "Withdrawn", who=who,
                                 amount=amount)

    def balance(self, who: str) -> int:
        return self.state.get(PALLET, "balance", who, default=0)

    # -- storage bridge -------------------------------------------------------
    def _sload(self, addr: bytes):
        return lambda k: self.state.get(PALLET, "storage", addr, k,
                                        default=0)

    def _sstore(self, addr: bytes):
        def store(k: int, v: int) -> None:
            if v == 0:
                self.state.delete(PALLET, "storage", addr, k)
            else:
                self.state.put(PALLET, "storage", addr, k, v)
        return store

    def storage_at(self, address: bytes, key: int) -> int:
        return self.state.get(PALLET, "storage", address, key, default=0)

    # -- contracts -----------------------------------------------------------
    def deploy(self, who: str, code: bytes,
               gas_limit: int = DEFAULT_GAS) -> bytes:
        """Run INIT ``code``; its RETURN data becomes the contract's
        runtime code at a CREATE-style address (hash of deployer +
        nonce). Reverts/exceptional halts fail the dispatch."""
        if not isinstance(code, bytes) or not code or len(code) > MAX_CODE:
            raise DispatchError("evm.InvalidCode")
        gas_limit = self._check_gas(gas_limit)
        nonce = self.state.get(PALLET, "nonce", who, default=0)
        self.state.put(PALLET, "nonce", who, nonce + 1)
        addr = hashlib.sha256(b"evm-create:" + who.encode()
                              + nonce.to_bytes(8, "little")).digest()[:20]
        try:
            res = evm_interp.execute(
                code, calldata=b"", caller=eth_address(who), address=addr,
                gas_limit=gas_limit,
                sload=self._sload(addr), sstore=self._sstore(addr))
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e
        runtime = res.output
        if len(runtime) > MAX_CODE:
            raise DispatchError("evm.InvalidCode", "runtime too large")
        self.state.put(PALLET, "code", addr, runtime)
        self._note_gas(res.gas_used)   # deploys count toward the market
        self._archive_logs(res.logs)
        self.state.deposit_event(PALLET, "Deployed", who=who,
                                 address=addr, code_len=len(runtime),
                                 gas_used=res.gas_used)
        return addr

    def code_at(self, address: bytes) -> bytes | None:
        return self.state.get(PALLET, "code", address)

    def _check_gas(self, gas_limit) -> int:
        if not isinstance(gas_limit, int) or gas_limit <= 0:
            raise DispatchError("evm.InvalidGas")
        return min(gas_limit, GAS_CAP)

    MAX_CALL_DEPTH = 8

    class _World(ChainedOverlay):
        """Frame-chained view of ALL contract storage, keyed by
        (address, slot) — see chain/overlay.py for the commit
        discipline shared with the contracts VM."""

        def __init__(self, evm: "Evm", parent=None):
            super().__init__(
                root_get=lambda ak: evm._sload(ak[0])(ak[1]),
                root_put=lambda ak, v: evm._sstore(ak[0])(ak[1], v),
                parent=parent)
            self.evm = evm

        def hooks(self, a: bytes):
            return (lambda k: self.get((a, k)),
                    lambda k, v: self.put((a, k), v))

    def _host(self, frame_addr: bytes, frame_caller: bytes, static: bool,
              depth: int, world: "Evm._World"):
        """call_host closure for one frame (see _World for the commit
        discipline). Value transfer is out of scope (value != 0 fails
        the call), depth is capped."""
        def call_host(kind, to, data, fwd_gas, value):
            if depth >= self.MAX_CALL_DEPTH or value != 0:
                return 0, b"", 0, []
            code = self.code_at(to)
            if code is None:
                return 1, b"", 0, []    # empty account: success, no-op
            if kind == "delegate":      # callee code, CALLER storage
                inner_addr, inner_caller = frame_addr, frame_caller
            else:
                inner_addr, inner_caller = to, frame_addr
            inner_static = static or kind == "static"
            child = Evm._World(self, parent=world)
            sload, sstore = child.hooks(inner_addr)
            try:
                res = evm_interp.execute(
                    code, calldata=data, caller=inner_caller,
                    address=inner_addr, gas_limit=fwd_gas,
                    sload=sload, sstore=sstore,
                    static=inner_static,
                    call_host=self._host(inner_addr, inner_caller,
                                         inner_static, depth + 1,
                                         child))
            except EvmRevert as e:
                return 0, e.data, e.gas_used, []
            except EvmError:
                return 0, b"", fwd_gas, []
            child.commit()              # into the PARENT frame's world
            return 1, res.output, res.gas_used, res.logs
        return call_host

    def call(self, who: str, address: bytes, calldata: bytes,
             gas_limit: int = DEFAULT_GAS) -> bytes:
        """Execute a contract call; storage writes + logs commit with
        the surrounding dispatch transaction."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes):
            raise DispatchError("evm.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        caller = eth_address(who)
        world = Evm._World(self)           # root: commits to chain
        sload, sstore = world.hooks(address)
        try:
            res = evm_interp.execute(
                code, calldata=calldata, caller=caller,
                address=address, gas_limit=gas_limit,
                sload=sload, sstore=sstore,
                call_host=self._host(address, caller, False, 0, world))
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e
        world.commit()
        self._note_gas(res.gas_used)
        self._archive_logs(res.logs)
        self.state.deposit_event(PALLET, "Called", who=who,
                                 address=address, out_len=len(res.output),
                                 gas_used=res.gas_used)
        return res.output

    def query(self, address: bytes, calldata: bytes,
              caller: str = "", gas_limit: int = DEFAULT_GAS) -> bytes:
        """Read-only call (eth_call analog): same engine, storage reads
        come from chain state, writes go to a throwaway overlay, no
        events or logs are archived."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes):
            raise DispatchError("evm.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        # a root world that is NEVER committed: every write in this
        # simulation — inner frames included — is thrown away
        world = Evm._World(self)
        sload, sstore = world.hooks(address)
        caller_w = eth_address(caller)
        try:
            res = evm_interp.execute(
                code, calldata=calldata, caller=caller_w,
                address=address, gas_limit=gas_limit,
                sload=sload, sstore=sstore,
                call_host=self._host(address, caller_w, False, 0,
                                     world))
        except EvmRevert as e:
            raise DispatchError("evm.Reverted", e.data.hex()) from e
        except EvmError as e:
            raise DispatchError("evm.ExecutionFailed", str(e)) from e
        return res.output

    # -- base-fee market -----------------------------------------------------
    def _note_gas(self, gas_used: int) -> None:
        self.state.put(PALLET, "block_gas",
                       self.state.get(PALLET, "block_gas", default=0)
                       + gas_used)

    def base_fee(self) -> int:
        return self.state.get(PALLET, "base_fee",
                              default=INITIAL_BASE_FEE)

    def on_initialize(self) -> None:
        """Roll the fee market: last block's demand moves the base fee
        (runtime hook, called once per block before dispatches)."""
        used = self.state.get(PALLET, "block_gas", default=0)
        base = self.base_fee()
        self.state.put(PALLET, "fee_hist", self.state.block - 1,
                       (base, used))
        stale = self.state.block - 1 - FEE_HISTORY_MAX
        if stale >= 0:
            self.state.delete(PALLET, "fee_hist", stale)
        self.state.put(PALLET, "base_fee", next_base_fee(base, used))
        self.state.put(PALLET, "block_gas", 0)

    def fee_history(self, count: int, newest: int) -> dict:
        """eth_feeHistory shape: per-block base fees + gas-used ratios
        for up to ``count`` blocks ending at ``newest``."""
        count = max(0, min(count, FEE_HISTORY_MAX))
        oldest = max(0, newest - count + 1)
        fees, ratios = [], []
        for n in range(oldest, newest + 1):
            base, used = self.state.get(PALLET, "fee_hist", n,
                                        default=(INITIAL_BASE_FEE, 0))
            fees.append(base)
            ratios.append(round(used / GAS_CAP, 6))
        # trailing entry = block newest+1's base fee (eth_feeHistory
        # shape): the recorded one for historical windows, the live one
        # only when the window ends at the head
        nxt = self.state.get(PALLET, "fee_hist", newest + 1)
        fees.append(nxt[0] if nxt is not None else self.base_fee())
        return {"oldestBlock": oldest, "baseFeePerGas": fees,
                "gasUsedRatio": ratios}

    # -- logs (eth_getLogs backing store) ------------------------------------
    def _archive_logs(self, logs) -> None:
        if not logs:
            return
        block = self.state.block
        seq = self.state.get(PALLET, "log_seq", block, default=0)
        for lg in logs:
            self.state.put(PALLET, "logs", block, seq,
                           (lg.address, tuple(lg.topics), lg.data))
            seq += 1
        self.state.put(PALLET, "log_seq", block, seq)

    def logs_in_range(self, from_block: int, to_block: int,
                      address: bytes | None = None) -> list[dict]:
        """O(blocks in range + matches) via the per-block log_seq
        index — never a scan of the whole archive."""
        out = []
        for blk in range(max(0, from_block), to_block + 1):
            n = self.state.get(PALLET, "log_seq", blk, default=0)
            for seq in range(n):
                addr, topics, data = self.state.get(PALLET, "logs",
                                                    blk, seq)
                if address is not None and addr != address:
                    continue
                out.append({"blockNumber": blk, "logIndex": seq,
                            "address": addr, "topics": list(topics),
                            "data": data})
        return out
