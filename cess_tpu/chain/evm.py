"""EVM / contracts capability boundary (Frontier stub).

The reference embeds the Frontier EVM stack + Wasm contracts
(/root/reference/runtime/src/lib.rs:1524-1528: Contracts, Ethereum,
EVM, DynamicFee, BaseFee; node-side Frontier DB + RPC workers,
node/src/service.rs:56-81,392-429). SURVEY.md §2.3 scopes this as
"port as optional module or stub behind the same API boundary" — out
of the TPU hot path.

This module IS that boundary: the dispatch surface (deploy / call /
query / account basics) exists with the reference's shape, maintains
EVM account + code storage, and executes a deliberately minimal
subset; anything beyond it fails with ``evm.NotSupported`` — a typed
capability refusal, not an AttributeError. A full interpreter (or a
bridge) slots in behind this exact surface without touching callers.

Supported today: code storage/retrieval, balance transfers into/out of
the EVM domain (the pallet-evm withdraw/deposit analog), and STOP/
RETURN-of-calldata bytecode (enough to round-trip deploy->call->query
in tests). Everything else: NotSupported.
"""
from __future__ import annotations

import hashlib

from .state import DispatchError, State

PALLET = "evm"

# one-byte "opcodes" of the minimal executable subset
OP_STOP = 0x00
OP_ECHO = 0xFE   # returns calldata (test/diagnostic contract)


class Evm:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    # -- accounts (pallet-evm deposit/withdraw analog) -----------------------
    def deposit(self, who: str, amount: int) -> None:
        """Move native balance into the EVM domain ledger."""
        if not isinstance(amount, int) or amount <= 0:
            raise DispatchError("evm.InvalidAmount")
        self.balances.reserve(who, amount)
        bal = self.state.get(PALLET, "balance", who, default=0)
        self.state.put(PALLET, "balance", who, bal + amount)
        self.state.deposit_event(PALLET, "Deposited", who=who,
                                 amount=amount)

    def withdraw(self, who: str, amount: int) -> None:
        bal = self.state.get(PALLET, "balance", who, default=0)
        if not isinstance(amount, int) or amount <= 0 or amount > bal:
            raise DispatchError("evm.InvalidAmount")
        self.state.put(PALLET, "balance", who, bal - amount)
        self.balances.unreserve(who, amount)
        self.state.deposit_event(PALLET, "Withdrawn", who=who,
                                 amount=amount)

    def balance(self, who: str) -> int:
        return self.state.get(PALLET, "balance", who, default=0)

    # -- contracts -----------------------------------------------------------
    def deploy(self, who: str, code: bytes) -> bytes:
        """Store contract code; returns the contract address
        (CREATE-address analog: hash of deployer + nonce)."""
        if not isinstance(code, bytes) or not code:
            raise DispatchError("evm.InvalidCode")
        nonce = self.state.get(PALLET, "nonce", who, default=0)
        self.state.put(PALLET, "nonce", who, nonce + 1)
        addr = hashlib.sha256(b"evm-create:" + who.encode()
                              + nonce.to_bytes(8, "little")).digest()[:20]
        self.state.put(PALLET, "code", addr, code)
        self.state.deposit_event(PALLET, "Deployed", who=who,
                                 address=addr, code_len=len(code))
        return addr

    def code_at(self, address: bytes) -> bytes | None:
        return self.state.get(PALLET, "code", address)

    def call(self, who: str, address: bytes, calldata: bytes) -> bytes:
        """Execute a contract call. Only the minimal subset runs;
        real bytecode gets the typed capability refusal."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if not isinstance(calldata, bytes):
            raise DispatchError("evm.InvalidCall")
        op = code[0]
        if op == OP_STOP:
            out = b""
        elif op == OP_ECHO:
            out = calldata
        else:
            raise DispatchError(
                "evm.NotSupported",
                f"opcode 0x{op:02x}: full EVM execution is behind this "
                "boundary but not implemented")
        self.state.deposit_event(PALLET, "Called", who=who,
                                 address=address, out_len=len(out))
        return out

    def query(self, address: bytes, calldata: bytes) -> bytes:
        """Read-only call (eth_call analog): same execution surface,
        no events, no state writes committed by the caller."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("evm.NoContract")
        if code[0] == OP_STOP:
            return b""
        if code[0] == OP_ECHO:
            return calldata
        raise DispatchError("evm.NotSupported",
                            f"opcode 0x{code[0]:02x}")
