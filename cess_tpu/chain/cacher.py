"""CDN/cacher registry + batched download micropayments.

Reference: c-pallets/cacher — register/update/logout/pay
(src/lib.rs:88-150) with CacherInfo{payee, peer_id, byte_price} and
Bill{id, to (cacher), amount} (src/types.rs:11-28). ``pay`` settles a
batch of signed download bills from the caller's balance.
"""
from __future__ import annotations

import dataclasses

from .. import codec
from .balances import Balances
from .state import DispatchError, State

PALLET = "cacher"


@codec.register
@dataclasses.dataclass(frozen=True)
class CacherInfo:
    payee: str
    peer_id: bytes
    byte_price: int     # token units per byte


@codec.register
@dataclasses.dataclass(frozen=True)
class Bill:
    id: bytes
    to: str             # cacher account
    amount: int


class Cacher:
    def __init__(self, state: State, balances: Balances):
        self.state = state
        self.balances = balances

    def register(self, who: str, payee: str, peer_id: bytes,
                 byte_price: int) -> None:
        if self.state.contains(PALLET, "cacher", who):
            raise DispatchError("cacher.Registered")
        self.state.put(PALLET, "cacher", who,
                       CacherInfo(payee, peer_id, byte_price))
        self.state.deposit_event(PALLET, "Register", who=who)

    def update(self, who: str, payee: str, peer_id: bytes,
               byte_price: int) -> None:
        if not self.state.contains(PALLET, "cacher", who):
            raise DispatchError("cacher.UnRegister")
        self.state.put(PALLET, "cacher", who,
                       CacherInfo(payee, peer_id, byte_price))
        self.state.deposit_event(PALLET, "Update", who=who)

    def logout(self, who: str) -> None:
        if not self.state.contains(PALLET, "cacher", who):
            raise DispatchError("cacher.UnRegister")
        self.state.delete(PALLET, "cacher", who)
        self.state.deposit_event(PALLET, "Logout", who=who)

    def cacher_info(self, who: str) -> CacherInfo | None:
        return self.state.get(PALLET, "cacher", who)

    def pay(self, who: str, bills: list[Bill]) -> None:
        """Settle download bills; duplicate bill ids are rejected
        (replay protection)."""
        for bill in bills:
            info = self.cacher_info(bill.to)
            if info is None:
                raise DispatchError("cacher.UnRegister", bill.to)
            if self.state.contains(PALLET, "paid", bill.id):
                raise DispatchError("cacher.BillReplayed", bill.id.hex())
            self.balances.transfer(who, info.payee, bill.amount)
            self.state.put(PALLET, "paid", bill.id, True)
            self.state.deposit_event(PALLET, "Pay", who=who, to=bill.to,
                                     amount=bill.amount)
