"""Composition root: pallets wired + block executive.

Mirrors the reference runtime (SURVEY.md §2.2): construct_runtime
composition with cross-pallet trait wiring, the Executive's
on_initialize order (audit sweeps -> storage-handler lease sweep ->
file-bank GC -> scheduler-credit rollover -> scheduler dispatch,
runtime/src/lib.rs:1479-1540 §3.4), transactional extrinsic dispatch,
and era rotation driving staking payouts + sminer reward tranches.

Consensus (who authors blocks, epoch randomness) lives in
cess_tpu/node; the runtime consumes randomness via
("system", "randomness") exactly like the reference's
ParentBlockRandomness.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import constants
from .audit import Audit
from .balances import Balances
from .cacher import Cacher
from .file_bank import FileBank
from .oss import Oss
from .scheduler import Scheduler
from .scheduler_credit import SchedulerCredit
from .sminer import Sminer
from .staking import Staking
from .state import DispatchError, State
from .storage_handler import StorageHandler
from .tee_worker import TeeWorker

ROOT = "root"

# extrinsics only the root / scheduler origin may call
ROOT_ONLY = {
    "file_bank.calculate_end",
    "file_bank.deal_timeout",
    "file_bank.force_miner_exit",
    "tee_worker.update_whitelist",
    "tee_worker.pin_ias_signer",
    "audit.set_keys",
}


@dataclasses.dataclass
class RuntimeConfig:
    fragment_count: int = constants.FRAGMENT_COUNT
    era_blocks: int = constants.EPOCH_DURATION_BLOCKS * constants.SESSIONS_PER_ERA
    credit_period_blocks: int | None = None  # default: era_blocks
    audit_challenge_life: int | None = None  # default: audit module constant
    audit_verify_life: int | None = None


class Runtime:
    def __init__(self, config: RuntimeConfig | None = None):
        self.config = config or RuntimeConfig()
        s = self.state = State()
        self.balances = Balances(s)
        self.storage_handler = StorageHandler(s, self.balances)
        self.sminer = Sminer(s, self.balances, self.storage_handler)
        self.scheduler = Scheduler(s)
        self.oss = Oss(s)
        self.cacher = Cacher(s, self.balances)
        self.staking = Staking(s, self.balances)
        self.credit = SchedulerCredit(
            s, self.config.credit_period_blocks or self.config.era_blocks)
        self.tee_worker = TeeWorker(s, staking=self.staking,
                                    credit=self.credit)
        self.file_bank = FileBank(s, self.balances, self.storage_handler,
                                  self.sminer, self.scheduler,
                                  fragment_count=self.config.fragment_count,
                                  oss=self.oss)
        # pass only explicitly configured lifetimes; Audit owns defaults
        audit_overrides = {
            k: v for k, v in {
                "challenge_life": self.config.audit_challenge_life,
                "verify_life": self.config.audit_verify_life,
            }.items() if v is not None}
        self.audit = Audit(
            s, self.sminer, tee_worker=self.tee_worker,
            storage_handler=self.storage_handler, file_bank=self.file_bank,
            **audit_overrides)
        self.pallets = {
            "balances": self.balances,
            "storage_handler": self.storage_handler,
            "sminer": self.sminer,
            "scheduler": self.scheduler,
            "oss": self.oss,
            "cacher": self.cacher,
            "staking": self.staking,
            "scheduler_credit": self.credit,
            "tee_worker": self.tee_worker,
            "file_bank": self.file_bank,
            "audit": self.audit,
        }
        self._update_randomness()

    # -- dispatch --------------------------------------------------------------
    def _resolve(self, call: str):
        pallet_name, _, method_name = call.partition(".")
        pallet = self.pallets.get(pallet_name)
        fn = getattr(pallet, method_name, None)
        if pallet is None or fn is None or method_name.startswith("_"):
            raise DispatchError("system.UnknownCall", call)
        return fn

    def apply_extrinsic(self, origin: str, call: str, *args, **kwargs):
        """Transactional dispatch; rolls back on DispatchError and
        re-raises (tests assert on error names like assert_noop!)."""
        fn = self._resolve(call)
        if call in ROOT_ONLY:
            if origin != ROOT:
                raise DispatchError("system.BadOrigin", call)
            call_args = args
        else:
            call_args = (origin, *args)
        self.state.begin_tx()
        try:
            result = fn(*call_args, **kwargs)
        except DispatchError:
            self.state.rollback_tx()
            raise
        self.state.commit_tx()
        return result

    # -- block execution ---------------------------------------------------------
    def _update_randomness(self) -> None:
        prev = self.state.get("system", "randomness", default=b"genesis")
        self.state.put("system", "randomness", hashlib.sha256(
            prev + self.state.block.to_bytes(8, "little")).digest())

    def set_randomness(self, randomness: bytes) -> None:
        """Consensus hook: epoch/VRF randomness replaces the fallback
        hash chain (reference ParentBlockRandomness)."""
        self.state.put("system", "randomness", randomness)

    def init_block(self, randomness: bytes | None = None) -> None:
        """Advance one block and run on_initialize hooks in the
        reference's construct_runtime order (§3.4). ``randomness``
        comes from consensus (the parent VRF output); without it a
        deterministic hash chain stands in."""
        self.state.archive_events()
        self.state.block += 1
        if randomness is not None:
            self.set_randomness(randomness)
        else:
            self._update_randomness()
        self.audit.on_initialize()
        dead = self.storage_handler.on_initialize()
        self.file_bank.on_initialize(dead)
        self.credit.on_initialize()
        if self.state.block % self.config.era_blocks == 0:
            era = self.staking.current_era()
            self.staking.end_era(era)
            self.sminer.release_reward_tranches()
            # session rotation: audit keys follow the elected set
            elected = self.staking.electable()
            if elected:
                self.audit.set_keys(tuple(elected))
        for name, pallet, method, task_args in self.scheduler.take_due():
            self.state.begin_tx()
            try:
                getattr(self.pallets[pallet], method)(*task_args)
            except DispatchError as e:
                self.state.rollback_tx()
                self.state.deposit_event("scheduler", "TaskFailed",
                                         name=name, error=e.name)
            else:
                self.state.commit_tx()

    def run_to_block(self, n: int) -> None:
        while self.state.block < n:
            self.init_block()

    def advance_blocks(self, n: int) -> None:
        self.run_to_block(self.state.block + n)

    # -- genesis helpers -----------------------------------------------------------
    def fund(self, who: str, amount: int) -> None:
        self.balances.mint(who, amount)
