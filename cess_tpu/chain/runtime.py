"""Composition root: pallets wired + block executive.

Mirrors the reference runtime (SURVEY.md §2.2): construct_runtime
composition with cross-pallet trait wiring, the Executive's
on_initialize order (audit sweeps -> storage-handler lease sweep ->
file-bank GC -> scheduler-credit rollover -> scheduler dispatch,
runtime/src/lib.rs:1479-1540 §3.4), transactional extrinsic dispatch,
and era rotation driving staking payouts + sminer reward tranches.

Consensus (who authors blocks, epoch randomness) lives in
cess_tpu/node; the runtime consumes randomness via
("system", "randomness") exactly like the reference's
ParentBlockRandomness.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import constants
from .audit import Audit
from .balances import Balances
from .assets import Assets
from .cacher import Cacher
from .indices import Indices, Preimage
from .contracts import Contracts
from .election import Election
from .evm import Evm
from .extrinsic import SignedExtrinsic, verify_signature
from .file_bank import FileBank
from .governance import Council, TechnicalCommittee, Treasury
from .im_online import ImOnline
from . import migrations
from .offences import Offences
from .oss import Oss
from .scheduler import Scheduler
from .scheduler_credit import SchedulerCredit
from .sminer import Sminer
from .staking import Staking
from .state import DispatchError, State
from .storage_handler import StorageHandler
from .system import System
from .tee_worker import TeeWorker

ROOT = "root"
TREASURY = "treasury"

# extrinsics only the root / scheduler origin may call
ROOT_ONLY = {
    "file_bank.calculate_end",
    "file_bank.deal_timeout",
    "file_bank.force_miner_exit",
    "tee_worker.update_whitelist",
    "tee_worker.pin_ias_signer",
    "audit.set_keys",
    "council.set_members",
    "technical_committee.set_members",
    "system.apply_runtime_upgrade",
    "assets.set_fee_rate",
}

# the dispatch surface — FRAME's #[pallet::call] analog. Pallet
# methods NOT listed here (mint, set_sudo, lock_space, punish hooks,
# ...) are internal: reachable only through other pallets or hooks,
# never from a transaction.
SIGNED_CALLS = {
    "system.set_session_key", "system.remark",
    "balances.transfer",
    "storage_handler.buy_space", "storage_handler.expansion_space",
    "storage_handler.renewal_space",
    "sminer.regnstk", "sminer.increase_collateral",
    "sminer.update_beneficiary", "sminer.update_peer_id",
    "sminer.commit_filler_seed",
    "oss.register", "oss.update", "oss.destroy",
    "oss.authorize", "oss.cancel_authorize",
    "cacher.register", "cacher.update", "cacher.logout", "cacher.pay",
    "staking.bond", "staking.unbond", "staking.withdraw_unbonded",
    "staking.validate", "staking.chill", "staking.nominate",
    "im_online.heartbeat",
    "election.submit_solution", "election.submit_unsigned",
    "council.propose", "council.vote", "council.close",
    "technical_committee.propose", "technical_committee.vote",
    "technical_committee.close",
    "treasury.propose_spend", "treasury.propose_bounty",
    "sminer.faucet",
    "evm.deposit", "evm.withdraw", "evm.deploy", "evm.call",
    "contracts.deploy", "contracts.call", "contracts.upload_code",
    "contracts.instantiate",
    "assets.create", "assets.destroy", "assets.set_team",
    "assets.transfer_ownership",
    "assets.set_metadata", "assets.mint", "assets.burn",
    "assets.transfer", "assets.freeze", "assets.thaw",
    "assets.freeze_asset", "assets.thaw_asset", "assets.set_fee_asset",
    "indices.claim", "indices.free", "indices.transfer",
    "preimage.note_preimage", "preimage.unnote_preimage",
    "treasury.add_child_bounty", "treasury.award_child_bounty",
    "treasury.close_child_bounty",
    "tee_worker.register", "tee_worker.exit",
    "file_bank.create_bucket", "file_bank.delete_bucket",
    "file_bank.upload_declaration", "file_bank.transfer_report",
    "file_bank.delete_file", "file_bank.ownership_transfer",
    "file_bank.upload_filler", "file_bank.replace_file_report",
    "file_bank.delete_filler",
    "file_bank.generate_restoral_order", "file_bank.claim_restoral_order",
    "file_bank.restoral_order_complete", "file_bank.miner_exit_prep",
    "file_bank.miner_withdraw",
    "audit.save_challenge_info", "audit.submit_proof",
    "audit.submit_verify_result",
    "offences.report_equivocation",
}
DISPATCHABLE = SIGNED_CALLS | ROOT_ONLY

# calls exempt from fees: the reference submits these as validated
# unsigned / operational transactions (audit/src/lib.rs:739-772), so
# the TEE/miner/validator accounts need no spendable balance to keep
# the audit loop alive
FEELESS = {
    "audit.save_challenge_info",
    "audit.submit_proof",
    # NOT submit_verify_result: the reference dispatches it
    # ensure_signed and fee-paying (audit/src/lib.rs:484-491), and the
    # on-chain BLS pairing check makes it the single most expensive
    # dispatch — a feeless failure path would let a compromised TEE
    # burn every replica's CPU for free (fees stick on failed calls)
    # evidence-carrying, self-validating (ref submits equivocation
    # reports as validated unsigned transactions)
    "offences.report_equivocation",
    # ref im-online heartbeats are validated unsigned operational txs
    "im_online.heartbeat",
    # OCW-mined election solutions ride as validated unsigned txs in
    # the reference (lib.rs:834-863); admission fully verifies the
    # session signature + exact score, so the feeless lane can't be
    # spammed with junk
    "election.submit_unsigned",
}


# Per-dispatch weights: MEASURED on a real runtime by
# tools/gen_weights.py (the analog of the reference's
# frame-benchmarking-generated per-pallet weights.rs via
# .maintain/frame-weight-template.hbs, SURVEY.md §6 "Extrinsic
# weights"). Unit: one balances.transfer dispatch; scaled x10 here so
# weight fees stay significant next to byte fees. The table covers
# EVERY entry of DISPATCHABLE — tests/test_weights.py fails the build
# if a new call ships unmeasured. Regenerate with
# `python tools/gen_weights.py --write`.
from .weights_generated import GENERATED_WEIGHTS

# Hand-set floors for heavy dispatches the measurement script has no
# scenario for yet (attestation/TEE setup is involved): they must not
# silently drop to weight 0 and become spammable.
HAND_WEIGHTS = {
    "tee_worker.register": 40,            # chain + report verification
    "audit.submit_verify_result": 50,     # BLS pairing check per verdict
    "file_bank.upload_filler": 30,
    "storage_handler.expansion_space": 10,
    "storage_handler.renewal_space": 10,
    "contracts.call": 20, "contracts.deploy": 20,
    "contracts.upload_code": 10,
}
CALL_WEIGHTS = {call: 10 * w
                for call, w in sorted(GENERATED_WEIGHTS.items())}
for _call, _floor in sorted(HAND_WEIGHTS.items()):
    # floors, not overrides: a future measured weight above the hand
    # value must win, or heavy dispatches get silently undercharged
    CALL_WEIGHTS[_call] = max(CALL_WEIGHTS.get(_call, 0), _floor)
WEIGHT_FEE = constants.TX_BYTE_FEE      # one weight unit == one byte


@dataclasses.dataclass
class RuntimeConfig:
    fragment_count: int = constants.FRAGMENT_COUNT
    era_blocks: int = constants.EPOCH_DURATION_BLOCKS * constants.SESSIONS_PER_ERA
    max_validators: int = 100                # ChainSpec default mirrored
    credit_period_blocks: int | None = None  # default: era_blocks
    audit_challenge_life: int | None = None  # default: audit module constant
    audit_verify_life: int | None = None
    genesis_spec_version: int = 0   # 0 -> current code version
    # reference defers offence slashes 28 eras (runtime :563); 0 =
    # immediate (dev/test default — deferral is config opt-in)
    slash_defer_eras: int = 0


class Runtime:
    def __init__(self, config: RuntimeConfig | None = None):
        self.config = config or RuntimeConfig()
        s = self.state = State()
        self.system = System(s)
        self.balances = Balances(s)
        self.storage_handler = StorageHandler(s, self.balances)
        self.sminer = Sminer(s, self.balances, self.storage_handler)
        self.scheduler = Scheduler(s)
        self.oss = Oss(s)
        self.cacher = Cacher(s, self.balances)
        self.assets = Assets(s, self.balances)
        self.indices = Indices(s, self.balances)
        self.preimage = Preimage(s, self.balances)
        self.staking = Staking(s, self.balances,
                               slash_defer_eras=self.config.slash_defer_eras)
        self.credit = SchedulerCredit(
            s, self.config.credit_period_blocks or self.config.era_blocks)
        self.tee_worker = TeeWorker(s, staking=self.staking,
                                    credit=self.credit)
        self.offences = Offences(s, self.staking, self.genesis_hash)
        self.im_online = ImOnline(s, self.staking, self.offences)
        self.file_bank = FileBank(s, self.balances, self.storage_handler,
                                  self.sminer, self.scheduler,
                                  fragment_count=self.config.fragment_count,
                                  oss=self.oss)
        # pass only explicitly configured lifetimes; Audit owns defaults
        audit_overrides = {
            k: v for k, v in {
                "challenge_life": self.config.audit_challenge_life,
                "verify_life": self.config.audit_verify_life,
            }.items() if v is not None}
        self.audit = Audit(
            s, self.sminer, tee_worker=self.tee_worker,
            storage_handler=self.storage_handler, file_bank=self.file_bank,
            **audit_overrides)
        self.pallets = {
            "system": self.system,
            "balances": self.balances,
            "storage_handler": self.storage_handler,
            "sminer": self.sminer,
            "scheduler": self.scheduler,
            "oss": self.oss,
            "cacher": self.cacher,
            "assets": self.assets,
            "indices": self.indices,
            "preimage": self.preimage,
            "staking": self.staking,
            "scheduler_credit": self.credit,
            "tee_worker": self.tee_worker,
            "file_bank": self.file_bank,
            "audit": self.audit,
            "offences": self.offences,
            "im_online": self.im_online,
        }
        self.treasury_pallet = Treasury(s, self.balances)
        self.council = Council(s, self)   # needs self.pallets at close()
        self.technical_committee = TechnicalCommittee(s, self)
        self.pallets["treasury"] = self.treasury_pallet
        self.pallets["council"] = self.council
        self.pallets["technical_committee"] = self.technical_committee
        self.evm = Evm(s, self.balances)
        self.pallets["evm"] = self.evm
        self.election = Election(s, self.balances, self.staking,
                                 self.credit, self.config.era_blocks,
                                 max_validators=self.config.max_validators)
        self.pallets["election"] = self.election
        self.contracts = Contracts(s)
        self.pallets["contracts"] = self.contracts
        # genesis stamps the CHAIN's spec version (ChainSpec field),
        # reproducible by any code version; upgrades activate via the
        # system.apply_runtime_upgrade extrinsic
        migrations.stamp_genesis(s, self.config.genesis_spec_version
                                 or migrations.SPEC_VERSION)
        self._update_randomness()

    # -- dispatch --------------------------------------------------------------
    def _resolve(self, call: str):
        if call not in DISPATCHABLE:
            raise DispatchError("system.UnknownCall", call)
        pallet_name, _, method_name = call.partition(".")
        pallet = self.pallets.get(pallet_name)
        fn = getattr(pallet, method_name, None)
        if pallet is None or fn is None:
            raise DispatchError("system.UnknownCall", call)
        return fn

    def apply_extrinsic(self, origin: str, call: str, *args, **kwargs):
        """RAW transactional dispatch: rolls back on DispatchError and
        re-raises (tests assert on error names like assert_noop!).

        This is the mock-runtime entry point — the analog of driving a
        FRAME pallet with RuntimeOrigin::signed(x) in unit tests. The
        node/network path never calls it: blocks carry
        ``SignedExtrinsic``s applied via :meth:`apply_signed`, which
        authenticates the origin first."""
        fn = self._resolve(call)
        if call in ROOT_ONLY:
            if origin != ROOT:
                raise DispatchError("system.BadOrigin", call)
            call_args = args
        else:
            call_args = (origin, *args)
        self.state.begin_tx()
        try:
            result = fn(*call_args, **kwargs)
        except DispatchError as e:
            self.state.rollback_tx()
            # reverted/trapping EVM executions still did metered work
            # (and paid for it): count it toward the base-fee market so
            # sustained reverting load moves the base fee like any
            # other demand (evm.Evm._fail)
            gas = getattr(e, "evm_gas_used", 0)
            if gas:
                self.evm._note_gas(gas)
            raise
        except Exception as e:
            # A validly-signed extrinsic can still carry arbitrary arg
            # *values* (codec.decode checks structure, not call
            # schemas): a TypeError/ValueError inside the call must
            # become a deterministic skip, never escape mid-block with
            # the tx open — the reference gets this for free from typed
            # SCALE call decoding (runtime/src/lib.rs:1564-1574).
            self.state.rollback_tx()
            raise DispatchError(
                "system.BadCallArgs", f"{call}: {type(e).__name__}") from e
        self.state.commit_tx()
        return result

    # -- signed pipeline (runtime/src/lib.rs:1564-1590) -----------------------
    def genesis_hash(self) -> bytes:
        return self.state.get("system", "genesis", default=b"\0" * 32)

    def set_genesis_hash(self, h: bytes) -> None:
        self.state.put("system", "genesis", h)

    def tx_fee(self, xt: SignedExtrinsic) -> int:
        """base + per-byte length + per-call weight fee
        (TransactionPayment's role; weights mirror the reference's
        measured per-dispatch weights)."""
        if xt.call in FEELESS:
            return 0
        return constants.TX_BASE_FEE + constants.TX_BYTE_FEE * len(xt) \
            + WEIGHT_FEE * CALL_WEIGHTS.get(xt.call, 0)

    @staticmethod
    def _check_shape(xt: SignedExtrinsic) -> None:
        """Structural validation of a (possibly peer-decoded)
        extrinsic: codec.decode constructs dataclasses without field
        checks, so every field is untrusted until proven well-formed.
        A self-signed-but-malformed tx must fail with a DispatchError
        (deterministic skip), never a TypeError mid-block.

        ``:`` is reserved for internal principals (the contracts VM
        names cross-contract callers ``contract:<addr>``,
        contracts.py:396): a signable account named like one could
        impersonate that contract to any callee doing caller-based
        auth, so colon names never enter the signed pipeline."""
        ok = (isinstance(xt.signer, str) and xt.signer
              and ":" not in xt.signer
              and isinstance(xt.public, bytes) and len(xt.public) == 32
              and isinstance(xt.nonce, int) and xt.nonce >= 0
              and isinstance(xt.call, str)
              and isinstance(xt.args, tuple)
              and isinstance(xt.kwargs, tuple)
              and all(isinstance(kv, tuple) and len(kv) == 2
                      and isinstance(kv[0], str) for kv in xt.kwargs)
              and isinstance(xt.signature, bytes)
              and len(xt.signature) == 64)
        if not ok:
            raise DispatchError("system.MalformedTransaction")

    def validate_signed(self, xt: SignedExtrinsic, *,
                        at_apply: bool = False,
                        pending_from_signer: int = 0) -> int:
        """Pre-dispatch validity (the SignedExtra checks): shape,
        signature over (genesis, nonce, call), account-key binding,
        sequential nonce, fee affordability. Raises DispatchError when
        invalid; returns (fee, asset_funding) so apply_signed charges
        exactly what was checked without re-resolving anything."""
        if not isinstance(xt, SignedExtrinsic):
            raise DispatchError("system.NotSigned", str(type(xt).__name__))
        self._check_shape(xt)
        if xt.call not in DISPATCHABLE:
            raise DispatchError("system.UnknownCall", xt.call)
        if not verify_signature(xt, self.genesis_hash()):
            raise DispatchError("system.BadSignature", xt.call)
        bound = self.system.account_key(xt.signer)
        if bound is not None and bound != xt.public:
            raise DispatchError("system.AccountKeyMismatch", xt.signer)
        expected = self.system.nonce(xt.signer) + pending_from_signer
        if xt.nonce != expected:
            raise DispatchError(
                "system.BadNonce", f"{xt.call}: {xt.nonce} != {expected}")
        fee = self.tx_fee(xt)
        # AssetTxPayment: an account preference + covering asset
        # balance satisfies affordability; else native tokens must.
        # The resolved funding is RETURNED so apply_signed charges
        # exactly what was checked (no re-resolution, no divergence).
        in_asset = self.assets.fee_in_asset(xt.signer, fee)
        if in_asset is None and self.balances.free(xt.signer) < fee:
            raise DispatchError("system.CannotPayFee", xt.signer)
        if at_apply and xt.call in ROOT_ONLY \
                and xt.signer != self.system.sudo():
            raise DispatchError("system.BadOrigin", xt.call)
        return fee, in_asset

    def apply_signed(self, xt: SignedExtrinsic):
        """Authenticated dispatch inside block execution. Signature,
        binding, and nonce are re-verified; the nonce bump, first-use
        key binding, and fee charge stick even if the call itself
        fails (frame-system semantics: replay protection and fees are
        not rolled back with the dispatch)."""
        fee, in_asset = self.validate_signed(xt, at_apply=True)
        self.system.bind_account_key(xt.signer, xt.public)
        self.system.bump_nonce(xt.signer)
        if fee:
            # 80% treasury / 20% block author (runtime/src/lib.rs:190-204);
            # accounts opted into AssetTxPayment pay in their chosen
            # asset when it covers the fee (assets.py)
            author = self.state.get("system", "author", default="")
            if in_asset is not None:
                aid, asset_fee = in_asset
                self.assets.charge_fee(xt.signer, aid, asset_fee,
                                       TREASURY, author)
            else:
                self.balances.transfer(xt.signer, TREASURY, fee * 8 // 10)
                self.balances.transfer(xt.signer, author or TREASURY,
                                       fee - fee * 8 // 10)
        origin = ROOT if xt.call in ROOT_ONLY else xt.signer
        return self.apply_extrinsic(origin, xt.call, *xt.args,
                                    **dict(xt.kwargs))

    def apply_in_block(self, xt) -> None:
        """Block-execution wrapper around :meth:`apply_signed`: never
        raises (a failed dispatch becomes a deterministic
        ExtrinsicFailed event, identical on every replica), and records
        the transaction-lifecycle artifacts the Ethereum RPC serves —
        tx-hash -> (block, index) plus a receipt with status, gas used,
        contract address, and the block-local log range (the
        pallet-ethereum / fc-rpc receipt mapping,
        /root/reference/node/src/rpc.rs:229-328). Receipts live in
        consensus state, so they reorg/rewind with their block."""
        from .. import codec

        block = self.state.block
        idx = self.state.get("ethereum", "count", block, default=0)
        log_start = self.evm.log_seq(block)
        call = getattr(xt, "call", "<malformed>")
        try:
            txhash = hashlib.sha256(codec.encode(xt)).digest()
        except Exception:
            txhash = None              # unencodable: skip the eth view
        try:
            self.apply_signed(xt)
        except DispatchError as e:
            self.state.deposit_event("system", "ExtrinsicFailed",
                                     call=call, error=e.name)
            status, error = 0, e.name
            gas_used = getattr(e, "evm_gas_used", 0) \
                or CALL_WEIGHTS.get(call, 0)
            contract = None
        else:
            status, error = 1, ""
            gas_used, contract = CALL_WEIGHTS.get(call, 0), None
            if call in ("evm.call", "evm.deploy"):
                gas_used, contract = self.state.get(
                    "evm", "last_exec", default=(0, None))
        if txhash is None:
            return
        prev = self.state.get("ethereum", "txloc", txhash)
        if prev is not None:
            # success-write-wins: a re-included duplicate (stale-nonce
            # replay by a later block author) must not re-point
            # eth_getTransactionReceipt at its failed dispatch — but a
            # SUCCESSFUL re-execution of a tx whose first inclusion
            # failed without consuming the nonce (e.g. CannotPayFee,
            # then funded) must supersede the failed record, or the
            # receipt would forever report failure for a transfer that
            # actually moved funds
            prev_rc = self.state.get("ethereum", "receipt", *prev)
            if status == 0 or (prev_rc is not None and prev_rc[3] == 1):
                return
            # overwrite path: the old block's receipt row stays (an
            # honest record of that block's failed attempt); only the
            # hash -> location mapping moves to the succeeding dispatch
        log_count = self.evm.log_seq(block) - log_start
        self.state.put("ethereum", "txloc", txhash, (block, idx))
        self.state.put("ethereum", "receipt", block, idx,
                       (txhash, getattr(xt, "signer", ""), call, status,
                        error, gas_used, contract, log_start, log_count))
        self.state.put("ethereum", "count", block, idx + 1)

    # receipts/logs retention: the eth view keeps this many recent
    # blocks in STATE (real chains serve older receipts from block
    # archives, not state — the repo's block store retains bodies, so
    # anything older is recomputable by replay). ~6.8 h at 6 s slots.
    ETH_HISTORY_BLOCKS = 4096
    # backlog catch-up: a chain upgrading onto this code may carry
    # arbitrarily many pre-pruner blocks; the cursor drains them a few
    # per block instead of only ever pruning block N - WINDOW
    # (review-caught), staying O(small) per block
    ETH_PRUNE_BATCH = 8

    def _prune_eth_history(self) -> None:
        target = self.state.block - self.ETH_HISTORY_BLOCKS
        if target < 0:
            return
        cursor = self.state.get("ethereum", "pruned_to", default=0)
        done = 0
        while cursor <= target and done < self.ETH_PRUNE_BATCH:
            self._prune_eth_block(cursor)
            cursor += 1
            done += 1
        if done:
            self.state.put("ethereum", "pruned_to", cursor)

    def _prune_eth_block(self, stale: int) -> None:
        count = self.state.get("ethereum", "count", stale, default=0)
        for idx in range(count):
            rc = self.state.get("ethereum", "receipt", stale, idx)
            if rc is not None and self.state.get(
                    "ethereum", "txloc", rc[0]) == (stale, idx):
                # only drop the mapping if it still points HERE — a
                # superseded failed inclusion's hash was re-pointed at
                # a newer (still-retained) successful receipt, which
                # must stay resolvable until ITS block ages out
                self.state.delete("ethereum", "txloc", rc[0])
            self.state.delete("ethereum", "receipt", stale, idx)
        if count:
            self.state.delete("ethereum", "count", stale)
        nlogs = self.state.get("evm", "log_seq", stale, default=0)
        for seq in range(nlogs):
            self.state.delete("evm", "logs", stale, seq)
        if nlogs:
            self.state.delete("evm", "log_seq", stale)

    # -- block execution ---------------------------------------------------------
    def _update_randomness(self) -> None:
        prev = self.state.get("system", "randomness", default=b"genesis")
        self.state.put("system", "randomness", hashlib.sha256(
            prev + self.state.block.to_bytes(8, "little")).digest())

    def set_randomness(self, randomness: bytes) -> None:
        """Consensus hook: epoch/VRF randomness replaces the fallback
        hash chain (reference ParentBlockRandomness)."""
        self.state.put("system", "randomness", randomness)

    def init_block(self, randomness: bytes | None = None,
                   author: str = "") -> None:
        """Advance one block and run on_initialize hooks in the
        reference's construct_runtime order (§3.4). ``randomness``
        comes from consensus (the parent VRF output); without it a
        deterministic hash chain stands in. ``author`` receives the
        20% fee share."""
        self.state.archive_events()
        self.state.block += 1
        self.state.put("system", "author", author)
        # the Timestamp role (pallet_timestamp, id 2): slots are fixed
        # 6 s, so the chain clock is DERIVED — block height times the
        # slot duration — rather than an author-supplied inherent (no
        # clock-skew surface, same monotonicity guarantee)
        self.state.put("system", "now_ms",
                       self.state.block * constants.MILLISECS_PER_BLOCK)
        if randomness is not None:
            self.set_randomness(randomness)
        else:
            self._update_randomness()
        self.audit.on_initialize()
        self.evm.on_initialize()      # base-fee market roll
        self._prune_eth_history()
        dead = self.storage_handler.on_initialize()
        self.file_bank.on_initialize(dead)
        self.credit.on_initialize()
        if self.state.block % self.config.era_blocks == 0:
            era = self.staking.current_era()
            self.im_online.era_check(era)
            self.staking.end_era(era)
            # due slashes land at the START of their apply_era, before
            # the new era's exposures are captured
            self.staking.apply_due_slashes()
            self.treasury_pallet.on_spend_period()
            self.staking.capture_exposures(era + 1)
            self.sminer.release_reward_tranches()
            # resolve the multi-phase election INSIDE block execution:
            # deposit moves/slashes and the queued-solution sweep must
            # be covered by the block's undo log (a reorg that rewinds
            # this block must rewind them too, or replicas diverge).
            # The node's session-rotation hook only READS the result.
            self.election.resolve(self.config.max_validators)
            # session rotation: audit keys follow the elected set
            elected = self.staking.electable()
            if elected:
                self.audit.set_keys(tuple(elected))
        for name, pallet, method, task_args in self.scheduler.take_due():
            self.state.begin_tx()
            try:
                getattr(self.pallets[pallet], method)(*task_args)
            except DispatchError as e:
                self.state.rollback_tx()
                self.state.deposit_event("scheduler", "TaskFailed",
                                         name=name, error=e.name)
            except Exception as e:
                self.state.rollback_tx()
                self.state.deposit_event(
                    "scheduler", "TaskFailed", name=name,
                    error=f"scheduler.TaskPanicked:{type(e).__name__}")
            else:
                self.state.commit_tx()

    def run_to_block(self, n: int) -> None:
        while self.state.block < n:
            self.init_block()

    def advance_blocks(self, n: int) -> None:
        self.run_to_block(self.state.block + n)

    # -- genesis helpers -----------------------------------------------------------
    def fund(self, who: str, amount: int) -> None:
        self.balances.mint(who, amount)
