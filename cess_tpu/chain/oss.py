"""OSS (off-chain storage gateway) registry + user authorization.

Reference: c-pallets/oss — authorize/cancel_authorize/register/update/
destroy (src/lib.rs:85-157) and the OssFindAuthor trait (:161-172)
consumed by file-bank's permission check (functions.rs:516-521).
"""
from __future__ import annotations

import dataclasses

from .. import codec
from .state import DispatchError, State

PALLET = "oss"


@codec.register
@dataclasses.dataclass(frozen=True)
class OssInfo:
    peer_id: bytes
    domain: str


class Oss:
    def __init__(self, state: State):
        self.state = state

    # -- gateway registry ----------------------------------------------------
    def register(self, who: str, peer_id: bytes, domain: str = "") -> None:
        if self.state.contains(PALLET, "oss", who):
            raise DispatchError("oss.Registered")
        self.state.put(PALLET, "oss", who, OssInfo(peer_id, domain))
        self.state.deposit_event(PALLET, "OssRegister", who=who)

    def update(self, who: str, peer_id: bytes, domain: str = "") -> None:
        if not self.state.contains(PALLET, "oss", who):
            raise DispatchError("oss.UnRegister")
        self.state.put(PALLET, "oss", who, OssInfo(peer_id, domain))
        self.state.deposit_event(PALLET, "OssUpdate", who=who)

    def destroy(self, who: str) -> None:
        if not self.state.contains(PALLET, "oss", who):
            raise DispatchError("oss.UnRegister")
        self.state.delete(PALLET, "oss", who)
        self.state.deposit_event(PALLET, "OssDestroy", who=who)

    def oss_info(self, who: str) -> OssInfo | None:
        return self.state.get(PALLET, "oss", who)

    # -- authorization --------------------------------------------------------
    def authorize(self, owner: str, operator: str) -> None:
        ops = self.state.get(PALLET, "auth", owner, default=())
        if operator in ops:
            raise DispatchError("oss.Authorized")
        self.state.put(PALLET, "auth", owner, ops + (operator,))
        self.state.deposit_event(PALLET, "Authorize", owner=owner,
                                 operator=operator)

    def cancel_authorize(self, owner: str, operator: str) -> None:
        ops = self.state.get(PALLET, "auth", owner, default=())
        if operator not in ops:
            raise DispatchError("oss.AuthorizationNotExist")
        self.state.put(PALLET, "auth", owner,
                       tuple(o for o in ops if o != operator))
        self.state.deposit_event(PALLET, "CancelAuthorize", owner=owner,
                                 operator=operator)

    # -- OssFindAuthor trait ---------------------------------------------------
    def is_authorized(self, owner: str, operator: str) -> bool:
        return operator in self.state.get(PALLET, "auth", owner, default=())
