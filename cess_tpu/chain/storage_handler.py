"""User-side space market (reference: c-pallets/storage-handler).

Buy/expand/renew purchased space priced per GiB per 30 days, a
per-user space ledger (total/used/locked, state normal/frozen/dead)
with lease-expiry sweeps, and network-wide idle/service totals.
Mirrors /root/reference/c-pallets/storage-handler/src/lib.rs:
buy_space :178-200, expansion_space :211-269, renewal_space :276-311,
lock/unlock/consume :557-588, frozen sweep :494-555, StorageHandle
trait :658-673.
"""
from __future__ import annotations

import dataclasses

from .. import codec, constants
from .balances import Balances
from .state import DispatchError, State

PALLET = "storage_handler"
TREASURY = "treasury"

NORMAL = "normal"
FROZEN = "frozen"
DEAD = "dead"

FROZEN_GRACE_BLOCKS = 10 * constants.ONE_DAY_BLOCKS  # FrozenDays=10 (runtime :955-957)


@codec.register
@dataclasses.dataclass(frozen=True)
class OwnedSpace:
    total_space: int      # bytes
    used_space: int
    locked_space: int
    start: int            # block
    deadline: int         # block
    state: str            # NORMAL | FROZEN | DEAD

    @property
    def remaining_space(self) -> int:
        return self.total_space - self.used_space - self.locked_space


class StorageHandler:
    def __init__(self, state: State, balances: Balances):
        self.state = state
        self.balances = balances
        if not state.contains(PALLET, "unit_price"):
            # genesis UnitPrice: 30 DOLLARS per GiB per 30 days
            # (reference genesis builder lib.rs:145-165)
            state.put(PALLET, "unit_price", 30 * constants.DOLLARS)

    # -- queries -----------------------------------------------------------
    def unit_price(self) -> int:
        return self.state.get(PALLET, "unit_price")

    def owned_space(self, who: str) -> OwnedSpace | None:
        return self.state.get(PALLET, "owned", who)

    def total_idle_space(self) -> int:
        return self.state.get(PALLET, "total_idle", default=0)

    def total_service_space(self) -> int:
        return self.state.get(PALLET, "total_service", default=0)

    def purchased_space(self) -> int:
        return self.state.get(PALLET, "purchased", default=0)

    # -- extrinsics ----------------------------------------------------------
    def buy_space(self, who: str, gib_count: int) -> None:
        """First purchase: gib_count GiB for 30 days (lib.rs:178-200)."""
        if gib_count <= 0:
            raise DispatchError("storage_handler.InvalidGibCount")
        if self.owned_space(who) is not None:
            raise DispatchError("storage_handler.PurchasedSpace",
                                "use expansion/renewal")
        space = gib_count * constants.GIB
        self._check_available(space)
        price = gib_count * self.unit_price()
        self.balances.transfer(who, TREASURY, price)
        now = self.state.block
        self.state.put(PALLET, "owned", who, OwnedSpace(
            total_space=space, used_space=0, locked_space=0,
            start=now, deadline=now + constants.MONTH_BLOCKS, state=NORMAL))
        self.state.put(PALLET, "purchased", self.purchased_space() + space)
        self.state.deposit_event(PALLET, "BuySpace", who=who,
                                 space=space, price=price)

    def expansion_space(self, who: str, gib_count: int) -> None:
        """Add space for the remaining lease, pro-rata (lib.rs:211-269)."""
        if gib_count <= 0:
            raise DispatchError("storage_handler.InvalidGibCount")
        own = self._require_normal(who)
        remain_blocks = own.deadline - self.state.block
        if remain_blocks <= 0:
            raise DispatchError("storage_handler.LeaseExpired")
        space = gib_count * constants.GIB
        self._check_available(space)
        price = gib_count * self.unit_price() * remain_blocks // constants.MONTH_BLOCKS
        self.balances.transfer(who, TREASURY, price)
        self.state.put(PALLET, "owned", who, dataclasses.replace(
            own, total_space=own.total_space + space))
        self.state.put(PALLET, "purchased", self.purchased_space() + space)
        self.state.deposit_event(PALLET, "ExpansionSpace", who=who,
                                 space=space, price=price)

    def renewal_space(self, who: str, days: int) -> None:
        """Extend the lease by ``days`` (lib.rs:276-311)."""
        if days <= 0:
            raise DispatchError("storage_handler.InvalidDays")
        own = self.owned_space(who)
        if own is None:
            raise DispatchError("storage_handler.NotPurchasedSpace")
        if own.state == DEAD:
            raise DispatchError("storage_handler.LeaseDead")
        gib = own.total_space // constants.GIB
        price = gib * self.unit_price() * days // 30
        self.balances.transfer(who, TREASURY, price)
        self.state.put(PALLET, "owned", who, dataclasses.replace(
            own, deadline=own.deadline + days * constants.ONE_DAY_BLOCKS,
            state=NORMAL))
        self.state.deposit_event(PALLET, "RenewalSpace", who=who,
                                 days=days, price=price)

    # -- StorageHandle trait (consumed by file-bank; lib.rs:658-673) --------
    def lock_user_space(self, who: str, space: int) -> None:
        own = self._require_normal(who)
        if own.remaining_space < space:
            raise DispatchError("storage_handler.InsufficientStorage",
                                f"remaining {own.remaining_space} < {space}")
        self.state.put(PALLET, "owned", who, dataclasses.replace(
            own, locked_space=own.locked_space + space))

    def unlock_user_space(self, who: str, space: int) -> None:
        own = self._require_owned(who)
        self.state.put(PALLET, "owned", who, dataclasses.replace(
            own, locked_space=max(0, own.locked_space - space)))

    def unlock_and_used_user_space(self, who: str, locked: int, used: int) -> None:
        """Deal completion: locked space becomes used (lib.rs:581)."""
        own = self._require_owned(who)
        self.state.put(PALLET, "owned", who, dataclasses.replace(
            own, locked_space=max(0, own.locked_space - locked),
            used_space=own.used_space + used))

    def free_used_space(self, who: str, space: int) -> None:
        own = self.owned_space(who)
        if own is None:
            return  # owner ledger may already be dead/cleared
        self.state.put(PALLET, "owned", who, dataclasses.replace(
            own, used_space=max(0, own.used_space - space)))

    def check_user_space(self, who: str, space: int) -> bool:
        own = self.owned_space(who)
        return own is not None and own.state == NORMAL \
            and own.remaining_space >= space

    # network totals (driven by sminer registrations / file lifecycle)
    def add_total_idle_space(self, space: int) -> None:
        self.state.put(PALLET, "total_idle", self.total_idle_space() + space)

    def sub_total_idle_space(self, space: int) -> None:
        self.state.put(PALLET, "total_idle",
                       max(0, self.total_idle_space() - space))

    def add_total_service_space(self, space: int) -> None:
        self.state.put(PALLET, "total_service",
                       self.total_service_space() + space)

    def sub_total_service_space(self, space: int) -> None:
        self.state.put(PALLET, "total_service",
                       max(0, self.total_service_space() - space))

    def sub_purchased_space(self, space: int) -> None:
        self.state.put(PALLET, "purchased",
                       max(0, self.purchased_space() - space))

    # -- hooks ----------------------------------------------------------------
    def on_initialize(self) -> list[str]:
        """Lease sweep (frozen_task, lib.rs:494-555): normal leases past
        deadline freeze; frozen leases past the grace period die.
        Returns the accounts that died this block (file-bank GCs their
        files, SURVEY §3.4)."""
        now = self.state.block
        died = []
        for (who,), own in self.state.iter_prefix(PALLET, "owned"):
            if own.state == NORMAL and now > own.deadline:
                self.state.put(PALLET, "owned", who,
                               dataclasses.replace(own, state=FROZEN))
                self.state.deposit_event(PALLET, "LeaseFrozen", who=who)
            elif own.state == FROZEN and now > own.deadline + FROZEN_GRACE_BLOCKS:
                self.state.put(PALLET, "owned", who,
                               dataclasses.replace(own, state=DEAD))
                self.state.deposit_event(PALLET, "LeaseDead", who=who)
                died.append(who)
        return died

    def remove_dead_lease(self, who: str) -> None:
        """Called by file-bank after GCing a dead user's files."""
        own = self.owned_space(who)
        if own is not None:
            self.state.put(PALLET, "purchased",
                           max(0, self.purchased_space() - own.total_space))
            self.state.delete(PALLET, "owned", who)

    # -- internals -----------------------------------------------------------
    def _require_owned(self, who: str) -> OwnedSpace:
        own = self.owned_space(who)
        if own is None:
            raise DispatchError("storage_handler.NotPurchasedSpace")
        return own

    def _require_normal(self, who: str) -> OwnedSpace:
        own = self._require_owned(who)
        if own.state != NORMAL:
            raise DispatchError("storage_handler.LeaseNotNormal", own.state)
        return own

    def _check_available(self, space: int) -> None:
        """Purchases are capped by unsold idle capacity (lib.rs:178-200)."""
        if self.purchased_space() + space > self.total_idle_space():
            raise DispatchError("storage_handler.InsufficientAvailableSpace")
