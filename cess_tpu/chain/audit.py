"""PoDR2 audit rounds (reference: c-pallets/audit).

Validators' offchain workers build identical challenge snapshots; the
chain accepts one at >=2/3 matching proposals; snapshotted miners
submit aggregated proofs; a randomly assigned TEE verifies; rewards and
escalating punishments apply; timeout sweeps run every block.

Mirrors /root/reference/c-pallets/audit/src/lib.rs:
save_challenge_info w/ 2/3 aggregation :377-425, generation_challenge
:901-988, submit_proof :430-479, submit_verify_result :484-545,
clear_challenge :614-655, clear_verify_mission :657-737, fault
tolerance = 2 consecutive failures (constants.rs:1-3).

The proof *content* here is the TPU PoDR2 scheme's (mu, sigma) blob
(cess_tpu/ops/podr2.py, <= SIGMA_MAX bytes); the chain treats it as
opaque, exactly like the reference.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec, constants
from ..crypto import bls12381, ed25519
from .sminer import Sminer
from .state import DispatchError, State

PALLET = "audit"

SESSION_SIGNING_CONTEXT = b"cess-tpu/audit-proposal-v1:"
VERDICT_SIGNING_CONTEXT = b"cess-tpu/tee-verdict-v1:"
VERDICT_LOG_MAX = 1024         # bounded public verdict log

CHALLENGE_LIFE_BASE = 300      # blocks; + per-miner extension like the ref
CHALLENGE_LIFE_PER_MINER = 1
VERIFY_LIFE = constants.BLOCKS_PER_HOUR   # VerifyDuration = +1h (:395-411)


@codec.register
@dataclasses.dataclass(frozen=True)
class NetSnapshot:
    total_reward: int
    total_idle_space: int
    total_service_space: int
    random_indices: tuple[int, ...]     # challenged chunk indices
    randoms: tuple[bytes, ...]          # 20-byte randoms per index


@codec.register
@dataclasses.dataclass(frozen=True)
class MinerSnapshot:
    """Per-miner challenge snapshot (ref types.rs:9-50). The owed
    fragment/filler sets are FROZEN here at challenge creation so the
    miner's proof and the TEE's verification fold over the exact same
    sets even when deals/restorals land mid-round (exact-set
    aggregation has no subset tolerance)."""
    miner: str
    idle_space: int
    service_space: int
    service_frags: tuple = ()     # owed service fragment hashes, sorted
    fillers: tuple = ()           # owed filler hashes, sorted


@codec.register
@dataclasses.dataclass(frozen=True)
class ChallengeInfo:
    net: NetSnapshot
    miners: tuple[MinerSnapshot, ...]   # still-pending miners
    start: int
    challenge_deadline: int
    verify_deadline: int
    cleared: bool = False


@codec.register
@dataclasses.dataclass(frozen=True)
class ProveInfo:
    miner: str
    snapshot: MinerSnapshot
    idle_proof: bytes
    service_proof: bytes


@codec.register
@dataclasses.dataclass(frozen=True)
class VerdictRecord:
    """A TEE verdict sealed for THIRD-PARTY re-verification: anyone
    holding the worker's on-chain 96-byte BLS pubkey can recheck
    bls12381.verify(bls_pk, verdict_message(...), bls_sig) without any
    TEE secret — the public-verifiability property the reference gets
    from enclave_verify::verify_bls
    (primitives/enclave-verify/src/lib.rs:230-235)."""
    tee: str
    miner: str
    mission_digest: bytes       # sha256 of the codec-encoded ProveInfo
    idle_ok: bool
    service_ok: bool
    bls_sig: bytes              # 48-byte G1 signature ("" = legacy worker)
    # the key that sealed this record, stamped by the chain at accept
    # time (the worker's then-current registered key): verification
    # survives the TEE exiting and re-registering with a NEW key, as
    # long as the stamp is in tee_worker.bls_keys_of's trusted set
    bls_pk: bytes = b""


def verdict_message(tee: str, mission_digest: bytes, idle_ok: bool,
                    service_ok: bool) -> bytes:
    """The exact bytes a TEE master key signs for one verify result."""
    return (VERDICT_SIGNING_CONTEXT
            + codec.encode((tee, mission_digest, idle_ok, service_ok)))


def mission_digest(mission: ProveInfo) -> bytes:
    return hashlib.sha256(codec.encode(mission)).digest()


def reverify_verdict(record: VerdictRecord, bls_pk: bytes) -> bool:
    """Public re-verification of a stored verdict — pure function of
    on-chain data, no secrets."""
    return bls12381.verify(
        bls_pk, verdict_message(record.tee, record.mission_digest,
                                record.idle_ok, record.service_ok),
        record.bls_sig)


def reverify_verdicts_batch(records, bls_keys: dict) -> bool:
    """Audit the WHOLE sealed log in one pairing product (the
    cess_teeVerdicts RPC output feeds straight in): ~N times cheaper
    than per-record verification for an external auditor. Duplicate
    messages are handled — exact duplicates collapse into one check,
    and message collisions with differing signatures verify
    individually (deterministic BLS: at most one can be valid) — so a
    False ALWAYS means some record is forged; the caller locates it
    with per-record reverify_verdict."""
    def key_for(r) -> bytes | None:
        """The key this record verifies under: its stamped sealing key
        when it belongs to the TEE's trusted set (bls_keys values may
        be one key or the full era history), else the newest key."""
        allowed = bls_keys.get(r.tee)
        if allowed is None:
            return None
        if isinstance(allowed, (bytes, bytearray)):
            allowed = (bytes(allowed),)
        else:
            allowed = tuple(allowed)
        if not allowed:
            return None
        if r.bls_pk:
            return r.bls_pk if r.bls_pk in allowed else None
        return allowed[-1]

    seen: dict[bytes, bytes] = {}      # message -> signature
    uniq: list[VerdictRecord] = []
    singles: list[VerdictRecord] = []
    for r in records:
        msg = verdict_message(r.tee, r.mission_digest, r.idle_ok,
                              r.service_ok)
        if msg not in seen:
            seen[msg] = r.bls_sig
            uniq.append(r)
        elif seen[msg] != r.bls_sig:
            # same message, different signature: BLS signatures are
            # deterministic, so at most one can be valid — check these
            # individually instead of poisoning the aggregate
            singles.append(r)
        # exact duplicates: one aggregated check covers both
    for r in singles:
        pk = key_for(r)
        if pk is None or not reverify_verdict(r, pk):
            return False
    if not uniq:
        return True
    try:
        agg = bls12381.aggregate([r.bls_sig for r in uniq])
    except ValueError:
        return False
    pairs = []
    for r in uniq:
        pk = key_for(r)
        if pk is None:
            return False
        pairs.append((pk, verdict_message(r.tee, r.mission_digest,
                                          r.idle_ok, r.service_ok)))
    return bls12381.aggregate_verify(pairs, agg)


class Audit:
    def __init__(self, state: State, sminer: Sminer, tee_worker=None,
                 storage_handler=None, file_bank=None,
                 challenge_life: int = CHALLENGE_LIFE_BASE,
                 verify_life: int = VERIFY_LIFE):
        self.state = state
        self.sminer = sminer
        self.tee_worker = tee_worker        # runtime wiring
        self.storage_handler = storage_handler
        self.file_bank = file_bank
        self.challenge_life = challenge_life
        self.verify_life = verify_life

    # -- session keys -------------------------------------------------------
    def set_keys(self, validators: tuple[str, ...]) -> None:
        """Session hook: the audit key set (lib.rs:1104-1142)."""
        self.state.put(PALLET, "keys", tuple(validators))

    def keys(self) -> tuple[str, ...]:
        return self.state.get(PALLET, "keys", default=())

    # -- challenge generation (OCW side; lib.rs:901-988) ---------------------
    def generation_challenge(self) -> tuple[NetSnapshot, tuple[MinerSnapshot, ...]]:
        """Deterministic snapshot every validator's OCW reproduces:
        all positive miners + 46/1000 random chunk indices + randoms."""
        miners = []
        for w in self.sminer.all_miners():
            m = self.sminer.miner(w)
            # frozen miners still hold data and stay challenged; only
            # exited/locked ones leave the audit set (lib.rs:901-988)
            if m.state in ("positive", "frozen") \
                    and (m.idle_space or m.service_space):
                service = tuple(sorted(
                    k[0] for k, _ in self.state.iter_prefix(
                        "file_bank", "frag_of_miner", w)))
                fillers = tuple(sorted(
                    self.file_bank.filler_hashes(w)
                    if self.file_bank else ()))
                miners.append(MinerSnapshot(w, m.idle_space,
                                            m.service_space, service,
                                            fillers))
        miners = tuple(miners[:constants.CHALLENGE_MINER_MAX])
        seed = self.state.get("system", "randomness", default=b"")
        n_chunks = constants.CHUNK_COUNT * constants.CHALLENGE_RATE_NUM \
            // constants.CHALLENGE_RATE_DEN + 1   # 47 (:956-964)
        indices = []
        randoms = []
        for i in range(n_chunks):
            h = hashlib.sha256(seed + i.to_bytes(4, "little")).digest()
            indices.append(int.from_bytes(h[:4], "little") % constants.CHUNK_COUNT)
            randoms.append(h[4:4 + constants.CHALLENGE_RANDOM_LEN])
        total = self.sminer.reward_pool_balance()
        net = NetSnapshot(
            total_reward=total,
            total_idle_space=(self.storage_handler.total_idle_space()
                              if self.storage_handler else 0),
            total_service_space=(self.storage_handler.total_service_space()
                                 if self.storage_handler else 0),
            random_indices=tuple(indices), randoms=tuple(randoms))
        return net, miners

    @staticmethod
    def snapshot_digest(net: NetSnapshot,
                        miners: tuple[MinerSnapshot, ...]) -> bytes:
        return hashlib.sha256(codec.encode((net, miners))).digest()

    # -- proposal aggregation (lib.rs:377-425) --------------------------------
    def save_challenge_info(self, validator: str, net: NetSnapshot,
                            miners: tuple[MinerSnapshot, ...],
                            signature: bytes) -> None:
        """Unsigned-transaction analog: ``signature`` is the session
        key's ed25519 signature over the snapshot digest, checked
        against the on-chain session-key registry — the reference's
        check_unsign/validate_unsigned (lib.rs:595-611,739-772).

        Aggregation counts DISTINCT voters per digest (a frozenset),
        so a validator alternating votes between digests can never
        raise any digest's count above one — the vote-switching
        count-pumping of the round-1 increment scheme is impossible
        by construction."""
        keys = self.keys()
        if validator not in keys:
            raise DispatchError("audit.NotAuditKey", validator)
        session_pub = self.state.get("system", "session_key", validator)
        if session_pub is None:
            raise DispatchError("audit.NoSessionKey", validator)
        digest = self.snapshot_digest(net, miners)
        if not ed25519.verify(session_pub, SESSION_SIGNING_CONTEXT + digest,
                              signature):
            raise DispatchError("audit.BadSessionSignature", validator)
        if self.challenge() is not None:
            raise DispatchError("audit.ChallengeInProgress")
        now = self.state.block
        # voters kept as a SORTED tuple: frozenset repr order is
        # PYTHONHASHSEED-dependent and would poison the state root
        # across processes
        voters, born = self.state.get(PALLET, "proposal", digest,
                                      default=((), now))
        if born + self.challenge_life < now:
            # stale proposal: old votes must not count toward quorum —
            # this vote starts a fresh accumulation window
            voters, born = (), now
        if validator in voters:
            raise DispatchError("audit.AlreadyProposed")
        voters = tuple(sorted((*voters, validator)))
        # keep the FIRST-SEEN born stamp: refreshing it on every vote
        # would let a trickle of votes keep a digest alive forever
        self.state.put(PALLET, "proposal", digest, (voters, born))
        # prune stale proposals so failed rounds don't leak state
        for (k,), (_, born) in list(self.state.iter_prefix(PALLET,
                                                           "proposal")):
            if born + self.challenge_life < now:
                self.state.delete(PALLET, "proposal", k)
        if len(voters) * 3 >= len(keys) * 2:
            life = self.challenge_life + CHALLENGE_LIFE_PER_MINER * len(miners)
            self.state.put(PALLET, "challenge", ChallengeInfo(
                net=net, miners=miners, start=now,
                challenge_deadline=now + life,
                verify_deadline=now + life + self.verify_life))
            for (k,), _ in list(self.state.iter_prefix(PALLET, "proposal")):
                self.state.delete(PALLET, "proposal", k)
            self.state.deposit_event(PALLET, "ChallengeStart", start=now,
                                     miners=len(miners))

    def verdicts(self) -> tuple[VerdictRecord, ...]:
        """The bounded public log of BLS-sealed TEE verdicts."""
        return self.state.get(PALLET, "verdicts", default=())

    def challenge(self) -> ChallengeInfo | None:
        return self.state.get(PALLET, "challenge")

    # -- proofs (lib.rs:430-479) ----------------------------------------------
    def submit_proof(self, miner: str, idle_proof: bytes,
                     service_proof: bytes) -> None:
        ch = self.challenge()
        if ch is None or ch.cleared:
            raise DispatchError("audit.NoChallenge")
        if self.state.block > ch.challenge_deadline:
            raise DispatchError("audit.ChallengeExpired")
        # proofs are opaque WIRE BYTES; the SIGMA_MAX cap measures the
        # actual serialized size (runtime/src/lib.rs:992), not a
        # self-reported length
        if not (isinstance(idle_proof, bytes)
                and isinstance(service_proof, bytes)):
            raise DispatchError("audit.MalformedProof")
        if len(idle_proof) > constants.SIGMA_MAX \
                or len(service_proof) > constants.SIGMA_MAX:
            raise DispatchError("audit.ProofTooLarge")
        snap = next((s for s in ch.miners if s.miner == miner), None)
        if snap is None:
            raise DispatchError("audit.NotChallengedMiner")
        # pop own snapshot (:454-474)
        self.state.put(PALLET, "challenge", dataclasses.replace(
            ch, miners=tuple(s for s in ch.miners if s.miner != miner)))
        tee = self._random_tee(miner)
        missions = self.state.get(PALLET, "unverify", tee, default=())
        if len(missions) >= constants.VERIFY_MISSION_MAX:
            raise DispatchError("audit.TeeOverloaded", tee)
        self.state.put(PALLET, "unverify", tee, missions + (ProveInfo(
            miner=miner, snapshot=snap, idle_proof=idle_proof,
            service_proof=service_proof),))
        # submitting at all resets the missed-challenge strike ladder
        self.state.delete(PALLET, "clear_strikes", miner)
        self.state.deposit_event(PALLET, "SubmitProof", miner=miner, tee=tee)

    def _random_tee(self, material: str) -> str:
        tees = self.tee_worker.controller_list() if self.tee_worker else ()
        if not tees:
            raise DispatchError("audit.NoTeeWorker")
        seed = self.state.get("system", "randomness", default=b"")
        h = hashlib.sha256(seed + material.encode()).digest()
        return sorted(tees)[int.from_bytes(h[:4], "little") % len(tees)]

    # -- verification results (lib.rs:484-545) ---------------------------------
    def submit_verify_result(self, tee: str, miner: str, idle_ok: bool,
                             service_ok: bool, bls_sig: bytes = b"") -> None:
        missions = self.state.get(PALLET, "unverify", tee, default=())
        mission = next((p for p in missions if p.miner == miner), None)
        if mission is None:
            raise DispatchError("audit.NonExistentMission")
        worker = self.tee_worker.worker(tee) if self.tee_worker else None
        if worker is not None and worker.bls_pk:
            # a worker that registered a BLS master key MUST seal every
            # verdict; the chain checks the pairing so the sealed record
            # below is verifiable by anyone from on-chain data alone
            digest = mission_digest(mission)
            if not bls12381.verify(
                    worker.bls_pk,
                    verdict_message(tee, digest, idle_ok, service_ok),
                    bls_sig):
                raise DispatchError("audit.BadVerdictSignature")
            log = self.state.get(PALLET, "verdicts", default=())
            log += (VerdictRecord(tee=tee, miner=miner,
                                  mission_digest=digest, idle_ok=idle_ok,
                                  service_ok=service_ok, bls_sig=bls_sig,
                                  bls_pk=worker.bls_pk),)
            self.state.put(PALLET, "verdicts", log[-VERDICT_LOG_MAX:])
        rest = tuple(p for p in missions if p.miner != miner)
        if rest:
            self.state.put(PALLET, "unverify", tee, rest)
        else:
            self.state.delete(PALLET, "unverify", tee)
        ch = self.challenge()
        if ch is None:
            return
        if idle_ok and service_ok:
            self.state.delete(PALLET, "fail_count", miner)
            self.sminer.calculate_miner_reward(
                miner, ch.net.total_reward, ch.net.total_idle_space,
                ch.net.total_service_space, mission.snapshot.idle_space,
                mission.snapshot.service_space)
        else:
            fails = self.state.get(PALLET, "fail_count", miner, default=0) + 1
            self.state.put(PALLET, "fail_count", miner, fails)
            if fails >= constants.AUDIT_FAULT_TOLERANCE:
                if not idle_ok:
                    self.sminer.idle_punish(miner)
                if not service_ok:
                    self.sminer.service_punish(miner)
                self.state.delete(PALLET, "fail_count", miner)
        if self.tee_worker:
            self.tee_worker.record_work(tee,
                                        mission.snapshot.service_space
                                        + mission.snapshot.idle_space)
        self.state.deposit_event(PALLET, "VerifyResult", miner=miner,
                                 idle=idle_ok, service=service_ok)

    # -- sweeps (on_initialize; lib.rs:340-345,614-737) --------------------------
    def on_initialize(self) -> None:
        ch = self.challenge()
        if ch is None:
            return
        now = self.state.block
        if not ch.cleared and now > ch.challenge_deadline:
            self._clear_challenge(ch)
            ch = self.challenge()
            if ch is None:
                return
        if now > ch.verify_deadline:
            extended = self._clear_verify_missions(ch)
            if not extended:
                self.state.delete(PALLET, "challenge")
                self.state.delete(PALLET, "verify_extended")
                self.state.deposit_event(PALLET, "ChallengeEnd", block=now)

    def _clear_challenge(self, ch: ChallengeInfo) -> None:
        """Non-submitters: escalating clear punish, 3rd strike = force
        exit (:614-655)."""
        for snap in ch.miners:
            strikes = self.state.get(PALLET, "clear_strikes", snap.miner,
                                     default=0) + 1
            self.state.put(PALLET, "clear_strikes", snap.miner, strikes)
            try:
                self.sminer.clear_punish(snap.miner, strikes)
            except DispatchError:
                continue
            if strikes >= 3:
                if self.file_bank is not None:
                    self.file_bank.force_miner_exit(snap.miner)
                else:
                    self.sminer.force_exit(snap.miner)
                self.state.delete(PALLET, "clear_strikes", snap.miner)
        self.state.put(PALLET, "challenge",
                       dataclasses.replace(ch, miners=(), cleared=True))

    def _clear_verify_missions(self, ch: ChallengeInfo) -> bool:
        """Overdue TEEs: slash + credit punishment; missions reassign
        ONCE to other TEEs with an extended window (:657-737). Returns
        True if the challenge was extended for the reassigned work."""
        pending = list(self.state.iter_prefix(PALLET, "unverify"))
        if not pending:
            return False
        laggards = {tee for (tee,), _ in pending}
        for tee in sorted(laggards):
            if self.tee_worker:
                self.tee_worker.punish_scheduler(tee)
            self.state.delete(PALLET, "unverify", tee)
        already_extended = self.state.get(PALLET, "verify_extended",
                                          default=False)
        others = sorted(set(self.tee_worker.controller_list() if
                            self.tee_worker else ()) - laggards)
        if already_extended or not others:
            self.state.delete(PALLET, "verify_extended")
            return False  # drop the missions; round ends
        all_missions = [m for (_,), ms in pending for m in ms]
        for i, mission in enumerate(all_missions):
            target = others[i % len(others)]
            cur = self.state.get(PALLET, "unverify", target, default=())
            self.state.put(PALLET, "unverify", target, cur + (mission,))
        self.state.put(PALLET, "verify_extended", True)
        self.state.put(PALLET, "challenge", dataclasses.replace(
            ch, verify_deadline=ch.verify_deadline + self.verify_life))
        self.state.deposit_event(PALLET, "VerifyReassigned",
                                 missions=len(all_missions))
        return True
