"""Short account indices + preimage store (reference: pallet_indices
id 7 and pallet_preimage id 5, runtime/src/lib.rs:1486-1496).

- Indices: claimable small integers resolving to accounts (the
  short-address lookup the reference wires as its AccountId lookup).
  claim/free/transfer with a reserved deposit so squatting costs.
- Preimage: content-addressed blob store for governance calls too big
  to inline in a motion: note_preimage reserves a size-scaled deposit,
  unnote refunds it; anyone can fetch by hash. Bounded size.
"""
from __future__ import annotations

import hashlib

from .state import DispatchError, State

PALLET = "indices"
PRE_PALLET = "preimage"

INDEX_DEPOSIT = 10 ** 10          # 0.01 DOLLARS
PREIMAGE_BYTE_DEPOSIT = 10 ** 6
MAX_PREIMAGE = 128 * 1024


class Indices:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    def lookup(self, index: int) -> str | None:
        v = self.state.get(PALLET, "index", index)
        return v[0] if v is not None else None

    def claim(self, who: str, index: int) -> None:
        if not isinstance(index, int) or isinstance(index, bool) \
                or index < 0:
            raise DispatchError("indices.BadIndex")
        if self.state.contains(PALLET, "index", index):
            raise DispatchError("indices.InUse", str(index))
        self.balances.reserve(who, INDEX_DEPOSIT)
        self.state.put(PALLET, "index", index, (who, INDEX_DEPOSIT))
        self.state.deposit_event(PALLET, "IndexAssigned", who=who,
                                 index=index)

    def free(self, who: str, index: int) -> None:
        v = self.state.get(PALLET, "index", index)
        if v is None or v[0] != who:
            raise DispatchError("indices.NotOwner", str(index))
        self.balances.unreserve(who, v[1])
        self.state.delete(PALLET, "index", index)
        self.state.deposit_event(PALLET, "IndexFreed", index=index)

    def transfer(self, who: str, index: int, new: str) -> None:
        """Move the index (deposit moves with it: the old owner is
        refunded, the new owner pays)."""
        v = self.state.get(PALLET, "index", index)
        if v is None or v[0] != who:
            raise DispatchError("indices.NotOwner", str(index))
        if not isinstance(new, str) or not new:
            raise DispatchError("indices.BadIndex", "owner")
        self.balances.reserve(new, INDEX_DEPOSIT)
        self.balances.unreserve(who, v[1])
        self.state.put(PALLET, "index", index, (new, INDEX_DEPOSIT))
        self.state.deposit_event(PALLET, "IndexAssigned", who=new,
                                 index=index)


class Preimage:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    def note_preimage(self, who: str, blob: bytes) -> bytes:
        if not isinstance(blob, bytes) or not blob \
                or len(blob) > MAX_PREIMAGE:
            raise DispatchError("preimage.TooBig")
        h = hashlib.sha256(blob).digest()
        if self.state.contains(PRE_PALLET, "blob", h):
            raise DispatchError("preimage.AlreadyNoted")
        deposit = len(blob) * PREIMAGE_BYTE_DEPOSIT
        self.balances.reserve(who, deposit)
        self.state.put(PRE_PALLET, "blob", h, (who, deposit, blob))
        self.state.deposit_event(PRE_PALLET, "Noted", hash=h,
                                 size=len(blob))
        return h

    def unnote_preimage(self, who: str, h: bytes) -> None:
        v = self.state.get(PRE_PALLET, "blob", h)
        if v is None or v[0] != who:
            raise DispatchError("preimage.NotNoter")
        self.balances.unreserve(who, v[1])
        self.state.delete(PRE_PALLET, "blob", h)
        self.state.deposit_event(PRE_PALLET, "Cleared", hash=h)

    def preimage(self, h: bytes) -> bytes | None:
        v = self.state.get(PRE_PALLET, "blob", h)
        return v[2] if v is not None else None
