"""Fungible asset classes + fee payment in assets (reference:
pallet_assets + pallet_asset_tx_payment,
/root/reference/runtime/src/lib.rs:1490-1502 ids 12-13).

Capability parity, redesigned native:
- asset classes with the reference's four-role team (owner / issuer /
  admin / freezer), min_balance dust rule (a transfer may not strand a
  destination below it; a debit that would leave dust burns the
  remainder), per-account and whole-asset freezing, and metadata.
- the AssetTxPayment role — "pay transaction fees in an asset" — is an
  on-chain ACCOUNT PREFERENCE (``set_fee_asset``) instead of the
  reference's per-extrinsic SignedExtension field: the capability is
  identical (fees charged in the asset at a governance-set conversion
  rate, split 80/20 treasury/author like native fees), but the wire
  format of signed extrinsics stays unchanged. The preference only
  takes effect for assets with a root-set fee rate, and fee charging
  falls back to native tokens when the asset can't cover the fee —
  a stale preference can never brick an account.
"""
from __future__ import annotations

import dataclasses

from .. import codec
from .. import constants
from .state import DispatchError, State

PALLET = "assets"
MAX_METADATA = 64
# pallet_assets reserves AssetDeposit on create so asset-id squatting
# and state growth aren't free; refunded by destroy
ASSET_DEPOSIT = 10 * constants.DOLLARS


@codec.register
@dataclasses.dataclass(frozen=True)
class AssetDetails:
    owner: str
    issuer: str
    admin: str
    freezer: str
    supply: int
    min_balance: int
    frozen: bool = False


@codec.register
@dataclasses.dataclass(frozen=True)
class AssetMetadata:
    name: str
    symbol: str
    decimals: int


class Assets:
    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    # -- queries -------------------------------------------------------------
    def asset(self, asset_id: int) -> AssetDetails | None:
        return self.state.get(PALLET, "asset", asset_id)

    def balance(self, asset_id: int, who: str) -> int:
        return self.state.get(PALLET, "account", asset_id, who, default=0)

    def metadata(self, asset_id: int) -> AssetMetadata | None:
        return self.state.get(PALLET, "metadata", asset_id)

    def _require(self, asset_id: int) -> AssetDetails:
        a = self.asset(asset_id)
        if a is None:
            raise DispatchError("assets.Unknown", str(asset_id))
        return a

    @staticmethod
    def _check_amount(v) -> int:
        if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
            raise DispatchError("assets.BadAmount")
        return v

    # -- lifecycle -----------------------------------------------------------
    def create(self, who: str, asset_id: int, min_balance: int = 1) -> None:
        """Permissionless create: caller becomes the whole team and
        reserves ASSET_DEPOSIT, refunded on destroy (pallet_assets
        create + AssetDeposit)."""
        if not isinstance(asset_id, int) or isinstance(asset_id, bool) \
                or asset_id < 0:
            raise DispatchError("assets.BadAssetId")
        if self.asset(asset_id) is not None:
            raise DispatchError("assets.InUse", str(asset_id))
        if not isinstance(min_balance, int) or isinstance(min_balance, bool) \
                or min_balance < 1:
            raise DispatchError("assets.BadMinBalance")
        self.balances.reserve(who, ASSET_DEPOSIT)
        self.state.put(PALLET, "deposit", asset_id, (who, ASSET_DEPOSIT))
        self.state.put(PALLET, "asset", asset_id, AssetDetails(
            owner=who, issuer=who, admin=who, freezer=who, supply=0,
            min_balance=min_balance))
        self.state.deposit_event(PALLET, "Created", asset_id=asset_id,
                                 owner=who)

    def destroy(self, who: str, asset_id: int) -> None:
        """Owner removes a fully-burned asset class; the creation
        deposit returns to whoever reserved it (pallet_assets destroy,
        collapsed to the supply == 0 case — accounts must be burned
        first, so no unbounded teardown inside one dispatch)."""
        a = self._require(asset_id)
        if who != a.owner:
            raise DispatchError("assets.NoPermission")
        if a.supply != 0:
            raise DispatchError("assets.InUse", "supply not zero")
        for suffix, _ in list(self.state.iter_prefix(PALLET, "account",
                                                     asset_id)):
            self.state.delete(PALLET, "account", asset_id, *suffix)
        for suffix, _ in list(self.state.iter_prefix(PALLET, "frozen",
                                                     asset_id)):
            self.state.delete(PALLET, "frozen", asset_id, *suffix)
        dep = self.state.get(PALLET, "deposit", asset_id)
        if dep is not None:
            self.balances.unreserve(dep[0], dep[1])
            self.state.delete(PALLET, "deposit", asset_id)
        self.state.delete(PALLET, "asset", asset_id)
        self.state.delete(PALLET, "metadata", asset_id)
        self.state.delete(PALLET, "fee_rate", asset_id)
        self.state.deposit_event(PALLET, "Destroyed", asset_id=asset_id)

    def set_team(self, who: str, asset_id: int, issuer: str, admin: str,
                 freezer: str) -> None:
        a = self._require(asset_id)
        if who != a.owner:
            raise DispatchError("assets.NoPermission")
        self.state.put(PALLET, "asset", asset_id, dataclasses.replace(
            a, issuer=issuer, admin=admin, freezer=freezer))

    def transfer_ownership(self, who: str, asset_id: int,
                           new_owner: str) -> None:
        a = self._require(asset_id)
        if who != a.owner:
            raise DispatchError("assets.NoPermission")
        self.state.put(PALLET, "asset", asset_id,
                       dataclasses.replace(a, owner=new_owner))

    def set_metadata(self, who: str, asset_id: int, name: str,
                     symbol: str, decimals: int) -> None:
        a = self._require(asset_id)
        if who != a.owner:
            raise DispatchError("assets.NoPermission")
        if not (isinstance(name, str) and isinstance(symbol, str)
                and len(name) <= MAX_METADATA
                and len(symbol) <= MAX_METADATA
                and isinstance(decimals, int)
                and 0 <= decimals <= 38):
            raise DispatchError("assets.BadMetadata")
        self.state.put(PALLET, "metadata", asset_id, AssetMetadata(
            name=name, symbol=symbol, decimals=decimals))

    # -- supply --------------------------------------------------------------
    def mint(self, who: str, asset_id: int, beneficiary: str,
             amount: int) -> None:
        a = self._require(asset_id)
        amount = self._check_amount(amount)
        if who != a.issuer:
            raise DispatchError("assets.NoPermission")
        have = self.balance(asset_id, beneficiary)
        if have + amount < a.min_balance:
            raise DispatchError("assets.BelowMinimum")
        self.state.put(PALLET, "account", asset_id, beneficiary,
                       have + amount)
        self.state.put(PALLET, "asset", asset_id,
                       dataclasses.replace(a, supply=a.supply + amount))
        self.state.deposit_event(PALLET, "Issued", asset_id=asset_id,
                                 to=beneficiary, amount=amount)

    def burn(self, who: str, asset_id: int, target: str,
             amount: int) -> None:
        a = self._require(asset_id)
        amount = self._check_amount(amount)
        if who != a.admin:
            raise DispatchError("assets.NoPermission")
        burned = self._debit(asset_id, a, target, amount)
        self.state.deposit_event(PALLET, "Burned", asset_id=asset_id,
                                 who=target, amount=burned)

    def _withdraw(self, asset_id: int, a: AssetDetails, who: str,
                  amount: int) -> int:
        """THE one implementation of the min_balance debit rule: remove
        ``amount`` from ``who``; a remainder below min_balance is dust.
        Returns the dust (which has left the account but NOT yet been
        burned from supply — the caller decides where amount goes)."""
        have = self.balance(asset_id, who)
        if have < amount:
            raise DispatchError("assets.BalanceLow")
        left = have - amount
        dust = 0
        if 0 < left < a.min_balance:
            dust, left = left, 0
        if left:
            self.state.put(PALLET, "account", asset_id, who, left)
        else:
            self.state.delete(PALLET, "account", asset_id, who)
        return dust

    def _burn_supply(self, asset_id: int, amount: int) -> None:
        if amount:
            a = self._require(asset_id)
            self.state.put(PALLET, "asset", asset_id,
                           dataclasses.replace(a, supply=a.supply - amount))

    def _debit(self, asset_id: int, a: AssetDetails, who: str,
               amount: int) -> int:
        """Burn ``amount`` (plus any dust) out of circulation; returns
        the total removed."""
        dust = self._withdraw(asset_id, a, who, amount)
        self._burn_supply(asset_id, amount + dust)
        return amount + dust

    # -- transfers -----------------------------------------------------------
    def transfer(self, who: str, asset_id: int, dest: str,
                 amount: int) -> None:
        a = self._require(asset_id)
        amount = self._check_amount(amount)
        if a.frozen or self.state.get(PALLET, "frozen", asset_id, who,
                                      default=False):
            raise DispatchError("assets.Frozen")
        if self.balance(asset_id, dest) + amount < a.min_balance:
            raise DispatchError("assets.BelowMinimum")
        if who == dest:
            # identity after validation: a round-trip through _withdraw
            # would burn a sub-min_balance remainder as dust on an
            # intent-neutral operation
            if self.balance(asset_id, who) < amount:
                raise DispatchError("assets.BalanceLow")
            self.state.deposit_event(PALLET, "Transferred",
                                     asset_id=asset_id, src=who, dst=dest,
                                     amount=amount)
            return
        dust = self._withdraw(asset_id, a, who, amount)
        # credit AFTER the debit, re-reading the destination: a
        # self-transfer is then the identity it should be (stale
        # pre-debit reads let who == dest mint, review-reproduced)
        self.state.put(PALLET, "account", asset_id, dest,
                       self.balance(asset_id, dest) + amount)
        self._burn_supply(asset_id, dust)
        self.state.deposit_event(PALLET, "Transferred", asset_id=asset_id,
                                 src=who, dst=dest, amount=amount)

    # -- freezing ------------------------------------------------------------
    def freeze(self, who: str, asset_id: int, target: str) -> None:
        a = self._require(asset_id)
        if who != a.freezer:
            raise DispatchError("assets.NoPermission")
        self.state.put(PALLET, "frozen", asset_id, target, True)

    def thaw(self, who: str, asset_id: int, target: str) -> None:
        a = self._require(asset_id)
        if who != a.admin:
            raise DispatchError("assets.NoPermission")
        self.state.delete(PALLET, "frozen", asset_id, target)

    def freeze_asset(self, who: str, asset_id: int) -> None:
        a = self._require(asset_id)
        if who != a.freezer:
            raise DispatchError("assets.NoPermission")
        self.state.put(PALLET, "asset", asset_id,
                       dataclasses.replace(a, frozen=True))

    def thaw_asset(self, who: str, asset_id: int) -> None:
        a = self._require(asset_id)
        if who != a.admin:
            raise DispatchError("assets.NoPermission")
        self.state.put(PALLET, "asset", asset_id,
                       dataclasses.replace(a, frozen=False))

    # -- fee payment in assets (pallet_asset_tx_payment role) ----------------
    def set_fee_rate(self, asset_id: int, num: int, den: int) -> None:
        """Root: asset units charged per native fee unit = num/den
        (the reference's asset-conversion config)."""
        self._require(asset_id)
        if not (isinstance(num, int) and isinstance(den, int)
                and num > 0 and den > 0):
            raise DispatchError("assets.BadRate")
        self.state.put(PALLET, "fee_rate", asset_id, (num, den))

    def fee_rate(self, asset_id: int):
        return self.state.get(PALLET, "fee_rate", asset_id)

    def set_fee_asset(self, who: str, asset_id) -> None:
        """Opt in (or out, with None) to paying fees in an asset."""
        if asset_id is None:
            self.state.delete(PALLET, "fee_asset", who)
            return
        if self.fee_rate(asset_id) is None:
            raise DispatchError("assets.NoFeeRate", str(asset_id))
        self.state.put(PALLET, "fee_asset", who, asset_id)

    def fee_asset_of(self, who: str):
        return self.state.get(PALLET, "fee_asset", who)

    def fee_in_asset(self, who: str, native_fee: int):
        """(asset_id, asset_fee) if the account's preference can cover
        this fee, else None (caller falls back to native charging)."""
        asset_id = self.fee_asset_of(who)
        if asset_id is None or native_fee <= 0:
            return None
        a = self.asset(asset_id)
        rate = self.fee_rate(asset_id)
        if a is None or rate is None or a.frozen:
            return None
        fee = -(-native_fee * rate[0] // rate[1])    # ceil
        have = self.balance(asset_id, who)
        if have < fee or self.state.get(PALLET, "frozen", asset_id, who,
                                        default=False):
            return None
        # the debit must not strand dust below min_balance unexpectedly
        return asset_id, fee

    def charge_fee(self, who: str, asset_id: int, fee: int,
                   treasury: str, author: str) -> None:
        """Move the asset fee 80/20 treasury/author (the native split,
        runtime/src/lib.rs:190-204, applied to the chosen asset). Fee
        sinks are system accounts, exempt from the min_balance dust
        rule; a payer remainder below min_balance burns as dust."""
        a = self._require(asset_id)
        dust = self._withdraw(asset_id, a, who, fee)
        to_treasury = fee * 8 // 10
        for dest, amt in ((treasury, to_treasury),
                          (author or treasury, fee - to_treasury)):
            if amt:
                self.state.put(PALLET, "account", asset_id, dest,
                               self.balance(asset_id, dest) + amt)
        self._burn_supply(asset_id, dust)
        self.state.deposit_event(PALLET, "FeePaid", who=who,
                                 asset_id=asset_id, amount=fee)