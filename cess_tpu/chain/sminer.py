"""Storage-miner registry & economics (reference: c-pallets/sminer).

Register with staked collateral, idle/service/locked space ledger,
power = 30% idle + 70% service, proportional reward orders with
20%-immediate / 80%-over-RELEASE_NUMBER-tranches release, punishment
by collateral slash with state freeze below the collateral limit.
Mirrors /root/reference/c-pallets/sminer/src/: regnstk lib.rs:261-307,
power calc lib.rs:665-673, calculate_miner_reward lib.rs:675-733,
punish tiers lib.rs:735-807, collateral limit lib.rs:809-815,
MinerControl trait lib.rs:931-1110.
"""
from __future__ import annotations

import dataclasses

from .. import codec, constants
from .balances import Balances
from .state import DispatchError, State

PALLET = "sminer"
REWARD_POOL = "sminer_reward_pool"
FAUCET_ACCOUNT = "faucet"
FAUCET_AMOUNT = 10_000 * constants.DOLLARS   # ref lib.rs:478 (10000 TCESS)
FAUCET_INTERVAL = constants.ONE_DAY_BLOCKS   # ref one-day rate limit :470

POSITIVE = "positive"   # in service
FROZEN = "frozen"       # collateral below limit; replenish to recover
EXITING = "exiting"     # exit prep done, fragments being restored
LOCKED = "locked"       # force-exited by punishment


@codec.register
@dataclasses.dataclass(frozen=True)
class MinerInfo:
    beneficiary: str
    peer_id: bytes
    collateral: int
    debt: int
    state: str
    idle_space: int
    service_space: int
    lock_space: int


@codec.register
@dataclasses.dataclass(frozen=True)
class RewardOrder:
    total: int            # full order amount
    released: int         # paid out so far
    each_share: int       # per-tranche amount for the 80% part
    tranches_left: int


class Sminer:
    def __init__(self, state: State, balances: Balances, storage_handler=None):
        self.state = state
        self.balances = balances
        self.storage_handler = storage_handler  # set by runtime wiring

    # -- queries -----------------------------------------------------------
    def miner(self, who: str) -> MinerInfo | None:
        return self.state.get(PALLET, "miner", who)

    def all_miners(self) -> list[str]:
        return [k[0] for k, _ in self.state.iter_prefix(PALLET, "miner")]

    def is_positive(self, who: str) -> bool:
        m = self.miner(who)
        return m is not None and m.state == POSITIVE

    def power_of(self, m: MinerInfo) -> int:
        """power = idle*30% + service*70% (lib.rs:665-673)."""
        return (m.idle_space * constants.IDLE_POWER_WEIGHT_NUM
                + m.service_space * constants.SERVICE_POWER_WEIGHT_NUM
                ) // constants.POWER_WEIGHT_DEN

    def collateral_limit(self, m: MinerInfo) -> int:
        """2000 CESS x (1 + power/TiB) (lib.rs:809-815, constants.rs:27)."""
        return constants.BASE_COLLATERAL * constants.DOLLARS \
            * (1 + self.power_of(m) // constants.TIB)

    # -- extrinsics ----------------------------------------------------------
    def regnstk(self, who: str, beneficiary: str, peer_id: bytes,
                staked: int) -> None:
        """Register with staked collateral (lib.rs:261-307)."""
        if self.miner(who) is not None:
            raise DispatchError("sminer.AlreadyRegistered")
        base = constants.BASE_COLLATERAL * constants.DOLLARS
        if staked < base:
            raise DispatchError("sminer.CollateralNotUp",
                                f"{staked} < {base}")
        self.balances.reserve(who, staked)
        self.state.put(PALLET, "miner", who, MinerInfo(
            beneficiary=beneficiary, peer_id=peer_id, collateral=staked,
            debt=0, state=POSITIVE, idle_space=0, service_space=0,
            lock_space=0))
        self.state.deposit_event(PALLET, "Registered", who=who, staked=staked)

    def increase_collateral(self, who: str, amount: int) -> None:
        """Top up collateral; clears debt first, may unfreeze (lib.rs)."""
        m = self._require(who)
        self.balances.reserve(who, amount)
        remaining = amount
        debt = m.debt
        if debt > 0:
            pay = min(debt, remaining)
            debt -= pay
            remaining -= pay
            # debt repayment goes to the reward pool
            self.balances.slash_reserved(who, pay, REWARD_POOL)
        m = dataclasses.replace(m, collateral=m.collateral + remaining, debt=debt)
        if m.state == FROZEN and debt == 0 \
                and m.collateral >= self.collateral_limit(m):
            m = dataclasses.replace(m, state=POSITIVE)
            self.state.deposit_event(PALLET, "MinerUnfrozen", who=who)
        self.state.put(PALLET, "miner", who, m)
        self.state.deposit_event(PALLET, "CollateralIncreased",
                                 who=who, amount=amount)

    def faucet(self, who: str, target: str) -> None:
        """Dev/testnet faucet: dispense FAUCET_AMOUNT to ``target`` at
        most once per FAUCET_INTERVAL blocks, from the genesis faucet
        account — the reference's sminer faucet with its one-day rate
        limit (c-pallets/sminer/src/lib.rs:460-498). Anyone may pull
        for any target (matches the reference: the extrinsic takes a
        destination AccountId)."""
        if not isinstance(target, str) or not target:
            raise DispatchError("sminer.BadFaucetTarget")
        last = self.state.get(PALLET, "faucet_last", target, default=None)
        now = self.state.block
        if last is not None and now < last + FAUCET_INTERVAL:
            raise DispatchError("sminer.FaucetUsedToday", target)
        if self.balances.free(FAUCET_ACCOUNT) < FAUCET_AMOUNT:
            raise DispatchError("sminer.FaucetEmpty")
        self.balances.transfer(FAUCET_ACCOUNT, target, FAUCET_AMOUNT)
        self.state.put(PALLET, "faucet_last", target, now)
        self.state.deposit_event(PALLET, "FaucetDispensed", who=who,
                                 target=target, amount=FAUCET_AMOUNT)

    def update_beneficiary(self, who: str, beneficiary: str) -> None:
        m = self._require(who)
        self.state.put(PALLET, "miner", who,
                       dataclasses.replace(m, beneficiary=beneficiary))

    def update_peer_id(self, who: str, peer_id: bytes) -> None:
        m = self._require(who)
        self.state.put(PALLET, "miner", who,
                       dataclasses.replace(m, peer_id=peer_id))

    def commit_filler_seed(self, who: str, commitment: bytes) -> None:
        """One-time commitment to the miner's PoIS-direction filler
        seed (node/offchain.py slow_filler_bytes): the TEE certifies
        secret-seeded fillers only against this on-chain value.
        Immutable — rotating the seed would orphan certified fillers."""
        self._require(who)
        if not isinstance(commitment, bytes) or len(commitment) != 32:
            raise DispatchError("sminer.BadCommitment")
        if self.state.contains(PALLET, "filler_seed", who):
            raise DispatchError("sminer.SeedAlreadyCommitted", who)
        self.state.put(PALLET, "filler_seed", who, commitment)
        self.state.deposit_event(PALLET, "FillerSeedCommitted", who=who)

    def filler_seed_commitment_of(self, who: str) -> bytes | None:
        return self.state.get(PALLET, "filler_seed", who)

    # -- MinerControl trait (lib.rs:931-1110) --------------------------------
    def add_miner_idle_space(self, who: str, space: int) -> None:
        """Filler upload certified: miner gains idle space."""
        m = self._require(who)
        self.state.put(PALLET, "miner", who,
                       dataclasses.replace(m, idle_space=m.idle_space + space))
        if self.storage_handler:
            self.storage_handler.add_total_idle_space(space)

    def lock_space(self, who: str, space: int) -> None:
        """Reserve idle space for an assigned deal (lib.rs)."""
        m = self._require(who)
        if m.idle_space < space:
            raise DispatchError("sminer.InsufficientIdleSpace")
        self.state.put(PALLET, "miner", who, dataclasses.replace(
            m, idle_space=m.idle_space - space,
            lock_space=m.lock_space + space))

    def unlock_space(self, who: str, space: int) -> None:
        """Deal failed: locked space returns to idle."""
        m = self.miner(who)
        if m is None:
            return
        freed = min(m.lock_space, space)
        self.state.put(PALLET, "miner", who, dataclasses.replace(
            m, lock_space=m.lock_space - freed,
            idle_space=m.idle_space + freed))

    def unlock_space_to_service(self, who: str, space: int) -> None:
        """Deal complete (calculate_end): locked -> service
        (lib.rs:1002-1009)."""
        m = self._require(who)
        moved = min(m.lock_space, space)
        self.state.put(PALLET, "miner", who, dataclasses.replace(
            m, lock_space=m.lock_space - moved,
            service_space=m.service_space + moved))
        if self.storage_handler:
            self.storage_handler.sub_total_idle_space(moved)
            self.storage_handler.add_total_service_space(moved)

    def add_miner_service_space(self, who: str, space: int) -> None:
        """Restoral completion transfers fragment ownership."""
        m = self._require(who)
        self.state.put(PALLET, "miner", who, dataclasses.replace(
            m, service_space=m.service_space + space))

    def sub_miner_service_space(self, who: str, space: int) -> None:
        m = self.miner(who)
        if m is None:
            return
        self.state.put(PALLET, "miner", who, dataclasses.replace(
            m, service_space=max(0, m.service_space - space)))

    def get_miner_idle_space(self, who: str) -> int:
        m = self.miner(who)
        return m.idle_space if m else 0

    # -- rewards (lib.rs:675-733) --------------------------------------------
    def reward_pool_balance(self) -> int:
        return self.balances.free(REWARD_POOL)

    def calculate_miner_reward(self, who: str, total_reward: int,
                               total_idle: int, total_service: int,
                               snap_idle: int, snap_service: int) -> None:
        """Create a reward order proportional to snapshotted power:
        20% released immediately, 80% over RELEASE_NUMBER tranches."""
        m = self._require(who)
        total_power = (total_idle * constants.IDLE_POWER_WEIGHT_NUM
                       + total_service * constants.SERVICE_POWER_WEIGHT_NUM)
        if total_power == 0:
            return
        my_power = (snap_idle * constants.IDLE_POWER_WEIGHT_NUM
                    + snap_service * constants.SERVICE_POWER_WEIGHT_NUM)
        order_total = total_reward * my_power // total_power
        if order_total == 0:
            return
        immediate = order_total * constants.REWARD_IMMEDIATE_NUM \
            // constants.REWARD_IMMEDIATE_DEN
        rest = order_total - immediate
        each = rest // constants.RELEASE_NUMBER
        orders = self.state.get(PALLET, "reward_orders", who, default=())
        orders = orders + (RewardOrder(
            total=order_total, released=immediate, each_share=each,
            tranches_left=constants.RELEASE_NUMBER),)
        self.state.put(PALLET, "reward_orders", who, orders)
        self._payout(who, m.beneficiary, immediate)
        self.state.deposit_event(PALLET, "RewardOrdered", who=who,
                                 total=order_total, immediate=immediate)

    def release_reward_tranches(self) -> None:
        """Era hook: release one tranche of every open order."""
        for (who,), orders in list(self.state.iter_prefix(PALLET, "reward_orders")):
            m = self.miner(who)
            if m is None:
                self.state.delete(PALLET, "reward_orders", who)
                continue
            new_orders = []
            pay = 0
            for o in orders:
                if o.tranches_left <= 0:
                    continue
                amt = o.each_share if o.tranches_left > 1 \
                    else o.total - o.released  # remainder in last tranche
                pay += amt
                o = dataclasses.replace(o, released=o.released + amt,
                                        tranches_left=o.tranches_left - 1)
                if o.tranches_left > 0:
                    new_orders.append(o)
            if new_orders:
                self.state.put(PALLET, "reward_orders", who, tuple(new_orders))
            else:
                self.state.delete(PALLET, "reward_orders", who)
            if pay:
                self._payout(who, m.beneficiary, pay)

    def _payout(self, who: str, beneficiary: str, amount: int) -> None:
        pool = self.balances.free(REWARD_POOL)
        amount = min(amount, pool)
        if amount:
            self.balances.transfer(REWARD_POOL, beneficiary, amount)
            self.state.deposit_event(PALLET, "RewardPaid", who=who,
                                     amount=amount)

    # -- punishment (lib.rs:735-807) -----------------------------------------
    def deposit_punish(self, who: str, amount: int) -> None:
        """Slash collateral into the reward pool; shortfall becomes debt
        and the miner freezes until replenished."""
        m = self._require(who)
        taken = self.balances.slash_reserved(who, min(amount, m.collateral),
                                             REWARD_POOL)
        new_collateral = m.collateral - taken
        debt = m.debt + (amount - taken)
        m = dataclasses.replace(m, collateral=new_collateral, debt=debt)
        limit = self.collateral_limit(m)
        if (new_collateral < limit or debt > 0) and m.state == POSITIVE:
            m = dataclasses.replace(m, state=FROZEN)
            self.state.deposit_event(PALLET, "MinerFrozen", who=who)
        self.state.put(PALLET, "miner", who, m)
        self.state.deposit_event(PALLET, "Punished", who=who, amount=amount)

    def idle_punish(self, who: str) -> None:
        """Failed idle-proof audit (fault tolerance exceeded)."""
        m = self._require(who)
        self.deposit_punish(who, self.collateral_limit(m) // 10)

    def service_punish(self, who: str) -> None:
        m = self._require(who)
        self.deposit_punish(who, self.collateral_limit(m) // 10)

    def clear_punish(self, who: str, strike: int) -> None:
        """Missed challenge entirely: 30%/60%/100% of the collateral
        limit by consecutive strike (audit lib.rs:614-655)."""
        m = self._require(who)
        tier = constants.CLEAR_PUNISH_TIERS[
            min(strike, len(constants.CLEAR_PUNISH_TIERS)) - 1]
        self.deposit_punish(who, self.collateral_limit(m) * tier // 100)

    # -- exit ------------------------------------------------------------------
    def begin_exit(self, who: str) -> MinerInfo:
        m = self._require(who)
        if m.state != POSITIVE:
            raise DispatchError("sminer.StateNotPositive", m.state)
        if m.lock_space:
            raise DispatchError("sminer.PendingDeals")
        m = dataclasses.replace(m, state=EXITING)
        self.state.put(PALLET, "miner", who, m)
        if self.storage_handler:
            self.storage_handler.sub_total_idle_space(m.idle_space)
        self.state.deposit_event(PALLET, "MinerExitPrep", who=who)
        return m

    def force_exit(self, who: str) -> MinerInfo | None:
        """Third clear-punish strike: lock the miner (audit escalation)."""
        m = self.miner(who)
        if m is None:
            return None
        m = dataclasses.replace(m, state=LOCKED)
        self.state.put(PALLET, "miner", who, m)
        if self.storage_handler:
            self.storage_handler.sub_total_idle_space(m.idle_space)
        self.state.deposit_event(PALLET, "MinerForceExit", who=who)
        return m

    def withdraw(self, who: str) -> None:
        """After exit cooling: unreserve remaining collateral, drop the
        registration (file-bank gates this on restoral completion)."""
        m = self._require(who)
        if m.state not in (EXITING, LOCKED):
            raise DispatchError("sminer.NotExited")
        self.balances.unreserve(who, m.collateral)
        self.state.delete(PALLET, "miner", who)
        self.state.delete(PALLET, "reward_orders", who)
        self.state.deposit_event(PALLET, "MinerWithdrawn", who=who,
                                 collateral=m.collateral)

    # -- internals --------------------------------------------------------------
    def _require(self, who: str) -> MinerInfo:
        m = self.miner(who)
        if m is None:
            raise DispatchError("sminer.NotMiner", who)
        return m
