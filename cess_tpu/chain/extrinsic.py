"""Signed extrinsics: the transaction envelope + signature pipeline.

Mirrors the reference's UncheckedExtrinsic/SignedExtra stack
(/root/reference/runtime/src/lib.rs:1564-1590): a transaction carries
(signer, public key, nonce, call, args) and an ed25519 signature over
the codec-canonical payload bound to the chain's genesis hash (no
cross-chain replay). Verification happens twice, like the reference:
at pool admission (cheap pre-dispatch validity) and again inside block
execution (`Runtime.apply_signed`), because imported blocks carry
transactions the local pool never saw.
"""
from __future__ import annotations

import dataclasses

from .. import codec
from ..crypto import ed25519

SIGNING_CONTEXT = b"cess-tpu/extrinsic-v1"


@codec.register
@dataclasses.dataclass(frozen=True)
class SignedExtrinsic:
    signer: str         # account alias
    public: bytes       # 32-byte ed25519 key the alias is bound to
    nonce: int
    call: str           # "pallet.method"
    args: tuple
    kwargs: tuple       # sorted ((key, value), ...) pairs
    signature: bytes    # 64 bytes over signing_payload(...)

    def encoded(self) -> bytes:
        return codec.encode(self)

    def __len__(self) -> int:
        """True wire size (the chain's length-fee input)."""
        return len(self.encoded())


def signing_payload(genesis: bytes, signer: str, public: bytes, nonce: int,
                    call: str, args: tuple, kwargs: tuple) -> bytes:
    return SIGNING_CONTEXT + codec.encode(
        (genesis, signer, public, nonce, call, args, kwargs))


def sign_extrinsic(key: ed25519.SigningKey, genesis: bytes, signer: str,
                   nonce: int, call: str, args: tuple = (),
                   kwargs: dict | None = None) -> SignedExtrinsic:
    kw = tuple(sorted((kwargs or {}).items()))
    payload = signing_payload(genesis, signer, key.public, nonce, call,
                              tuple(args), kw)
    return SignedExtrinsic(signer=signer, public=key.public, nonce=nonce,
                           call=call, args=tuple(args), kwargs=kw,
                           signature=key.sign(payload))


def verify_signature(xt: SignedExtrinsic, genesis: bytes) -> bool:
    try:
        payload = signing_payload(genesis, xt.signer, xt.public, xt.nonce,
                                  xt.call, xt.args, xt.kwargs)
    except codec.CodecError:
        return False
    return ed25519.verify(xt.public, payload, xt.signature)
