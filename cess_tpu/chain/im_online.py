"""Liveness heartbeats (the reference's pallet_im_online).

Authorities submit one heartbeat per era
(/root/reference/runtime/src/lib.rs:514-540: ImOnline is in the
session keys and unresponsive validators become offences). Here the
node layer auto-submits a feeless signed heartbeat for each local
authority key once per era (cess_tpu/node/network.py driver and
node/net.py author loop — the OCW analog); at era end, every validator
in the era's exposure set with no heartbeat is reported to the
offences pallet (1% slash).

Network-outage guard: if NO heartbeat at all arrived in an era, the
check is skipped — a chain where nobody could submit (harness without
the driver, or a full network partition) must not slash everyone. The
reference's im-online is similarly session-gated.
"""
from __future__ import annotations

from .state import DispatchError, State

PALLET = "im_online"


class ImOnline:
    def __init__(self, state: State, staking, offences):
        self.state = state
        self.staking = staking
        self.offences = offences

    def heartbeat(self, who: str) -> None:
        """One per era per authority; duplicates are an error so the
        tx pool / pool admission naturally dedups. Only accounts in
        the era's exposed set (or declared validators) may beat —
        heartbeat is FEELESS, so an open surface would be a free-tx
        spam vector and would defeat the outage guard."""
        era = self.staking.current_era()
        if who not in self.staking.era_validators(era) \
                and who not in self.staking.validators():
            raise DispatchError("im_online.NotAuthority", who)
        if self.state.contains(PALLET, "beat", era, who):
            raise DispatchError("im_online.DuplicateHeartbeat", who)
        self.state.put(PALLET, "beat", era, who, self.state.block)
        self.state.deposit_event(PALLET, "Heartbeat", who=who, era=era)

    def has_beat(self, era: int, who: str) -> bool:
        return self.state.contains(PALLET, "beat", era, who)

    def era_check(self, era: int) -> None:
        """Era rotation hook: report validators exposed in ``era``
        that never heartbeat."""
        beats = [k[0] for k, _ in self.state.iter_prefix(PALLET, "beat",
                                                         era)]
        if not beats:
            return   # outage guard (see module docstring)
        for v in self.staking.era_validators(era):
            if v not in beats:
                self.offences.report_liveness_fault(v, era)
        # prune: this era's beats have been judged
        for (e, who), _ in list(self.state.iter_prefix(PALLET, "beat")):
            if e <= era:
                self.state.delete(PALLET, "beat", e, who)
