"""TEE "scheduler" worker registry (reference: c-pallets/tee-worker).

Register with an attestation report verified on-chain (controller and
stash binding, libp2p PeerId, PoDR2 public key); the network-wide
PoDR2 key is the first registered worker's; an MRENCLAVE whitelist
gates registration; punishment slashes the worker's stash via staking
and records a scheduler-credit punishment.
Mirrors /root/reference/c-pallets/tee-worker/src/lib.rs: register
:138-177 (verify_miner_cert -> enclave-verify lib.rs:135-219),
TeePodr2Pk :122-123, update_whitelist :210-218, ScheduleFind incl.
punish_scheduler :294-321.

Attestation: a STRUCTURED report + signer certificate chain
(cess_tpu/chain/attestation.py) — the report is parsed, its
report_data must equal the (podr2_pk, controller) binding, its
MRENCLAVE must be whitelisted, and the signing cert must chain to a
root pinned on chain — mirroring the reference's webpki chain
verification + fixed-offset quote parsing
(primitives/enclave-verify/src/lib.rs:46-219).
"""
from __future__ import annotations

import dataclasses

from ..crypto import bls12381
from ..crypto.rsa import RsaPublicKey
from .. import codec
from .attestation import (AttestationReport, SignerCert,
                          report_data_binding, verify_attestation)
from .state import DispatchError, State

PALLET = "tee_worker"


@codec.register
@dataclasses.dataclass(frozen=True)
class TeeWorkerInfo:
    controller: str
    stash: str
    peer_id: bytes
    podr2_pk: bytes
    # BLS12-381 G2 master pubkey (96B) for publicly verifiable verdict
    # signatures; empty for workers registered before the capability
    # (the reference's enclave_verify::verify_bls key material,
    # primitives/enclave-verify/src/lib.rs:230-235).
    bls_pk: bytes = b""


class TeeWorker:
    def __init__(self, state: State, staking=None, credit=None):
        self.state = state
        self.staking = staking          # runtime wiring
        self.credit = credit

    # -- governance ----------------------------------------------------------
    def update_whitelist(self, mrenclave: bytes) -> None:
        """Root: allow an enclave measurement (lib.rs:210-218)."""
        wl = self.state.get(PALLET, "whitelist", default=())
        if mrenclave not in wl:
            self.state.put(PALLET, "whitelist", wl + (mrenclave,))

    def pin_ias_signer(self, key: RsaPublicKey) -> None:
        """Root: pin an attestation ROOT key (the IAS root CA analog;
        cert chains must terminate here)."""
        if not isinstance(key, RsaPublicKey):
            raise DispatchError("tee_worker.BadRootKey")
        pins = self.state.get(PALLET, "ias_pins", default=())
        if key not in pins:
            self.state.put(PALLET, "ias_pins", pins + (key,))

    # -- registration (lib.rs:138-177) ----------------------------------------
    def register(self, controller: str, stash: str, peer_id: bytes,
                 podr2_pk: bytes, report: AttestationReport,
                 report_sig: bytes,
                 cert_chain: tuple[SignerCert, ...],
                 bls_pk: bytes = b"", bls_pop: bytes = b"") -> None:
        if self.state.contains(PALLET, "worker", controller):
            raise DispatchError("tee_worker.Registered")
        roots = self.state.get(PALLET, "ias_pins", default=())
        verify_attestation(roots, cert_chain, report, report_sig)
        wl = self.state.get(PALLET, "whitelist", default=())
        if report.mrenclave not in wl:   # parsed field, exact match
            raise DispatchError("tee_worker.NonTeeWorker",
                                "MRENCLAVE not whitelisted")
        if report.report_data != report_data_binding(podr2_pk, controller,
                                                     bls_pk):
            raise DispatchError("tee_worker.VerifyCertFailed",
                                "report_data does not bind podr2_pk"
                                " + controller")
        if bls_pk:
            # the verdict-signing master key must come with a proof of
            # possession (rogue-key discipline for later aggregation)
            if not (isinstance(bls_pk, bytes)
                    and len(bls_pk) == bls12381.PK_BYTES
                    and isinstance(bls_pop, bytes)
                    and bls12381.verify_possession(bls_pk, bls_pop)):
                raise DispatchError("tee_worker.BadBlsKey",
                                    "invalid BLS pk or possession proof")
        self.state.put(PALLET, "worker", controller, TeeWorkerInfo(
            controller=controller, stash=stash, peer_id=peer_id,
            podr2_pk=podr2_pk, bls_pk=bls_pk))
        # network PoDR2 key = first registered worker's (lib.rs:122-123)
        if not self.state.contains(PALLET, "podr2_pk"):
            self.state.put(PALLET, "podr2_pk", podr2_pk)
        self.state.deposit_event(PALLET, "RegistrationTeeWorker",
                                 controller=controller)

    def exit(self, controller: str) -> None:
        w = self.worker(controller)
        if w is None:
            raise DispatchError("tee_worker.NonTeeWorker")
        if w.bls_pk:
            # preserve the verdict-signing key: sealed verdicts in the
            # audit log must stay publicly verifiable AFTER the worker
            # leaves (an exited TEE must not launder its history).
            # APPEND-ONLY: a re-registration with a new key followed by
            # another exit must not overwrite older eras' keys
            old = self.state.get(PALLET, "retired_bls", controller,
                                 default=())
            if w.bls_pk not in old:
                self.state.put(PALLET, "retired_bls", controller,
                               old + (w.bls_pk,))
        self.state.delete(PALLET, "worker", controller)
        self.state.deposit_event(PALLET, "ExitTeeWorker",
                                 controller=controller)

    # -- queries ---------------------------------------------------------------
    def worker(self, controller: str) -> TeeWorkerInfo | None:
        return self.state.get(PALLET, "worker", controller)

    def tee_podr2_pk(self) -> bytes | None:
        return self.state.get(PALLET, "podr2_pk")

    def bls_keys_of(self, controller: str) -> tuple[bytes, ...]:
        """EVERY verdict-signing key this controller has ever held
        (live + retired eras) — the trusted set a sealed record's
        stamped key must belong to. A controller that exits and
        re-registers with a new key keeps its whole history."""
        keys = self.state.get(PALLET, "retired_bls", controller,
                              default=())
        w = self.worker(controller)
        if w is not None and w.bls_pk and w.bls_pk not in keys:
            keys = keys + (w.bls_pk,)
        return keys

    def bls_key_of(self, controller: str) -> bytes:
        """The controller's CURRENT verdict-signing key (live, else
        the most recently retired)."""
        w = self.worker(controller)
        if w is not None and w.bls_pk:
            return w.bls_pk
        keys = self.state.get(PALLET, "retired_bls", controller,
                              default=())
        return keys[-1] if keys else b""

    # -- ScheduleFind trait (lib.rs:287-321) -------------------------------------
    def controller_list(self) -> tuple[str, ...]:
        return tuple(k[0] for k, _ in self.state.iter_prefix(PALLET, "worker"))

    def punish_scheduler(self, controller: str) -> None:
        """Verify-timeout escalation: slash the stash 5% of the minimum
        validator bond + credit punishment (staking slashing.rs:694-705)."""
        w = self.worker(controller)
        if w is None:
            return
        if self.staking is not None:
            self.staking.slash_scheduler(w.stash)
        if self.credit is not None:
            self.credit.record_punishment(w.controller)
        self.state.deposit_event(PALLET, "PunishScheduler",
                                 controller=controller)

    def record_work(self, controller: str, nbytes: int) -> None:
        """Verified bytes feed the credit score (SchedulerCreditCounter)."""
        if self.credit is not None and self.worker(controller) is not None:
            self.credit.record_proceed_block_size(controller, nbytes)
