"""A real EVM bytecode interpreter behind the evm boundary.

The reference runs the full Frontier stack (pallet-evm/pallet-ethereum,
/root/reference/runtime/src/lib.rs:1310-1380) with Eth RPC
(node/src/rpc.rs:229-328). This is the framework-native execution
engine for the same boundary: a 256-bit word stack machine with gas
metering, covering the core opcode set — arithmetic / comparison /
bitwise, SHA3, environment (ADDRESS/CALLER/CALLVALUE/CALLDATA*/CODE*),
stack / memory / storage, control flow (JUMP/JUMPI/JUMPDEST/PC), PUSH /
DUP / SWAP, LOG0-4, and RETURN / REVERT / STOP / INVALID — enough to
run hand-assembled or simple compiled contracts (an ERC-20-style token
round-trips deploy -> transfer -> balanceOf through it, tests/
test_evm.py).

Deliberate deviations from mainnet EVM, documented once:
- SHA3 is NIST sha3_256 (hashlib), not Keccak-256 — contracts compiled
  for Ethereum that depend on specific keccak digests will differ; the
  dispatch/storage-slot PATTERN (hash-derived slots) works identically.
- Gas costs are simplified tiers (VERYLOW/LOW/MID/HIGH + SSTORE/SLOAD/
  LOG/SHA3/memory expansion), not the full Berlin/London schedule. Out
  of gas always consumes the limit and reverts state — an infinite
  loop can never stall block production (tested).
- Inter-contract CALL / STATICCALL / DELEGATECALL and CREATE/CREATE2
  run through host callbacks (evm.py recursion with
  commit-on-success overlays, depth cap, 63/64 gas forwarding).
  Value-carrying CALL moves EVM-domain balance with full revert
  semantics; BALANCE/SELFBALANCE read through the ``balance`` hook.
  CREATE2 addresses derive with sha256 (not keccak, per the SHA3
  deviation above): sha256("evm-create2:" || creator20 || salt32 ||
  sha256(init))[:20] — deterministic and predictable by contracts
  using the same formula, which is the property EIP-1014 exists for.
  The creator's nonce bump for CREATE/CREATE2 persists in the parent
  frame even when init reverts (mainnet semantics; geth orders the
  balance check before the bump, mirrored here) — a retried create
  derives a fresh address rather than reusing the reverted one.
- Precompiles 0x1-0x4 (ecrecover / sha256 / ripemd160 / identity)
  are serviced by the call host in evm.py; ecrecover's address
  derivation is sha3_256-based (crypto/secp256k1.py docstring).

Execution state (storage, logs) is written through the transactional
KV ``State``, so the runtime's dispatch transactionality applies:
a REVERT or OutOfGas inside ``Evm.call`` raises DispatchError and the
surrounding state tx rolls everything back.
"""
from __future__ import annotations

import dataclasses
import hashlib

U256 = 1 << 256
MASK256 = U256 - 1
MAX_MEM = 1 << 22          # 4 MiB memory hard cap (anti-DoS)
MAX_STACK = 1024

# simplified gas schedule
G_BASE = 2
G_VERYLOW = 3
G_LOW = 5
G_MID = 8
G_HIGH = 10
G_EXP = 50
G_SHA3 = 30
G_SHA3_WORD = 6
G_SLOAD = 200
G_SSTORE_SET = 20_000
G_SSTORE_RESET = 5_000
G_LOG = 375
G_LOG_TOPIC = 375
G_LOG_DATA = 8
G_MEM_WORD = 3
G_COPY_WORD = 3
G_CALL = 700
G_CREATE = 32_000
G_BALANCE = 400
G_EXT = 700


class EvmRevert(Exception):
    def __init__(self, data: bytes, gas_used: int = 0):
        self.data = data
        self.gas_used = gas_used


class EvmError(Exception):
    """Exceptional halt: out of gas, bad jump, stack violation,
    invalid opcode. Consumes all gas; state reverts."""


@dataclasses.dataclass
class Log:
    address: bytes
    topics: tuple[bytes, ...]
    data: bytes


@dataclasses.dataclass
class ExecResult:
    output: bytes
    gas_used: int
    logs: list[Log]


def sha3(data: bytes) -> bytes:
    return hashlib.sha3_256(data).digest()


class _Memory:
    def __init__(self):
        self.buf = bytearray()

    def _expand(self, end: int, gas) -> None:
        if end > MAX_MEM:
            raise EvmError("memory cap exceeded")
        if end > len(self.buf):
            new_words = (end + 31) // 32
            old_words = (len(self.buf) + 31) // 32
            gas.use(G_MEM_WORD * (new_words - old_words))
            self.buf.extend(b"\0" * (new_words * 32 - len(self.buf)))

    def load(self, off: int, gas) -> int:
        self._expand(off + 32, gas)
        return int.from_bytes(self.buf[off:off + 32], "big")

    def store(self, off: int, value: int, gas) -> None:
        self._expand(off + 32, gas)
        self.buf[off:off + 32] = value.to_bytes(32, "big")

    def store8(self, off: int, value: int, gas) -> None:
        self._expand(off + 1, gas)
        self.buf[off] = value & 0xFF

    def write(self, off: int, data: bytes, gas) -> None:
        if data:
            self._expand(off + len(data), gas)
            self.buf[off:off + len(data)] = data

    def read(self, off: int, size: int, gas) -> bytes:
        if size == 0:
            return b""
        self._expand(off + size, gas)
        return bytes(self.buf[off:off + size])


class _Gas:
    def __init__(self, limit: int):
        self.limit = limit
        self.used = 0

    def use(self, n: int) -> None:
        self.used += n
        if self.used > self.limit:
            raise EvmError("out of gas")

    @property
    def remaining(self) -> int:
        return self.limit - self.used


def _signed(x: int) -> int:
    return x - U256 if x >> 255 else x


def _valid_jumpdests(code: bytes) -> set[int]:
    """JUMPDEST positions, skipping PUSH immediates."""
    dests, i = set(), 0
    while i < len(code):
        op = code[i]
        if op == 0x5B:
            dests.add(i)
        i += (op - 0x5F + 1) if 0x60 <= op <= 0x7F else 1
    return dests


def execute(code: bytes, *, calldata: bytes = b"", caller: bytes = b"",
            address: bytes = b"", value: int = 0, gas_limit: int = 1_000_000,
            sload=None, sstore=None, static: bool = False,
            call_host=None, create_host=None, balance=None,
            extcode=None, origin: bytes = b"",
            env: dict | None = None) -> ExecResult:
    """Run ``code`` to completion.

    sload(key_int) -> int and sstore(key_int, value_int) bridge contract
    storage to the chain KV; both default to an in-memory dict (pure
    eth_call-style simulation).

    ``call_host(kind, to20, data, fwd_gas, value)`` services the
    inter-contract CALL family (kind in "call"/"static"/"delegate");
    it returns (success, returndata, gas_spent, inner_logs) and NEVER
    raises. Absent a host, CALL-family opcodes fail cleanly (push 0).
    ``static`` makes SSTORE/LOG*/CREATE* exceptional halts (STATICCALL
    frame).

    ``create_host(init, value, salt_or_None, fwd_gas)`` services
    CREATE/CREATE2; returns (addr_int_or_0, returndata, gas_spent,
    inner_logs) and never raises — addr 0 means the creation failed
    (returndata then carries the init code's revert payload, EVM
    semantics). ``balance(addr20) -> int`` backs BALANCE/SELFBALANCE
    (0 without a host). ``extcode(addr20) -> bytes`` backs
    EXTCODESIZE/EXTCODECOPY/EXTCODEHASH. ``env`` supplies block
    context: number, timestamp, chainid, basefee, gasprice, coinbase.

    Raises EvmRevert (REVERT opcode, gas charged so far) or EvmError
    (exceptional halt, all gas consumed).
    """
    local: dict[int, int] = {}
    sload = sload or (lambda k: local.get(k, 0))
    sstore = sstore or local.__setitem__
    balance = balance or (lambda a: 0)
    extcode = extcode or (lambda a: b"")
    env = env or {}
    origin = origin or caller

    gas = _Gas(gas_limit)
    mem = _Memory()
    stack: list[int] = []
    logs: list[Log] = []
    dests = _valid_jumpdests(code)
    returndata = b""               # last CALL-family return buffer
    pc = 0

    def push(v: int) -> None:
        if len(stack) >= MAX_STACK:
            raise EvmError("stack overflow")
        stack.append(v & MASK256)

    def pop() -> int:
        if not stack:
            raise EvmError("stack underflow")
        return stack.pop()

    while pc < len(code):
        op = code[pc]
        pc += 1
        # -- PUSH / DUP / SWAP families ----------------------------------
        if 0x60 <= op <= 0x7F:                      # PUSH1..PUSH32
            n = op - 0x5F
            gas.use(G_VERYLOW)
            # missing code bytes read as zeros (EVM right-pads)
            push(int.from_bytes(code[pc:pc + n].ljust(n, b"\0"), "big"))
            pc += n
        elif 0x80 <= op <= 0x8F:                    # DUP1..DUP16
            n = op - 0x7F
            gas.use(G_VERYLOW)
            if len(stack) < n:
                raise EvmError("stack underflow")
            push(stack[-n])
        elif 0x90 <= op <= 0x9F:                    # SWAP1..SWAP16
            n = op - 0x8F
            gas.use(G_VERYLOW)
            if len(stack) < n + 1:
                raise EvmError("stack underflow")
            stack[-1], stack[-n - 1] = stack[-n - 1], stack[-1]
        # -- halting ------------------------------------------------------
        elif op == 0x00:                            # STOP
            return ExecResult(b"", gas.used, logs)
        elif op == 0xF3:                            # RETURN
            off, size = pop(), pop()
            out = mem.read(off, size, gas)
            return ExecResult(out, gas.used, logs)
        elif op == 0xFD:                            # REVERT
            off, size = pop(), pop()
            raise EvmRevert(mem.read(off, size, gas), gas.used)
        # -- arithmetic ---------------------------------------------------
        elif op == 0x01:                            # ADD
            gas.use(G_VERYLOW); push(pop() + pop())
        elif op == 0x02:                            # MUL
            gas.use(G_LOW); push(pop() * pop())
        elif op == 0x03:                            # SUB
            gas.use(G_VERYLOW); a, b = pop(), pop(); push(a - b)
        elif op == 0x04:                            # DIV
            gas.use(G_LOW); a, b = pop(), pop(); push(a // b if b else 0)
        elif op == 0x05:                            # SDIV
            gas.use(G_LOW)
            a, b = _signed(pop()), _signed(pop())
            push(0 if b == 0 else abs(a) // abs(b)
                 * (1 if (a < 0) == (b < 0) else -1))
        elif op == 0x06:                            # MOD
            gas.use(G_LOW); a, b = pop(), pop(); push(a % b if b else 0)
        elif op == 0x07:                            # SMOD
            gas.use(G_LOW)
            a, b = _signed(pop()), _signed(pop())
            push(0 if b == 0 else abs(a) % abs(b) * (1 if a >= 0 else -1))
        elif op == 0x08:                            # ADDMOD
            gas.use(G_MID); a, b, n = pop(), pop(), pop()
            push((a + b) % n if n else 0)
        elif op == 0x09:                            # MULMOD
            gas.use(G_MID); a, b, n = pop(), pop(), pop()
            push((a * b) % n if n else 0)
        elif op == 0x0A:                            # EXP
            a, e = pop(), pop()
            gas.use(G_EXP + 50 * ((e.bit_length() + 7) // 8))
            push(pow(a, e, U256))
        # -- comparison / bitwise ----------------------------------------
        elif op == 0x10:                            # LT
            gas.use(G_VERYLOW); a, b = pop(), pop(); push(int(a < b))
        elif op == 0x11:                            # GT
            gas.use(G_VERYLOW); a, b = pop(), pop(); push(int(a > b))
        elif op == 0x12:                            # SLT
            gas.use(G_VERYLOW)
            a, b = _signed(pop()), _signed(pop()); push(int(a < b))
        elif op == 0x13:                            # SGT
            gas.use(G_VERYLOW)
            a, b = _signed(pop()), _signed(pop()); push(int(a > b))
        elif op == 0x14:                            # EQ
            gas.use(G_VERYLOW); push(int(pop() == pop()))
        elif op == 0x15:                            # ISZERO
            gas.use(G_VERYLOW); push(int(pop() == 0))
        elif op == 0x16:                            # AND
            gas.use(G_VERYLOW); push(pop() & pop())
        elif op == 0x17:                            # OR
            gas.use(G_VERYLOW); push(pop() | pop())
        elif op == 0x18:                            # XOR
            gas.use(G_VERYLOW); push(pop() ^ pop())
        elif op == 0x19:                            # NOT
            gas.use(G_VERYLOW); push(~pop())
        elif op == 0x1A:                            # BYTE
            gas.use(G_VERYLOW); i, x = pop(), pop()
            push((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
        elif op == 0x1B:                            # SHL
            gas.use(G_VERYLOW); s, x = pop(), pop()
            push(x << s if s < 256 else 0)
        elif op == 0x1C:                            # SHR
            gas.use(G_VERYLOW); s, x = pop(), pop()
            push(x >> s if s < 256 else 0)
        elif op == 0x1D:                            # SAR
            gas.use(G_VERYLOW); s, x = pop(), pop()
            push((_signed(x) >> min(s, 255)))
        # -- SHA3 ---------------------------------------------------------
        elif op == 0x20:                            # SHA3 (sha3_256 here)
            off, size = pop(), pop()
            gas.use(G_SHA3 + G_SHA3_WORD * ((size + 31) // 32))
            push(int.from_bytes(sha3(mem.read(off, size, gas)), "big"))
        # -- environment --------------------------------------------------
        elif op == 0x30:                            # ADDRESS
            gas.use(G_BASE); push(int.from_bytes(address, "big"))
        elif op == 0x31:                            # BALANCE
            gas.use(G_BALANCE)
            push(balance(pop().to_bytes(32, "big")[-20:]))
        elif op == 0x32:                            # ORIGIN
            gas.use(G_BASE); push(int.from_bytes(origin, "big"))
        elif op == 0x33:                            # CALLER
            gas.use(G_BASE); push(int.from_bytes(caller, "big"))
        elif op == 0x34:                            # CALLVALUE
            gas.use(G_BASE); push(value)
        elif op == 0x35:                            # CALLDATALOAD
            gas.use(G_VERYLOW); off = pop()
            chunk = calldata[off:off + 32] if off < len(calldata) else b""
            push(int.from_bytes(chunk.ljust(32, b"\0"), "big"))
        elif op == 0x36:                            # CALLDATASIZE
            gas.use(G_BASE); push(len(calldata))
        elif op == 0x37:                            # CALLDATACOPY
            doff, soff, size = pop(), pop(), pop()
            gas.use(G_VERYLOW + G_COPY_WORD * ((size + 31) // 32))
            if size:
                # cap + expansion gas BEFORE materializing the padded
                # chunk: a huge size must fail here, not after a
                # transient multi-MB ljust allocation
                mem._expand(doff + size, gas)
                chunk = calldata[soff:soff + size] \
                    if soff < len(calldata) else b""
                mem.write(doff, chunk.ljust(size, b"\0"), gas)
        elif op == 0x38:                            # CODESIZE
            gas.use(G_BASE); push(len(code))
        elif op == 0x39:                            # CODECOPY
            doff, soff, size = pop(), pop(), pop()
            gas.use(G_VERYLOW + G_COPY_WORD * ((size + 31) // 32))
            if size:
                mem._expand(doff + size, gas)
                chunk = code[soff:soff + size] if soff < len(code) else b""
                mem.write(doff, chunk.ljust(size, b"\0"), gas)
        elif op == 0x3A:                            # GASPRICE
            gas.use(G_BASE); push(env.get("gasprice", 0))
        elif op == 0x3B:                            # EXTCODESIZE
            gas.use(G_EXT)
            push(len(extcode(pop().to_bytes(32, "big")[-20:])))
        elif op == 0x3C:                            # EXTCODECOPY
            a20 = pop().to_bytes(32, "big")[-20:]
            doff, soff, size = pop(), pop(), pop()
            gas.use(G_EXT + G_COPY_WORD * ((size + 31) // 32))
            if size:
                mem._expand(doff + size, gas)
                xc = extcode(a20)
                chunk = xc[soff:soff + size] if soff < len(xc) else b""
                mem.write(doff, chunk.ljust(size, b"\0"), gas)
        elif op == 0x3F:                            # EXTCODEHASH
            gas.use(G_EXT)
            xc = extcode(pop().to_bytes(32, "big")[-20:])
            push(int.from_bytes(sha3(xc), "big") if xc else 0)
        elif op == 0x3D:                            # RETURNDATASIZE
            gas.use(G_BASE); push(len(returndata))
        elif op == 0x3E:                            # RETURNDATACOPY
            doff, soff, size = pop(), pop(), pop()
            gas.use(G_VERYLOW + G_COPY_WORD * ((size + 31) // 32))
            if soff + size > len(returndata):       # spec: exceptional
                raise EvmError("returndatacopy out of bounds")
            if size:
                mem.write(doff, returndata[soff:soff + size], gas)
        # -- block context -------------------------------------------------
        elif op == 0x41:                            # COINBASE
            gas.use(G_BASE)
            push(int.from_bytes(env.get("coinbase", b""), "big"))
        elif op == 0x42:                            # TIMESTAMP
            gas.use(G_BASE); push(env.get("timestamp", 0))
        elif op == 0x43:                            # NUMBER
            gas.use(G_BASE); push(env.get("number", 0))
        elif op == 0x46:                            # CHAINID
            gas.use(G_BASE); push(env.get("chainid", 0))
        elif op == 0x47:                            # SELFBALANCE
            gas.use(G_LOW); push(balance(address))
        elif op == 0x48:                            # BASEFEE
            gas.use(G_BASE); push(env.get("basefee", 0))
        # -- stack / memory / storage ------------------------------------
        elif op == 0x50:                            # POP
            gas.use(G_BASE); pop()
        elif op == 0x51:                            # MLOAD
            gas.use(G_VERYLOW); push(mem.load(pop(), gas))
        elif op == 0x52:                            # MSTORE
            gas.use(G_VERYLOW); off, v = pop(), pop()
            mem.store(off, v, gas)
        elif op == 0x53:                            # MSTORE8
            gas.use(G_VERYLOW); off, v = pop(), pop()
            mem.store8(off, v, gas)
        elif op == 0x54:                            # SLOAD
            gas.use(G_SLOAD); push(sload(pop()))
        elif op == 0x55:                            # SSTORE
            if static:
                raise EvmError("SSTORE in static context")
            k, v = pop(), pop()
            gas.use(G_SSTORE_SET if sload(k) == 0 and v != 0
                    else G_SSTORE_RESET)
            sstore(k, v)
        elif op == 0x56:                            # JUMP
            gas.use(G_MID); dst = pop()
            if dst not in dests:
                raise EvmError(f"bad jump dest {dst}")
            pc = dst
        elif op == 0x57:                            # JUMPI
            gas.use(G_HIGH); dst, cond = pop(), pop()
            if cond:
                if dst not in dests:
                    raise EvmError(f"bad jump dest {dst}")
                pc = dst
        elif op == 0x58:                            # PC
            gas.use(G_BASE); push(pc - 1)
        elif op == 0x59:                            # MSIZE
            gas.use(G_BASE); push(len(mem.buf))
        elif op == 0x5A:                            # GAS
            gas.use(G_BASE); push(gas.remaining)
        elif op == 0x5B:                            # JUMPDEST
            gas.use(1)
        # -- logs ---------------------------------------------------------
        elif 0xA0 <= op <= 0xA4:                    # LOG0..LOG4
            if static:
                raise EvmError("LOG in static context")
            ntopics = op - 0xA0
            off, size = pop(), pop()
            topics = tuple(pop().to_bytes(32, "big")
                           for _ in range(ntopics))
            gas.use(G_LOG + G_LOG_TOPIC * ntopics + G_LOG_DATA * size)
            logs.append(Log(address=address, topics=topics,
                            data=mem.read(off, size, gas)))
        # -- CREATE / CREATE2 (serviced by create_host) -------------------
        elif op in (0xF0, 0xF5):                    # CREATE/CREATE2
            if static:
                raise EvmError("CREATE in static context")
            gas.use(G_CREATE)
            val, off, size = pop(), pop(), pop()
            salt = pop().to_bytes(32, "big") if op == 0xF5 else None
            init = mem.read(off, size, gas)
            fwd = gas.remaining - gas.remaining // 64   # EIP-150
            if create_host is None:
                addr_int, retdata, spent, inner_logs = 0, b"", 0, []
            else:
                addr_int, retdata, spent, inner_logs = create_host(
                    init, val, salt, fwd)
            gas.use(min(spent, fwd))
            returndata = retdata            # revert payload on failure
            if addr_int:
                logs.extend(inner_logs)
            push(addr_int)
        # -- inter-contract calls (serviced by call_host) -----------------
        elif op in (0xF1, 0xF4, 0xFA):              # CALL/DELEGATECALL/
            gas.use(G_CALL)                         # STATICCALL
            gas_req, to = pop(), pop()
            val = pop() if op == 0xF1 else 0
            in_off, in_size = pop(), pop()
            out_off, out_size = pop(), pop()
            if static and op == 0xF1 and val:
                raise EvmError("value transfer in static context")
            if op == 0xF4:
                val = value     # apparent value rides along, no transfer
            data = mem.read(in_off, in_size, gas)
            if out_size:
                mem._expand(out_off + out_size, gas)
            # 63/64 forwarding rule bounds recursion cost
            fwd = min(gas_req, gas.remaining - gas.remaining // 64)
            kind = {0xF1: "call", 0xF4: "delegate", 0xFA: "static"}[op]
            if call_host is None:
                success, retdata, spent, inner_logs = 0, b"", 0, []
            else:
                success, retdata, spent, inner_logs = call_host(
                    kind, to.to_bytes(32, "big")[-20:], data, fwd, val)
            gas.use(min(spent, fwd))
            returndata = retdata
            if success:
                logs.extend(inner_logs)
            if out_size:
                mem.write(out_off,
                          retdata[:out_size].ljust(out_size, b"\0"), gas)
            push(1 if success else 0)
        else:
            raise EvmError(f"invalid/unsupported opcode 0x{op:02x}")
    return ExecResult(b"", gas.used, logs)


def initcode(runtime: bytes, ctor: bytes = b"") -> bytes:
    """Standard CREATE wrapper: INIT code that runs ``ctor`` (e.g. a
    mint-to-CALLER sequence, ending with an empty stack), CODECOPYs
    ``runtime`` into memory and RETURNs it — what Solidity
    constructors compile to."""
    # tail: PUSH2 len, PUSH2 off, PUSH1 0, CODECOPY,
    #       PUSH2 len, PUSH1 0, RETURN   -> 15 bytes
    off = len(ctor) + 15
    return ctor + bytes([
        0x61, *len(runtime).to_bytes(2, "big"),
        0x61, *off.to_bytes(2, "big"),
        0x60, 0x00, 0x39,
        0x61, *len(runtime).to_bytes(2, "big"),
        0x60, 0x00, 0xF3,
    ]) + runtime


# -- tiny assembler (tests + hand-written contracts) -----------------------

OPS = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "DIV": 0x04,
    "SDIV": 0x05, "MOD": 0x06, "SMOD": 0x07, "ADDMOD": 0x08,
    "MULMOD": 0x09, "EXP": 0x0A, "LT": 0x10, "GT": 0x11, "SLT": 0x12,
    "SGT": 0x13, "EQ": 0x14, "ISZERO": 0x15, "AND": 0x16, "OR": 0x17,
    "XOR": 0x18, "NOT": 0x19, "BYTE": 0x1A, "SHL": 0x1B, "SHR": 0x1C,
    "SAR": 0x1D, "SHA3": 0x20, "ADDRESS": 0x30, "CALLER": 0x33,
    "CALLVALUE": 0x34, "CALLDATALOAD": 0x35, "CALLDATASIZE": 0x36,
    "CALLDATACOPY": 0x37, "CODESIZE": 0x38, "CODECOPY": 0x39,
    "RETURNDATASIZE": 0x3D, "RETURNDATACOPY": 0x3E,
    "BALANCE": 0x31, "ORIGIN": 0x32, "GASPRICE": 0x3A,
    "EXTCODESIZE": 0x3B, "EXTCODECOPY": 0x3C, "EXTCODEHASH": 0x3F,
    "COINBASE": 0x41, "TIMESTAMP": 0x42, "NUMBER": 0x43,
    "CHAINID": 0x46, "SELFBALANCE": 0x47, "BASEFEE": 0x48,
    "CREATE": 0xF0, "CREATE2": 0xF5,
    "CALL": 0xF1, "DELEGATECALL": 0xF4, "STATICCALL": 0xFA,
    "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52,
    "MSTORE8": 0x53, "SLOAD": 0x54, "SSTORE": 0x55, "JUMP": 0x56,
    "JUMPI": 0x57, "PC": 0x58, "MSIZE": 0x59, "GAS": 0x5A,
    "JUMPDEST": 0x5B, "LOG0": 0xA0, "LOG1": 0xA1, "LOG2": 0xA2,
    "LOG3": 0xA3, "LOG4": 0xA4, "RETURN": 0xF3, "REVERT": 0xFD,
    "INVALID": 0xFE,
}
OPS.update({f"DUP{i}": 0x7F + i for i in range(1, 17)})
OPS.update({f"SWAP{i}": 0x8F + i for i in range(1, 17)})


def asm(*items) -> bytes:
    """Assemble a contract: strings are opcodes, ints become minimal
    PUSHn, ("label", name) defines a jump target, ("push_label", name)
    pushes its (2-byte) position. Two passes resolve labels; an
    undefined label is an assembly-time error."""
    labels: dict[str, int] = {}
    used: set[str] = set()
    out = bytearray()
    for final in (False, True):
        out = bytearray()
        for it in items:
            if isinstance(it, str):
                out.append(OPS[it])
            elif isinstance(it, int):
                n = max(1, (it.bit_length() + 7) // 8)
                out.append(0x5F + n)
                out.extend(it.to_bytes(n, "big"))
            elif isinstance(it, bytes):
                out.extend(it)
            elif isinstance(it, tuple) and it[0] == "label":
                labels[it[1]] = len(out)
                out.append(OPS["JUMPDEST"])
            elif isinstance(it, tuple) and it[0] == "push_label":
                used.add(it[1])
                if final and it[1] not in labels:
                    raise ValueError(f"undefined label {it[1]!r}")
                out.append(0x61)   # PUSH2
                out.extend(labels.get(it[1], 0).to_bytes(2, "big"))
            else:
                raise ValueError(f"bad asm item {it!r}")
    missing = used - labels.keys()
    if missing:
        raise ValueError(f"undefined labels {sorted(missing)}")
    return bytes(out)
