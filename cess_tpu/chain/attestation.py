"""Structured TEE attestation: parsed reports + signer cert chains.

The reference verifies an Intel IAS attestation in two steps
(/root/reference/primitives/enclave-verify/src/lib.rs:135-219):
the report-signing certificate must chain to a PINNED root
(IAS_SERVER_ROOTS, :46-93) and be time-valid (a fixed verification
instant, :150), then the report signature is checked with that cert,
and the quote body is parsed at fixed offsets for MRENCLAVE
(bytes 112..144), MRSIGNER (176..208) and the bound public key
(368..401) (:181-219).

This module mirrors that structure natively: an ``AttestationReport``
is a typed, parsed object (never substring-matched); its signer is an
end-entity ``SignerCert`` verified through an explicit chain to a
root key pinned on chain; and ``report_data`` must equal the SHA-256
binding of (podr2_pk, controller) — so a report can neither be forged
field-by-field nor replayed for a different key or registrant.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec
from ..crypto.rsa import RsaPublicKey, rsa_verify_pkcs1v15
from .state import DispatchError

# The reference validates certs against a FIXED instant
# (webpki::Time::from_seconds_since_unix_epoch(1670515200), lib.rs:150);
# same pinned-clock design here.
ATTESTATION_TIME = 1670515200

CERT_SIGNING_CONTEXT = b"cess-tpu/attest-cert-v1:"
REPORT_SIGNING_CONTEXT = b"cess-tpu/attest-report-v1:"
REPORT_DATA_CONTEXT = b"cess-tpu/podr2-bind-v1:"

MAX_CHAIN_LEN = 3


@codec.register
@dataclasses.dataclass(frozen=True)
class SignerCert:
    """One link of the report-signing chain (webpki EndEntityCert /
    intermediate analog)."""

    subject: str
    pubkey: RsaPublicKey
    not_after: int        # unix seconds
    signature: bytes      # by the PARENT key over signing_payload()

    def signing_payload(self) -> bytes:
        return CERT_SIGNING_CONTEXT + codec.encode(
            (self.subject, self.pubkey.n, self.pubkey.e, self.not_after))


@codec.register
@dataclasses.dataclass(frozen=True)
class AttestationReport:
    """The parsed quote body (ref fixed offsets 112/176/368)."""

    mrenclave: bytes      # 32: enclave measurement
    mr_signer: bytes      # 32: enclave signer measurement
    report_data: bytes    # 32: sha256 binding of (podr2_pk, controller)
    timestamp: int        # report issue time, unix seconds

    def signing_payload(self) -> bytes:
        return REPORT_SIGNING_CONTEXT + codec.encode(self)


def report_data_binding(podr2_pk: bytes, controller: str,
                        bls_pk: bytes = b"") -> bytes:
    """What an honest enclave puts in report_data: binds the PoDR2 key
    AND the registering controller (and the BLS verdict-signing master
    key when the worker carries one), so none can be swapped."""
    extra = b"|bls:" + bls_pk if bls_pk else b""
    return hashlib.sha256(REPORT_DATA_CONTEXT + podr2_pk + b"|"
                          + controller.encode() + extra).digest()


def _check_shape(report: AttestationReport,
                 chain: tuple[SignerCert, ...]) -> None:
    ok = (isinstance(report, AttestationReport)
          and isinstance(report.mrenclave, bytes)
          and len(report.mrenclave) == 32
          and isinstance(report.mr_signer, bytes)
          and len(report.mr_signer) == 32
          and isinstance(report.report_data, bytes)
          and len(report.report_data) == 32
          and isinstance(report.timestamp, int))
    if not ok:
        raise DispatchError("tee_worker.MalformedReport")
    if not (isinstance(chain, tuple) and 1 <= len(chain) <= MAX_CHAIN_LEN
            and all(isinstance(c, SignerCert)
                    and isinstance(c.subject, str)
                    and isinstance(c.pubkey, RsaPublicKey)
                    and isinstance(c.not_after, int)
                    and isinstance(c.signature, bytes) for c in chain)):
        raise DispatchError("tee_worker.MalformedCertChain")


def verify_attestation(roots: tuple[RsaPublicKey, ...],
                       chain: tuple[SignerCert, ...],
                       report: AttestationReport, report_sig: bytes,
                       now: int = ATTESTATION_TIME) -> None:
    """Full verification; raises DispatchError on any failure.

    chain[0] is signed by a pinned root; each subsequent cert by its
    predecessor; the LAST cert signs the report (the reference's
    verify_is_valid_tls_server_cert + verify_signature split)."""
    _check_shape(report, chain)
    if not roots:
        raise DispatchError("tee_worker.NoPinnedRoot")
    head = chain[0]
    if not any(rsa_verify_pkcs1v15(root, head.signing_payload(),
                                   head.signature) for root in roots):
        raise DispatchError("tee_worker.UntrustedSigner",
                            "cert chain does not reach a pinned root")
    for parent, cert in zip(chain, chain[1:]):
        if not rsa_verify_pkcs1v15(parent.pubkey, cert.signing_payload(),
                                   cert.signature):
            raise DispatchError("tee_worker.BrokenCertChain", cert.subject)
    for cert in chain:
        if cert.not_after < now:
            raise DispatchError("tee_worker.CertExpired", cert.subject)
    if not isinstance(report_sig, bytes) or not rsa_verify_pkcs1v15(
            chain[-1].pubkey, report.signing_payload(), report_sig):
        raise DispatchError("tee_worker.VerifyCertFailed",
                            "report signature invalid")


# -- dev/test issuance helpers (the chain only ever verifies) ----------------

def issue_cert(parent_keypair, subject: str, pubkey: RsaPublicKey,
               not_after: int = ATTESTATION_TIME + 10 * 365 * 86400
               ) -> SignerCert:
    c = SignerCert(subject=subject, pubkey=pubkey, not_after=not_after,
                   signature=b"")
    return dataclasses.replace(
        c, signature=parent_keypair.sign_pkcs1v15(c.signing_payload()))


def issue_report(signer_keypair, mrenclave: bytes, podr2_pk: bytes,
                 controller: str, mr_signer: bytes = b"\x05" * 32,
                 timestamp: int = ATTESTATION_TIME, bls_pk: bytes = b""
                 ) -> tuple[AttestationReport, bytes]:
    report = AttestationReport(
        mrenclave=mrenclave, mr_signer=mr_signer,
        report_data=report_data_binding(podr2_pk, controller, bls_pk),
        timestamp=timestamp)
    return report, signer_keypair.sign_pkcs1v15(report.signing_payload())
