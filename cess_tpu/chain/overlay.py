"""Frame-chained overlays: the one commit discipline both execution
layers (evm.py worlds, contracts.py sessions) share.

Each call frame holds an overlay chained over its PARENT frame's
overlay; the root falls through to chain state. A frame that succeeds
commits into its parent — so when an intermediate frame later reverts,
its whole subtree's writes vanish with it (call-chain transactionality;
a direct-to-chain commit let a reverted frame's grandchildren persist,
review-confirmed in both VMs before this was factored out). Chained
reads also give re-entered frames a consistent view of ancestors'
pending writes. The root commits to chain only when the TOP frame
succeeds; read-only queries simply never commit their root.
"""
from __future__ import annotations


class ChainedOverlay:
    """Key/value overlay chain; ``root_get(key)`` / ``root_put(key, v)``
    bridge the root frame to real storage. Subclasses add frame-local
    extras (e.g. pending events) by extending ``commit``."""

    def __init__(self, root_get, root_put, parent=None):
        self.root_get = root_get
        self.root_put = root_put
        self.parent = parent
        self.over: dict = {}

    def get(self, key):
        frame = self
        while frame is not None:
            if key in frame.over:
                return frame.over[key]
            frame = frame.parent
        return self.root_get(key)

    def put(self, key, value) -> None:
        self.over[key] = value

    def commit(self) -> None:
        """Into the parent frame; at the root, into real storage."""
        if self.parent is not None:
            self.parent.over.update(self.over)
        else:
            for key, value in sorted(self.over.items(),
                                     key=lambda kv: repr(kv[0])):
                self.root_put(key, value)
