"""Account balances: free/reserved, transfers, slashes, issuance.

The reference uses Substrate pallet-balances; this provides the subset
the CESS pallets consume: transfer, reserve/unreserve (miner collateral,
space payments), slash (punishments), and mint (era rewards inflow).
Fees/locks/vesting are out of scope for the domain logic.
"""
from __future__ import annotations

from .state import DispatchError, State

PALLET = "balances"


class Balances:
    def __init__(self, state: State):
        self.state = state

    # -- queries -----------------------------------------------------------
    def free(self, who: str) -> int:
        return self.state.get(PALLET, "free", who, default=0)

    def reserved(self, who: str) -> int:
        return self.state.get(PALLET, "reserved", who, default=0)

    def total_issuance(self) -> int:
        return self.state.get(PALLET, "issuance", default=0)

    # -- genesis / issuance --------------------------------------------------
    def mint(self, who: str, amount: int) -> None:
        assert amount >= 0
        self.state.put(PALLET, "free", who, self.free(who) + amount)
        self.state.put(PALLET, "issuance", self.total_issuance() + amount)

    def burn(self, who: str, amount: int) -> None:
        """Remove from free balance and issuance (e.g. fee burn)."""
        self._withdraw_free(who, amount)
        self.state.put(PALLET, "issuance", self.total_issuance() - amount)

    # -- operations ----------------------------------------------------------
    def _withdraw_free(self, who: str, amount: int) -> None:
        f = self.free(who)
        if f < amount:
            raise DispatchError("balances.InsufficientBalance",
                                f"{who} has {f} < {amount}")
        self.state.put(PALLET, "free", who, f - amount)

    def transfer(self, src: str, dst: str, amount: int) -> None:
        if amount < 0:
            raise DispatchError("balances.InvalidAmount")
        self._withdraw_free(src, amount)
        self.state.put(PALLET, "free", dst, self.free(dst) + amount)
        self.state.deposit_event(PALLET, "Transfer",
                                 src=src, dst=dst, amount=amount)

    def reserve(self, who: str, amount: int) -> None:
        self._withdraw_free(who, amount)
        self.state.put(PALLET, "reserved", who, self.reserved(who) + amount)

    def unreserve(self, who: str, amount: int) -> int:
        """Release up to ``amount`` from reserve; returns actually freed."""
        r = self.reserved(who)
        freed = min(r, amount)
        self.state.put(PALLET, "reserved", who, r - freed)
        self.state.put(PALLET, "free", who, self.free(who) + freed)
        return freed

    def slash_reserved(self, who: str, amount: int, beneficiary: str | None = None) -> int:
        """Take up to ``amount`` from reserve (punishments). Slashed funds
        go to ``beneficiary`` (e.g. the treasury/reward pool) or are burnt."""
        r = self.reserved(who)
        taken = min(r, amount)
        self.state.put(PALLET, "reserved", who, r - taken)
        if beneficiary is not None:
            self.state.put(PALLET, "free", beneficiary,
                           self.free(beneficiary) + taken)
        else:
            self.state.put(PALLET, "issuance", self.total_issuance() - taken)
        return taken
