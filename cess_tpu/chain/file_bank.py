"""File lifecycle (reference: c-pallets/file-bank, the largest pallet).

Upload declaration with whole-file dedup, deal creation with random
miner assignment and scheduler-driven timeout/retry (<=5), storage
confirmation (transfer_report), tag-calculation window (calculate_end),
deletion, buckets, ownership transfer, filler (idle file) accounting,
fragment restoral orders, and miner exit with cooling.

Mirrors /root/reference/c-pallets/file-bank/src/:
upload_declaration lib.rs:423-499, generate_deal functions.rs:127-152,
random_assign_miner functions.rs:187-283, deal_reassign_miner
lib.rs:504-540, transfer_report lib.rs:623-697, calculate_end
lib.rs:702-726, replace_file_report lib.rs:731-760, fillers
lib.rs:798-859, restoral orders lib.rs:943-1122, miner exit
lib.rs:1128-1207 + functions.rs:543-573, lease-expiry GC lib.rs:362-402.

Layout note (TPU-first geometry): a deal assigns FRAGMENT_COUNT = k+m
miners; miner j stores fragment row j of EVERY segment — so the
off-chain encode batch is one [segments, k+m, fragment_size] device
array whose row-j slice ships to one miner (cess_tpu/models/pipeline).
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec, constants
from .scheduler import Scheduler
from .sminer import Sminer
from .state import DispatchError, State
from .storage_handler import StorageHandler

PALLET = "file_bank"

CALCULATE = "calculate"   # fragments stored, tags being computed
ACTIVE = "active"

MINER_COOLING_BLOCKS = constants.ONE_DAY_BLOCKS  # exit cooling ledger


@codec.register
@dataclasses.dataclass(frozen=True)
class SegmentInfo:
    hash: bytes
    fragment_hashes: tuple[bytes, ...]   # len == fragment_count


@codec.register
@dataclasses.dataclass(frozen=True)
class UserBrief:
    user: str
    file_name: str
    bucket: str


@codec.register
@dataclasses.dataclass(frozen=True)
class DealInfo:
    file_hash: bytes
    owner: UserBrief
    file_size: int
    segments: tuple[SegmentInfo, ...]
    assigned: tuple[str, ...]           # miner per fragment row
    complete: frozenset[str]            # miners that reported
    count: int                          # reassignment retries
    needed_space: int


@codec.register
@dataclasses.dataclass(frozen=True)
class FileInfo:
    file_size: int
    segments: tuple[SegmentInfo, ...]
    miners: tuple[str, ...]             # fragment row -> miner
    owners: tuple[UserBrief, ...]
    state: str
    needed_space: int


@codec.register
@dataclasses.dataclass(frozen=True)
class RestoralOrder:
    miner: str              # claimant ("" = unclaimed)
    origin_miner: str
    file_hash: bytes
    fragment_hash: bytes
    fragment_row: int
    gen_block: int
    deadline: int           # claim deadline (re-opens on expiry)


@codec.register
@dataclasses.dataclass(frozen=True)
class RestoralTarget:
    """Exit cooling ledger gating withdrawal (functions.rs:543-573)."""
    miner: str
    service_space: int
    restored_space: int
    cooling_block: int


class FileBank:
    def __init__(self, state: State, balances, storage: StorageHandler,
                 sminer: Sminer, scheduler: Scheduler,
                 fragment_count: int = constants.FRAGMENT_COUNT,
                 oss=None):
        self.state = state
        self.storage = storage
        self.sminer = sminer
        self.scheduler = scheduler
        self.fragment_count = fragment_count
        self.oss = oss  # OssFindAuthor provider, set by runtime wiring

    # -- queries -----------------------------------------------------------
    def deal(self, file_hash: bytes) -> DealInfo | None:
        return self.state.get(PALLET, "deal", file_hash)

    def file(self, file_hash: bytes) -> FileInfo | None:
        return self.state.get(PALLET, "file", file_hash)

    def user_files(self, user: str) -> list[bytes]:
        return [k[0] for k, _ in self.state.iter_prefix(PALLET, "hold", user)]

    def restoral_order(self, fragment_hash: bytes) -> RestoralOrder | None:
        return self.state.get(PALLET, "restoral", fragment_hash)

    def pending_replacements(self, miner: str) -> int:
        return self.state.get(PALLET, "pending_replace", miner, default=0)

    def restoral_target(self, miner: str) -> RestoralTarget | None:
        return self.state.get(PALLET, "restoral_target", miner)

    # -- permission (functions.rs:516-521) ----------------------------------
    def _check_permission(self, operator: str, owner: str) -> None:
        if operator == owner:
            return
        if self.oss is not None and self.oss.is_authorized(owner, operator):
            return
        raise DispatchError("file_bank.NoPermission",
                            f"{operator} not authorized by {owner}")

    # -- buckets -------------------------------------------------------------
    def create_bucket(self, operator: str, owner: str, name: str) -> None:
        self._check_permission(operator, owner)
        if not (3 <= len(name) <= 63) or not name.replace("-", "").isalnum():
            raise DispatchError("file_bank.InvalidBucketName", name)
        if self.state.contains(PALLET, "bucket", owner, name):
            raise DispatchError("file_bank.BucketExists", name)
        self.state.put(PALLET, "bucket", owner, name, ())
        self.state.deposit_event(PALLET, "CreateBucket", owner=owner, name=name)

    def delete_bucket(self, operator: str, owner: str, name: str) -> None:
        self._check_permission(operator, owner)
        files = self.state.get(PALLET, "bucket", owner, name)
        if files is None:
            raise DispatchError("file_bank.NonExistentBucket", name)
        if files:
            raise DispatchError("file_bank.BucketNotEmpty", name)
        self.state.delete(PALLET, "bucket", owner, name)
        self.state.deposit_event(PALLET, "DeleteBucket", owner=owner, name=name)

    def _bucket_add(self, owner: str, name: str, file_hash: bytes) -> None:
        files = self.state.get(PALLET, "bucket", owner, name)
        if files is None:
            raise DispatchError("file_bank.NonExistentBucket", name)
        self.state.put(PALLET, "bucket", owner, name, files + (file_hash,))

    def _bucket_remove(self, owner: str, name: str, file_hash: bytes) -> None:
        files = self.state.get(PALLET, "bucket", owner, name)
        if files is not None:
            self.state.put(PALLET, "bucket", owner, name,
                           tuple(f for f in files if f != file_hash))

    # -- upload (lib.rs:423-499) ----------------------------------------------
    def upload_declaration(self, operator: str, file_hash: bytes,
                           segments: list[tuple[bytes, tuple[bytes, ...]]],
                           owner: UserBrief, file_size: int) -> None:
        self._check_permission(operator, owner.user)
        # check_file_spec (functions.rs:4-14): counts only, hashes trusted
        if not 0 < len(segments) <= constants.SEGMENT_COUNT_MAX:
            raise DispatchError("file_bank.SegmentCountError")
        if any(len(frags) != self.fragment_count for _, frags in segments):
            raise DispatchError("file_bank.FragmentCountError")
        if file_size <= 0:
            raise DispatchError("file_bank.InvalidFileSize")
        needed = len(segments) * constants.SEGMENT_SIZE \
            * constants.SPACE_OVERHEAD_NUM // constants.SPACE_OVERHEAD_DEN

        existing = self.file(file_hash)
        if existing is not None:
            # whole-file dedup: just add ownership (lib.rs:466-487)
            if any(o.user == owner.user for o in existing.owners):
                raise DispatchError("file_bank.OwnedFile")
            if not self.storage.check_user_space(owner.user, needed):
                raise DispatchError("storage_handler.InsufficientStorage")
            self.storage.unlock_and_used_user_space(owner.user, 0, needed)
            self._bucket_add(owner.user, owner.bucket, file_hash)
            self.state.put(PALLET, "file", file_hash, dataclasses.replace(
                existing, owners=existing.owners + (owner,)))
            self.state.put(PALLET, "hold", owner.user, file_hash, True)
            self.state.deposit_event(PALLET, "UploadDeclaration",
                                     operator=operator, owner=owner.user,
                                     file_hash=file_hash, shared=True)
            return

        if self.deal(file_hash) is not None:
            raise DispatchError("file_bank.DealExists")
        seg_infos = tuple(SegmentInfo(h, tuple(f)) for h, f in segments)
        self.storage.lock_user_space(owner.user, needed)
        assigned = self._random_assign_miner(file_hash, len(segments))
        deal = DealInfo(file_hash=file_hash, owner=owner,
                        file_size=file_size, segments=seg_infos,
                        assigned=assigned, complete=frozenset(), count=0,
                        needed_space=needed)
        self.state.put(PALLET, "deal", file_hash, deal)
        self._start_deal_task(file_hash)
        self.state.deposit_event(PALLET, "UploadDeclaration",
                                 operator=operator, owner=owner.user,
                                 file_hash=file_hash, shared=False)

    def _random_assign_miner(self, file_hash: bytes, seg_count: int,
                             exclude: frozenset[str] = frozenset(),
                             rows_needed: int | None = None) -> tuple[str, ...]:
        """Pick fragment_count distinct positive miners with enough idle
        space, deterministically seeded (functions.rs:187-283); each
        selected miner locks seg_count * FRAGMENT_SIZE."""
        rows = rows_needed if rows_needed is not None else self.fragment_count
        need = seg_count * constants.FRAGMENT_SIZE
        candidates = [w for w in self.sminer.all_miners()
                      if w not in exclude and self.sminer.is_positive(w)
                      and self.sminer.get_miner_idle_space(w) >= need]
        if len(candidates) < rows:
            raise DispatchError("file_bank.NotQualifiedMiner",
                                f"{len(candidates)} candidates < {rows}")
        seed = self.state.get("system", "randomness", default=b"") + file_hash
        rng_order = sorted(
            candidates,
            key=lambda w: hashlib.sha256(seed + w.encode()).digest())
        chosen = tuple(rng_order[:rows])
        for w in chosen:
            self.sminer.lock_space(w, need)
        return chosen

    def _start_deal_task(self, file_hash: bytes) -> None:
        # timeout = 600 blocks per assigned miner (functions.rs:154-168)
        life = constants.DEAL_TIMEOUT_BLOCKS * self.fragment_count
        self.scheduler.schedule_named(
            f"deal:{file_hash.hex()}", self.state.block + life,
            PALLET, "deal_timeout", file_hash)

    # -- deal progression -------------------------------------------------------
    def transfer_report(self, miner: str, file_hash: bytes) -> None:
        """A miner confirms it stored its fragment rows (lib.rs:623-697)."""
        deal = self.deal(file_hash)
        if deal is None:
            raise DispatchError("file_bank.NonExistentDeal")
        if miner not in deal.assigned:
            raise DispatchError("file_bank.NotAssignedMiner")
        if miner in deal.complete:
            raise DispatchError("file_bank.AlreadyReported")
        complete = deal.complete | {miner}
        deal = dataclasses.replace(deal, complete=complete)
        self.state.put(PALLET, "deal", file_hash, deal)
        self.state.deposit_event(PALLET, "TransferReport", miner=miner,
                                 file_hash=file_hash)
        if complete != frozenset(deal.assigned):
            return
        # last reporter: file enters Calculate (tag window), space settles
        owner = deal.owner
        self.state.put(PALLET, "file", file_hash, FileInfo(
            file_size=deal.file_size, segments=deal.segments,
            miners=deal.assigned, owners=(owner,), state=CALCULATE,
            needed_space=deal.needed_space))
        self.state.put(PALLET, "hold", owner.user, file_hash, True)
        self._bucket_add(owner.user, owner.bucket, file_hash)
        seg_count = len(deal.segments)
        for row, w in enumerate(deal.assigned):
            # each miner may now replace seg_count fillers (lib.rs:663-668)
            self.state.put(PALLET, "pending_replace", w,
                           self.pending_replacements(w) + seg_count)
            for seg in deal.segments:
                self.state.put(PALLET, "frag_of_miner", w,
                               seg.fragment_hashes[row],
                               (file_hash, row))
        self.storage.unlock_and_used_user_space(
            owner.user, deal.needed_space, deal.needed_space)
        self.scheduler.cancel_named(f"deal:{file_hash.hex()}")
        self.scheduler.schedule_named(
            f"calc:{file_hash.hex()}",
            self.state.block + constants.DEAL_TIMEOUT_BLOCKS,
            PALLET, "calculate_end", file_hash)
        self.state.deposit_event(PALLET, "StorageCompleted",
                                 file_hash=file_hash)

    def calculate_end(self, file_hash: bytes) -> None:
        """Tag window closed: locked miner space becomes service space,
        file goes Active (lib.rs:702-726). Root/scheduled origin."""
        f = self.file(file_hash)
        if f is None or f.state != CALCULATE:
            return
        seg_space = len(f.segments) * constants.FRAGMENT_SIZE
        for w in f.miners:
            self.sminer.unlock_space_to_service(w, seg_space)
        self.state.put(PALLET, "file", file_hash,
                       dataclasses.replace(f, state=ACTIVE))
        self.state.delete(PALLET, "deal", file_hash)
        self.scheduler.cancel_named(f"calc:{file_hash.hex()}")
        self.state.deposit_event(PALLET, "CalculateEnd", file_hash=file_hash)

    def deal_timeout(self, file_hash: bytes) -> None:
        """Scheduled retry: reassign non-reporting miners, <=5 attempts
        then abort with refund (lib.rs:504-540)."""
        deal = self.deal(file_hash)
        if deal is None:
            return
        seg_count = len(deal.segments)
        need = seg_count * constants.FRAGMENT_SIZE
        laggards = [w for w in deal.assigned if w not in deal.complete]
        if deal.count >= constants.DEAL_MAX_RETRIES:
            for w in deal.assigned:
                self.sminer.unlock_space(w, need)
            self.storage.unlock_user_space(deal.owner.user, deal.needed_space)
            self.state.delete(PALLET, "deal", file_hash)
            self.state.deposit_event(PALLET, "DealAborted", file_hash=file_hash)
            return
        for w in laggards:
            self.sminer.unlock_space(w, need)
        try:
            replacements = self._random_assign_miner(
                file_hash, seg_count,
                exclude=frozenset(deal.assigned),
                rows_needed=len(laggards))
        except DispatchError:
            # no candidates: keep the same laggards assigned, re-lock
            for w in laggards:
                self.sminer.lock_space(w, need)
            replacements = tuple(laggards)
        new_assigned = []
        it = iter(replacements)
        for w in deal.assigned:
            new_assigned.append(next(it) if w in laggards else w)
        deal = dataclasses.replace(deal, assigned=tuple(new_assigned),
                                   count=deal.count + 1)
        self.state.put(PALLET, "deal", file_hash, deal)
        self._start_deal_task(file_hash)
        self.state.deposit_event(PALLET, "DealReassigned",
                                 file_hash=file_hash, count=deal.count)

    # -- deletion (lib.rs) -------------------------------------------------------
    def delete_file(self, operator: str, owner: str, file_hash: bytes) -> None:
        self._check_permission(operator, owner)
        f = self.file(file_hash)
        if f is None:
            raise DispatchError("file_bank.NonExistentFile")
        brief = next((o for o in f.owners if o.user == owner), None)
        if brief is None:
            raise DispatchError("file_bank.NotOwner")
        owners = tuple(o for o in f.owners if o.user != owner)
        self.storage.free_used_space(owner, f.needed_space)
        self.state.delete(PALLET, "hold", owner, file_hash)
        self._bucket_remove(owner, brief.bucket, file_hash)
        if owners:
            self.state.put(PALLET, "file", file_hash,
                           dataclasses.replace(f, owners=owners))
        else:
            self._drop_file_storage(file_hash, f)
        self.state.deposit_event(PALLET, "DeleteFile", owner=owner,
                                 file_hash=file_hash)

    def _drop_file_storage(self, file_hash: bytes, f: FileInfo) -> None:
        seg_space = len(f.segments) * constants.FRAGMENT_SIZE
        for row, w in enumerate(f.miners):
            if f.state == ACTIVE:
                self.sminer.sub_miner_service_space(w, seg_space)
                self.storage.sub_total_service_space(seg_space)
            else:
                self.sminer.unlock_space(w, seg_space)
            for seg in f.segments:
                self.state.delete(PALLET, "frag_of_miner", w,
                                  seg.fragment_hashes[row])
        self.state.delete(PALLET, "file", file_hash)
        self.scheduler.cancel_named(f"calc:{file_hash.hex()}")

    def ownership_transfer(self, operator: str, old_owner: str,
                           new_brief: UserBrief, file_hash: bytes) -> None:
        self._check_permission(operator, old_owner)
        f = self.file(file_hash)
        if f is None:
            raise DispatchError("file_bank.NonExistentFile")
        if not any(o.user == old_owner for o in f.owners):
            raise DispatchError("file_bank.NotOwner")
        if any(o.user == new_brief.user for o in f.owners):
            raise DispatchError("file_bank.OwnedFile", "target already owns")
        if not self.storage.check_user_space(new_brief.user, f.needed_space):
            raise DispatchError("storage_handler.InsufficientStorage")
        old_brief = next(o for o in f.owners if o.user == old_owner)
        self.storage.unlock_and_used_user_space(new_brief.user, 0, f.needed_space)
        self.storage.free_used_space(old_owner, f.needed_space)
        self._bucket_remove(old_owner, old_brief.bucket, file_hash)
        self._bucket_add(new_brief.user, new_brief.bucket, file_hash)
        self.state.delete(PALLET, "hold", old_owner, file_hash)
        self.state.put(PALLET, "hold", new_brief.user, file_hash, True)
        owners = tuple(o for o in f.owners if o.user != old_owner) + (new_brief,)
        self.state.put(PALLET, "file", file_hash,
                       dataclasses.replace(f, owners=owners))
        self.state.deposit_event(PALLET, "OwnershipTransfer",
                                 file_hash=file_hash, old=old_owner,
                                 new=new_brief.user)

    # -- fillers (idle files; lib.rs:798-859) -------------------------------------
    # The reference's FillerMap keys (miner, filler_hash) with TEE
    # attribution and delete_filler. Here a filler's CONTENT is
    # PRF-derived from (miner, index) (cess_tpu.node.offchain.
    # filler_bytes); the TEE regenerates it, checks the hash, tags it,
    # and signs the batch — so idle space only enters the ledger
    # against TEE-certified, auditable content.
    #
    # Known limitation (shared with this reference snapshot's
    # generated idle files, lib.rs:798-859): publicly-derivable filler
    # content proves TAG possession, not dedicated disk — a miner can
    # regenerate challenged fillers on demand. CESS later replaced
    # this with PoIS; a miner-secret-seeded variant is the upgrade
    # path here.
    FILLER_CERT_CONTEXT = b"cess-filler-cert-v1:"

    def filler_hashes(self, miner: str) -> list[bytes]:
        return [k[0] for k, _ in self.state.iter_prefix(PALLET, "filler",
                                                        miner)]

    def filler_cert_nonce(self, miner: str) -> int:
        return self.state.get(PALLET, "filler_cert_nonce", miner, default=0)

    def upload_filler(self, miner: str, hashes: tuple[bytes, ...],
                      tee: str, tee_sig: bytes) -> None:
        """TEE-certified filler registration: every filler hash goes
        into the registry with the certifying TEE recorded; idle space
        is credited per filler (8 MiB protocol units).

        The cert covers (miner, hashes, cert_nonce) where cert_nonce
        is the miner's on-chain filler-cert counter — a cert can never
        be replayed to re-credit idle space after delete_filler /
        replace_file_report removed the filler."""
        from ..crypto import ed25519

        if not hashes or len(set(hashes)) != len(hashes):
            raise DispatchError("file_bank.InvalidCount")
        if not self.sminer.is_positive(miner):
            raise DispatchError("sminer.StateNotPositive")
        tee_registry = self.state.get("tee_worker", "worker", tee)
        if tee_registry is None:
            raise DispatchError("file_bank.NonExistentTee", tee)
        tee_pub = self.state.get("system", "account_key", tee)
        nonce = self.filler_cert_nonce(miner)
        payload = self.FILLER_CERT_CONTEXT + codec.encode(
            (miner, tuple(hashes), nonce))
        if tee_pub is None or not isinstance(tee_sig, bytes) \
                or not ed25519.verify(tee_pub, payload, tee_sig):
            raise DispatchError("file_bank.BadFillerCert", miner)
        for h in hashes:
            if self.state.contains(PALLET, "filler", miner, h):
                raise DispatchError("file_bank.FillerExists", h.hex())
        for h in hashes:
            self.state.put(PALLET, "filler", miner, h,
                           (tee, self.state.block))
        self.state.put(PALLET, "filler_cert_nonce", miner, nonce + 1)
        self.sminer.add_miner_idle_space(
            miner, len(hashes) * constants.FRAGMENT_SIZE)
        self.state.deposit_event(PALLET, "FillerUpload", miner=miner,
                                 count=len(hashes))

    def delete_filler(self, miner: str, filler_hash: bytes) -> None:
        """Remove one filler from the registry and the idle ledger
        (lib.rs:798-859 delete_filler)."""
        if not self.state.contains(PALLET, "filler", miner, filler_hash):
            raise DispatchError("file_bank.NonExistentFiller")
        m = self.sminer.miner(miner)
        if m is not None and m.idle_space < constants.FRAGMENT_SIZE:
            # the filler's space is currently locked for a deal:
            # deleting now would strand the reservation and drift the
            # registry against the idle ledger (invariant:
            # idle + lock + pending_replace*FRAG == fillers*FRAG)
            raise DispatchError("file_bank.IdleSpaceLocked", miner)
        self.state.delete(PALLET, "filler", miner, filler_hash)
        if m is not None:
            self.state.put("sminer", "miner", miner, dataclasses.replace(
                m, idle_space=m.idle_space - constants.FRAGMENT_SIZE))
            self.storage.sub_total_idle_space(constants.FRAGMENT_SIZE)

    def replace_file_report(self, miner: str,
                            filler_hashes: tuple[bytes, ...]) -> None:
        """Miner deletes specific fillers freed by stored service
        fragments (lib.rs:731-760): each named filler leaves the
        registry, so it stops being audited and stops counting as
        idle space."""
        pending = self.pending_replacements(miner)
        count = len(filler_hashes)
        if count <= 0 or count > pending:
            raise DispatchError("file_bank.InvalidCount",
                                f"{count} > pending {pending}")
        if len(set(filler_hashes)) != count:
            raise DispatchError("file_bank.InvalidCount", "duplicate hash")
        for h in filler_hashes:
            if not self.state.contains(PALLET, "filler", miner, h):
                raise DispatchError("file_bank.NonExistentFiller")
        # registry-only removal: the replaced space already left the
        # idle ledger when the deal's lock converted to service
        # (unlock_space_to_service at calculate_end) — delete_filler
        # here would subtract it a second time and drift
        # idle + lock + pending*FRAG below fillers*FRAG
        for h in filler_hashes:
            self.state.delete(PALLET, "filler", miner, h)
        self.state.put(PALLET, "pending_replace", miner, pending - count)
        self.state.deposit_event(PALLET, "ReplaceFiller", miner=miner,
                                 count=count)

    # -- restoral orders (lib.rs:943-1122) ----------------------------------------
    def generate_restoral_order(self, miner: str, file_hash: bytes,
                                fragment_hash: bytes) -> None:
        """A miner reports one of ITS fragments broken/lost."""
        entry = self.state.get(PALLET, "frag_of_miner", miner, fragment_hash)
        if entry is None:
            raise DispatchError("file_bank.NotFragmentOwner")
        if self.restoral_order(fragment_hash) is not None:
            raise DispatchError("file_bank.OrderExists")
        fh, row = entry
        if fh != file_hash:
            raise DispatchError("file_bank.HashMismatch")
        self._push_restoral(miner, file_hash, fragment_hash, row)

    def _push_restoral(self, origin_miner: str, file_hash: bytes,
                       fragment_hash: bytes, row: int) -> None:
        self.state.put(PALLET, "restoral", fragment_hash, RestoralOrder(
            miner="", origin_miner=origin_miner, file_hash=file_hash,
            fragment_hash=fragment_hash, fragment_row=row,
            gen_block=self.state.block,
            deadline=self.state.block + constants.RESTORAL_ORDER_LIFE))
        self.state.deposit_event(PALLET, "GenerateRestoralOrder",
                                 fragment_hash=fragment_hash)

    def claim_restoral_order(self, miner: str, fragment_hash: bytes) -> None:
        """Any positive miner claims a pending restoral (lib.rs)."""
        if not self.sminer.is_positive(miner):
            raise DispatchError("sminer.StateNotPositive")
        order = self.restoral_order(fragment_hash)
        if order is None:
            raise DispatchError("file_bank.NonExistentOrder")
        if order.miner and self.state.block <= order.deadline:
            raise DispatchError("file_bank.OrderClaimed")
        self.state.put(PALLET, "restoral", fragment_hash, dataclasses.replace(
            order, miner=miner,
            deadline=self.state.block + constants.RESTORAL_ORDER_LIFE))
        self.state.deposit_event(PALLET, "ClaimRestoralOrder", miner=miner,
                                 fragment_hash=fragment_hash)

    def restoral_order_complete(self, miner: str, fragment_hash: bytes) -> None:
        """Claimant repaired the fragment: ownership (and its service
        space) transfers (lib.rs:1068-1122)."""
        order = self.restoral_order(fragment_hash)
        if order is None:
            raise DispatchError("file_bank.NonExistentOrder")
        if order.miner != miner:
            raise DispatchError("file_bank.NotClaimant")
        if self.state.block > order.deadline:
            raise DispatchError("file_bank.OrderExpired")
        f = self.file(order.file_hash)
        if f is None:
            self.state.delete(PALLET, "restoral", fragment_hash)
            return
        # move fragment-row ownership: origin loses, claimant gains
        self.sminer.sub_miner_service_space(order.origin_miner,
                                            constants.FRAGMENT_SIZE)
        self.sminer.add_miner_service_space(miner, constants.FRAGMENT_SIZE)
        self.state.delete(PALLET, "frag_of_miner", order.origin_miner,
                          fragment_hash)
        self.state.put(PALLET, "frag_of_miner", miner, fragment_hash,
                       (order.file_hash, order.fragment_row))
        # the file's row->miner mapping flips to the claimant once the
        # origin holds no fragment of that row anymore
        row = order.fragment_row
        if not any(self.state.contains(PALLET, "frag_of_miner",
                                       order.origin_miner,
                                       s.fragment_hashes[row])
                   for s in f.segments):
            miners = tuple(miner if i == row else w
                           for i, w in enumerate(f.miners))
            self.state.put(PALLET, "file", order.file_hash,
                           dataclasses.replace(f, miners=miners))
        # exit bookkeeping
        tgt = self.restoral_target(order.origin_miner)
        if tgt is not None:
            self.state.put(PALLET, "restoral_target", order.origin_miner,
                           dataclasses.replace(
                               tgt, restored_space=tgt.restored_space
                               + constants.FRAGMENT_SIZE))
        self.state.delete(PALLET, "restoral", fragment_hash)
        self.state.deposit_event(PALLET, "RestoralComplete", miner=miner,
                                 fragment_hash=fragment_hash)

    # -- miner exit (lib.rs:1128-1207) ---------------------------------------------
    def miner_exit_prep(self, miner: str) -> None:
        """Begin exit: every held fragment becomes a restoral order;
        withdrawal gates on full restoral + cooling."""
        m = self.sminer.begin_exit(miner)
        count = 0
        for (frag_hash,), (file_hash, row) in list(
                self.state.iter_prefix(PALLET, "frag_of_miner", miner)):
            if self.restoral_order(frag_hash) is None:
                self._push_restoral(miner, file_hash, frag_hash, row)
            count += 1
        self.state.put(PALLET, "restoral_target", miner, RestoralTarget(
            miner=miner, service_space=count * constants.FRAGMENT_SIZE,
            restored_space=0,
            cooling_block=self.state.block + MINER_COOLING_BLOCKS))

    def force_miner_exit(self, miner: str) -> None:
        """Audit escalation (3rd clear strike): lock the miner and open
        restoral orders for everything it held (audit lib.rs:637-648)."""
        m = self.sminer.force_exit(miner)
        if m is None:
            return
        count = 0
        for (frag_hash,), (file_hash, row) in list(
                self.state.iter_prefix(PALLET, "frag_of_miner", miner)):
            if self.restoral_order(frag_hash) is None:
                self._push_restoral(miner, file_hash, frag_hash, row)
            count += 1
        self.state.put(PALLET, "restoral_target", miner, RestoralTarget(
            miner=miner, service_space=count * constants.FRAGMENT_SIZE,
            restored_space=0,
            cooling_block=self.state.block + MINER_COOLING_BLOCKS))

    def miner_withdraw(self, miner: str) -> None:
        tgt = self.restoral_target(miner)
        if tgt is None:
            raise DispatchError("file_bank.NonExistentTarget")
        if self.state.block < tgt.cooling_block:
            raise DispatchError("file_bank.CoolingNotOver")
        if tgt.restored_space < tgt.service_space:
            raise DispatchError("file_bank.RestoralIncomplete",
                                f"{tgt.restored_space}/{tgt.service_space}")
        self.sminer.withdraw(miner)
        self.state.delete(PALLET, "restoral_target", miner)
        self.state.delete(PALLET, "pending_replace", miner)

    # -- hooks (lease GC, lib.rs:362-402) -------------------------------------------
    def on_initialize(self, dead_users: list[str]) -> None:
        """GC files of users whose lease died (<=300 files per block)."""
        queue = list(self.state.get(PALLET, "gc_queue", default=()))
        queue.extend(dead_users)
        budget = constants.FROZEN_SWEEP_MAX_FILES
        remaining = []
        for user in queue:
            files = self.user_files(user)
            for fh in files[:budget]:
                try:
                    self.delete_file(user, user, fh)
                except DispatchError:
                    self.state.delete(PALLET, "hold", user, fh)
            budget -= min(len(files), budget)
            if len(files) > constants.FROZEN_SWEEP_MAX_FILES or budget <= 0:
                if self.user_files(user):
                    remaining.append(user)
                    continue
            if not self.user_files(user):
                self.storage.remove_dead_lease(user)
        self.state.put(PALLET, "gc_queue", tuple(remaining))
