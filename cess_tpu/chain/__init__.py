"""Deterministic protocol state machine (the chain-equivalent layer).

Re-implements the reference's domain pallets (SURVEY.md §2.1) as a
transaction-apply library over a journaled KV store: balances, space
market (storage-handler), miner registry/economics (sminer), file
lifecycle (file-bank), PoDR2 audit rounds (audit), TEE registry
(tee-worker), gateway/cacher registries (oss, cacher), scheduler
credit, staking economics, and the named-task scheduler — composed by
``runtime.Runtime`` in the reference's on_initialize order.

Not a FRAME translation: pallets are plain Python classes over a
shared ``State``; extrinsics are methods dispatched transactionally
(journal rollback on error), events are appended per block. All heavy
data-plane compute stays in cess_tpu.ops / cess_tpu.models — the chain
stores hashes and metadata only, mirroring the reference
(c-pallets/file-bank/src/lib.rs:423-428 trusts precomputed hashes).
"""
from .state import State, Event, DispatchError  # noqa: F401
