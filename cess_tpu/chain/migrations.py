"""Runtime versioning + StorageVersion-gated migrations.

The reference stamps the runtime with ``spec_version: 109``
(/root/reference/runtime/src/lib.rs:173) and migrates pallet storage
through ``StorageVersion`` gates in on_runtime_upgrade
(c-pallets/audit/src/migrations.rs:29-40: run only when the on-chain
version is behind, transform entries, bump the version). Same design
here: each pallet has an on-chain storage version; registered
migrations run INSIDE block execution at the first block authored by
upgraded code (deterministic across replicas, part of the state root
like the reference's runtime-upgrade block), then bump versions.

Real migrations in the registry (round-2 -> round-3 format changes):
- staking v1 -> v2: validators gained ValidatorPrefs (commission);
  pre-existing validators get the default 0 entry.
- tee_worker v1 -> v2: pinned attestation signers changed from
  32-byte key FINGERPRINTS to full RsaPublicKey roots (fingerprints
  cannot verify cert chains and cannot be inverted) — stale-format
  pins are dropped and must be re-pinned by governance.
"""
from __future__ import annotations

from .state import State

SPEC_VERSION = 112   # reference snapshot is 109 (runtime/src/lib.rs:173)

SYSTEM = "system"


def spec_version(state: State) -> int:
    return state.get(SYSTEM, "spec_version", default=0)


def storage_version(state: State, pallet: str) -> int:
    return state.get(SYSTEM, "storage_version", pallet, default=1)


def _migrate_staking_v2(state: State) -> int:
    """Backfill ValidatorPrefs (commission=0) for existing validators."""
    n = 0
    for v in state.get("staking", "validators", default=()):
        if not state.contains("staking", "prefs", v):
            state.put("staking", "prefs", v, 0)
            n += 1
    return n


def _migrate_tee_worker_v2(state: State) -> int:
    """Drop fingerprint-format (bytes) attestation pins; structured
    chain verification needs full root keys, re-pinned by governance."""
    from ..crypto.rsa import RsaPublicKey

    pins = state.get("tee_worker", "ias_pins", default=())
    kept = tuple(p for p in pins if isinstance(p, RsaPublicKey))
    if kept != pins:
        state.put("tee_worker", "ias_pins", kept)
    return len(pins) - len(kept)


def _migrate_tee_worker_v3(state: State) -> int:
    """retired_bls changed from a single bytes key to an append-only
    tuple of era keys (exit/re-register must not lose old eras): wrap
    old-format entries."""
    n = 0
    for key, v in list(state.iter_prefix("tee_worker", "retired_bls")):
        if isinstance(v, bytes):
            state.put("tee_worker", "retired_bls", *key, (v,))
            n += 1
    return n


def _migrate_evm_v2(state: State) -> int:
    """EVM ledger re-key (round-5): balances/nonces moved from
    native-account-string keys to 20-byte EVM addresses, and the
    backing model changed from per-depositor reserves to the EVM_POT
    pot account (value-carrying calls need any address's balance to be
    pot-covered). Old entries are re-keyed and their reserve backing
    is released into the pot, so pre-upgrade deposits stay withdrawable."""
    from .evm import EVM_POT, eth_address

    n = 0
    for (who,), bal in list(state.iter_prefix("evm", "balance")):
        if not isinstance(who, str):
            continue
        state.delete("evm", "balance", who)
        addr = eth_address(who)
        state.put("evm", "balance", addr,
                  state.get("evm", "balance", addr, default=0) + bal)
        reserved = state.get("balances", "reserved", who, default=0)
        moved = min(reserved, bal)
        state.put("balances", "reserved", who, reserved - moved)
        state.put("balances", "free", EVM_POT,
                  state.get("balances", "free", EVM_POT, default=0)
                  + moved)
        n += 1
    for (who,), nonce in list(state.iter_prefix("evm", "nonce")):
        if isinstance(who, str):
            state.delete("evm", "nonce", who)
            state.put("evm", "nonce", eth_address(who), nonce)
            n += 1
    return n


def _migrate_staking_v3(state: State) -> int:
    """Build the VoterList bags index (round-5) for validators that
    predate it; top_stakers falls back to the flat set until this
    runs, so an un-upgraded restart keeps electing correctly."""
    from .staking import PALLET as STAKING, Staking

    n = 0
    for who in state.get(STAKING, "validators", default=()):
        if state.get(STAKING, "bag_of", who) is not None:
            continue
        b = Staking.bag_index(state.get(STAKING, "bond", who, default=0))
        state.put(STAKING, "bag", b,
                  state.get(STAKING, "bag", b, default=()) + (who,))
        state.put(STAKING, "bag_of", who, b)
        state.put(STAKING, "bag_count",
                  state.get(STAKING, "bag_count", default=0) + 1)
        n += 1
    return n


def _migrate_contracts_v2(state: State) -> int:
    """Contracts code moved behind the canonical code-hash store
    (round-5): inline per-address bodies become hash references with
    the body stored once per hash (pallet-contracts CodeStorage)."""
    from .contracts import code_hash

    n = 0
    for (addr,), code in list(state.iter_prefix("contracts", "code")):
        if isinstance(code, tuple):
            h = code_hash(code)
            if not state.contains("contracts", "code_store", h):
                state.put("contracts", "code_store", h, code)
            state.put("contracts", "code", addr, h)
            n += 1
    return n


# (pallet, target_version, fn) — fn returns #entries transformed
MIGRATIONS = [
    ("staking", 2, _migrate_staking_v2),
    ("staking", 3, _migrate_staking_v3),
    ("tee_worker", 2, _migrate_tee_worker_v2),
    ("tee_worker", 3, _migrate_tee_worker_v3),
    ("evm", 2, _migrate_evm_v2),
    ("contracts", 2, _migrate_contracts_v2),
]


def current_versions() -> dict[str, int]:
    out: dict[str, int] = {}
    for pallet, target, _ in MIGRATIONS:
        out[pallet] = max(out.get(pallet, 1), target)
    return out


def stamp_genesis(state: State, version: int = SPEC_VERSION) -> None:
    """Stamp genesis at the CHAIN's genesis spec version (a ChainSpec
    field, part of the genesis hash) — NOT the running code's version.
    Any code version therefore reproduces a historical chain's genesis
    byte-exactly; upgrades activate only via the in-band
    system.apply_runtime_upgrade extrinsic, so full replay from
    genesis stays deterministic across code versions."""
    state.put(SYSTEM, "spec_version", version)
    versions = current_versions() if version >= SPEC_VERSION \
        else {pallet: 1 for pallet in current_versions()}
    for pallet, v in sorted(versions.items()):
        state.put(SYSTEM, "storage_version", pallet, v)


def run_pending(state: State) -> list[str]:
    """on_runtime_upgrade: run every migration whose pallet storage
    version is behind; bump versions + spec_version. Invoked by the
    system.apply_runtime_upgrade extrinsic (root/council), so the
    migration block is part of consensus — every replica and every
    future replayer on upgraded code executes it at the same height
    (the reference records upgrades the same way: set_code in a
    block, migrations at that block's on_runtime_upgrade). Returns
    the applied migration names (events are the caller's job)."""
    applied = []
    for pallet, target, fn in MIGRATIONS:
        if storage_version(state, pallet) < target:
            n = fn(state)
            state.put(SYSTEM, "storage_version", pallet, target)
            applied.append(f"{pallet}-v{target}({n})")
    if spec_version(state) < SPEC_VERSION:
        state.put(SYSTEM, "spec_version", SPEC_VERSION)
    return applied
