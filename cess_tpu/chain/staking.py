"""Staking with CESS economics (reference: c-pallets/cess-staking).

The reference forks Substrate pallet-staking, changing the reward
schedule to a fixed yearly issuance split validator/sminer
(238.5M / 477M DOLLARS year 1, x0.841 per year for 30 years) with the
sminer share pushed into the sminer reward pool each era, and adding
``slash_scheduler`` = 5% of MinValidatorBond for TEE punishment.
Mirrors /root/reference/c-pallets/staking/src/: reward schedule
pallet/impls.rs:452-474, end_era sminer issuance :430-449,
slash_scheduler slashing.rs:694-705, config runtime/src/lib.rs:585-589.

Nominators: the CESS runtime pins ``MaxNominations = 1``
(runtime/src/lib.rs:378), so a nominator backs exactly one validator
with their whole bond. Era exposure (own + nominator bonds) is
captured at era START and drives both the era payout split (validator
commission off the top, remainder exposure-pro-rata,
pallet/impls.rs era payout) and offence slashing (validator AND
exposed nominators slashed at the offence fraction). The election
itself is credit-weighted and lives in cess_tpu/node/consensus.py
(the reference's VrfSolver).
"""
from __future__ import annotations

import dataclasses

from .. import codec, constants
from .balances import Balances
from .sminer import REWARD_POOL
from .state import DispatchError, State

PALLET = "staking"
TREASURY = "treasury"

MIN_VALIDATOR_BOND = 1_000_000 * constants.DOLLARS   # runtime :585-589
MIN_NOMINATOR_BOND = 1_000 * constants.DOLLARS       # genesis min_nominator_bond analog (pallet/mod.rs:313,638)
ERAS_PER_YEAR = 365 * 4   # 6-hour eras (1h epochs x 6 sessions)
BONDING_DURATION_ERAS = 4 * 28    # 28 days (runtime/src/lib.rs:562)
MAX_UNLOCKING_CHUNKS = 32
# the reference defers offence slashes by 28 eras so governance can
# cancel wrongful ones (SlashDeferDuration = 4 * 7, runtime :563);
# configurable here — 0 applies immediately
SLASH_DEFER_ERAS_REF = 4 * 7


@codec.register
@dataclasses.dataclass(frozen=True)
class Exposure:
    """Who backs a validator for one era (Substrate's Exposure)."""

    own: int
    nominators: tuple[tuple[str, int], ...]
    total: int


class Staking:
    def __init__(self, state: State, balances: Balances,
                 slash_defer_eras: int = 0):
        if not 0 <= slash_defer_eras < BONDING_DURATION_ERAS:
            # a deferral >= the bonding duration would let an offender
            # withdraw the whole ledger before the slash ever applies
            # (the reference enforces the same: pallet/mod.rs:828)
            raise ValueError(
                f"slash_defer_eras {slash_defer_eras} must be < "
                f"BONDING_DURATION_ERAS {BONDING_DURATION_ERAS}")
        self.state = state
        self.balances = balances
        self.slash_defer_eras = slash_defer_eras

    # -- bonding --------------------------------------------------------------
    def bond(self, who: str, amount: int) -> None:
        if amount <= 0:
            raise DispatchError("staking.InvalidAmount")
        self.balances.reserve(who, amount)
        self.state.put(PALLET, "bond", who, self.bonded(who) + amount)
        self._bags_update(who)
        self.state.deposit_event(PALLET, "Bonded", who=who, amount=amount)

    def unbond(self, who: str, amount: int) -> None:
        """Active bond -> an unlocking chunk released BondingDuration
        eras later by withdraw_unbonded (ref BondingDuration = 112
        eras, runtime/src/lib.rs:562; MaxUnlockingChunks cap). Funds
        stay reserved — and slashable — until withdrawn."""
        b = self.bonded(who)
        if not isinstance(amount, int) or amount <= 0 or amount > b:
            raise DispatchError("staking.InvalidAmount")
        if who in self.validators() and b - amount < MIN_VALIDATOR_BOND:
            raise DispatchError("staking.InsufficientBond",
                                "would fall below MinValidatorBond")
        chunks = self.state.get(PALLET, "unlocking", who, default=())
        unlock_era = self.current_era() + BONDING_DURATION_ERAS
        if chunks and chunks[-1][1] == unlock_era:
            # merge same-era unbonds into one chunk (Substrate does;
            # otherwise repeated small unbonds exhaust the chunk cap)
            chunks = chunks[:-1] + ((chunks[-1][0] + amount, unlock_era),)
        elif len(chunks) >= MAX_UNLOCKING_CHUNKS:
            raise DispatchError("staking.NoMoreChunks")
        else:
            chunks = chunks + ((amount, unlock_era),)
        self.state.put(PALLET, "unlocking", who, chunks)
        self.state.put(PALLET, "bond", who, b - amount)
        self._bags_update(who)
        self.state.deposit_event(PALLET, "Unbonded", who=who,
                                 amount=amount, unlock_era=unlock_era)

    def withdraw_unbonded(self, who: str) -> int:
        """Release every unlocking chunk whose era has passed
        (withdraw_unbonded, pallet/mod.rs:716). Returns the amount."""
        chunks = self.state.get(PALLET, "unlocking", who, default=())
        if not chunks:
            raise DispatchError("staking.NoUnlockChunk", who)
        era = self.current_era()
        due = sum(a for a, e in chunks if e <= era)
        left = tuple((a, e) for a, e in chunks if e > era)
        if due:
            self.balances.unreserve(who, due)
        if left:
            self.state.put(PALLET, "unlocking", who, left)
        else:
            self.state.delete(PALLET, "unlocking", who)
        if due:
            self.state.deposit_event(PALLET, "Withdrawn", who=who,
                                     amount=due)
        return due

    def unlocking(self, who: str) -> tuple:
        return self.state.get(PALLET, "unlocking", who, default=())

    def bonded(self, who: str) -> int:
        return self.state.get(PALLET, "bond", who, default=0)

    def validate(self, who: str, commission_permill: int = 0) -> None:
        """Declare validator intent (needs MinValidatorBond) with
        commission prefs (ValidatorPrefs, pallet/mod.rs:1111-1137)."""
        if self.bonded(who) < MIN_VALIDATOR_BOND:
            raise DispatchError("staking.InsufficientBond")
        if not isinstance(commission_permill, int) \
                or not 0 <= commission_permill <= 1000:
            raise DispatchError("staking.InvalidCommission")
        self.state.put(PALLET, "prefs", who, commission_permill)
        # a validator cannot simultaneously nominate: its bond would be
        # exposed twice (own + as someone's backer)
        self.state.delete(PALLET, "nomination", who)
        vals = self.validators()
        if who not in vals:
            self.state.put(PALLET, "validators", vals + (who,))
        self._bags_update(who)

    def commission(self, who: str) -> int:
        return self.state.get(PALLET, "prefs", who, default=0)

    def chill(self, who: str) -> None:
        """Drop validator intent AND any nomination (Substrate chill)."""
        vals = self.validators()
        if who in vals:
            self.state.put(PALLET, "validators",
                           tuple(v for v in vals if v != who))
            self._bags_update(who)
        self.state.delete(PALLET, "nomination", who)

    def validators(self) -> tuple[str, ...]:
        return self.state.get(PALLET, "validators", default=())

    # -- VoterList (bags-list) analog -----------------------------------------
    # The reference keeps a semi-sorted on-chain voter index
    # (pallet_bags_list as VoterList, runtime/src/lib.rs:1512) so the
    # election snapshot never scans every account. Same structure
    # here: validators live in log2-stake BAGS — ("bag", b) holds an
    # insertion-ordered tuple, ("bag_of", who) its index — updated
    # incrementally on every bond/unbond/slash/validate/chill, and the
    # election snapshot walks bags from the heaviest down
    # (top_stakers), stopping at its bound instead of scoring the full
    # candidate set.

    @staticmethod
    def bag_index(stake: int) -> int:
        return stake.bit_length()        # log2 buckets, exact enough

    def _bags_update(self, who: str) -> None:
        """Re-place ``who`` in the stake-ordered index. Call after any
        change to its bond or validator-set membership; no-op when the
        bag is already right (same-bag bond moves keep position, like
        the reference's lazy rebag)."""
        cur = self.state.get(PALLET, "bag_of", who)
        want = self.bag_index(self.bonded(who)) \
            if who in self.validators() else None
        if cur == want:
            return
        if cur is not None:
            members = tuple(m for m in self.state.get(
                PALLET, "bag", cur, default=()) if m != who)
            if members:
                self.state.put(PALLET, "bag", cur, members)
            else:
                self.state.delete(PALLET, "bag", cur)
        count = self.state.get(PALLET, "bag_count", default=0)
        if want is None:
            self.state.delete(PALLET, "bag_of", who)
            self.state.put(PALLET, "bag_count", count - 1)
        else:
            self.state.put(PALLET, "bag", want, self.state.get(
                PALLET, "bag", want, default=()) + (who,))
            self.state.put(PALLET, "bag_of", who, want)
            if cur is None:
                self.state.put(PALLET, "bag_count", count + 1)

    def top_stakers(self, limit: int) -> list[str]:
        """Up to ``limit`` validators, heaviest bags first (within a
        bag: insertion order — semi-sorted, like the reference's
        VoterList). A PARTIAL index (an old snapshot before the
        staking-v3 migration ran — even one where post-restart staking
        ops already indexed a few validators) falls back to the plain
        set: the bag_count counter vs the roster length detects it in
        O(1), so an un-upgraded restart can never hide incumbents from
        the election snapshot (review-caught on the empty-only check)."""
        vals = self.validators()
        if self.state.get(PALLET, "bag_count", default=0) != len(vals):
            # pre-migration fallback must still rank by stake — a
            # registration-order truncation would hide whales from the
            # snapshot (review-caught); O(V log V) only in this window
            return sorted(vals, key=lambda v: (-self.bonded(v), v))[:limit]
        bags = sorted(((k[0], v) for k, v in
                       self.state.iter_prefix(PALLET, "bag")),
                      reverse=True)
        out: list[str] = []
        for _, members in bags:
            for who in members:
                out.append(who)
                if len(out) >= limit:
                    return out
        return out

    # -- nominations (MaxNominations = 1, runtime/src/lib.rs:378) ---------------
    def nominate(self, who: str, target: str) -> None:
        if self.bonded(who) < MIN_NOMINATOR_BOND:
            raise DispatchError("staking.InsufficientBond",
                                "below MinNominatorBond")
        if target not in self.validators():
            raise DispatchError("staking.NotValidator", target)
        if who in self.validators():
            raise DispatchError("staking.AlreadyValidating", who)
        self.state.put(PALLET, "nomination", who, target)
        self.state.deposit_event(PALLET, "Nominated", who=who,
                                 target=target)

    def nomination(self, who: str) -> str | None:
        return self.state.get(PALLET, "nomination", who)

    def nominators_of(self, target: str) -> list[tuple[str, int]]:
        return sorted((n[0], self.bonded(n[0]))
                      for n, t in self.state.iter_prefix(PALLET,
                                                         "nomination")
                      if t == target)

    # -- era exposure -----------------------------------------------------------
    def capture_exposures(self, era: int) -> None:
        """Era start: freeze who backs whom with how much; the era's
        payout and any offence slashing use THIS snapshot, immune to
        post-hoc bond shuffling (ErasStakers, pallet/mod.rs:344-460)."""
        for v in (self.electable() or list(self.validators())):
            noms = tuple(self.nominators_of(v))
            own = self.bonded(v)
            self.state.put(PALLET, "exposure", era, v, Exposure(
                own=own, nominators=noms,
                total=own + sum(a for _, a in noms)))

    def exposure(self, era: int, validator: str) -> Exposure | None:
        return self.state.get(PALLET, "exposure", era, validator)

    def era_validators(self, era: int) -> list[str]:
        return [k[0] for k, _ in self.state.iter_prefix(PALLET,
                                                        "exposure", era)]

    def electable(self) -> list[str]:
        """Stake floor for election: MIN_ELECTABLE_STAKE = 3M DOLLARS
        (runtime/src/lib.rs:764-772)."""
        return [v for v in self.validators()
                if self.bonded(v) >= constants.MIN_ELECTABLE_STAKE]

    # -- era rewards (impls.rs:430-474) -----------------------------------------
    @staticmethod
    def rewards_in_year(year: int) -> tuple[int, int]:
        """(validator_total, sminer_total) issued across that year's
        eras; x0.841 decay, 30-year horizon."""
        if year >= constants.REWARD_YEARS:
            return 0, 0
        v = constants.VALIDATOR_REWARD_YEAR1
        s = constants.SMINER_REWARD_YEAR1
        for _ in range(year):
            v = v * constants.REWARD_DECAY_NUM // constants.REWARD_DECAY_DEN
            s = s * constants.REWARD_DECAY_NUM // constants.REWARD_DECAY_DEN
        return v, s

    def end_era(self, era_index: int) -> None:
        """Mint the era's issuance: validator share split by era
        exposure (commission off the top, remainder exposure-pro-rata
        across own + nominator stakes — Substrate's payout shape),
        sminer share into the reward pool."""
        year = era_index // ERAS_PER_YEAR
        v_year, s_year = self.rewards_in_year(year)
        v_era = v_year // ERAS_PER_YEAR
        s_era = s_year // ERAS_PER_YEAR
        self.balances.mint(REWARD_POOL, s_era)
        exposed = self.era_validators(era_index)
        if exposed:
            stakes = {v: self.exposure(era_index, v) for v in exposed}
            grand = sum(e.total for e in stakes.values())
            for v in sorted(exposed):
                e = stakes[v]
                if grand <= 0 or e.total <= 0:
                    continue
                pot = v_era * e.total // grand
                fee = pot * self.commission(v) // 1000
                rest = pot - fee
                self.balances.mint(v, fee + rest * e.own // e.total)
                for nom, amount in e.nominators:
                    self.balances.mint(nom, rest * amount // e.total)
        else:
            # genesis era: no exposure snapshot yet; split by own bond
            active = self.electable() or list(self.validators())
            total_bond = sum(self.bonded(v) for v in active)
            if total_bond > 0:
                for v in active:
                    self.balances.mint(v, v_era * self.bonded(v)
                                       // total_bond)
        # exposures are retained long enough for deferred slashes to
        # still see the offence era (HistoryDepth analog)
        retention = max(1, self.slash_defer_eras)
        for (e, v), _ in list(self.state.iter_prefix(PALLET, "exposure")):
            if e < era_index - retention:
                self.state.delete(PALLET, "exposure", e, v)
        self.state.put(PALLET, "era", era_index + 1)
        self.state.deposit_event(PALLET, "EraPaid", era=era_index,
                                 validator_payout=v_era, sminer_payout=s_era)

    def current_era(self) -> int:
        return self.state.get(PALLET, "era", default=0)

    # -- offence slashing ---------------------------------------------------------
    def _drain(self, who: str, amount: int) -> int:
        """Take up to ``amount`` from active bond first, then from
        unlocking chunks oldest-first (Substrate slashes the ledger
        including unlocking — queued withdrawals stay liable)."""
        taken = 0
        b = self.bonded(who)
        from_bond = min(b, amount)
        if from_bond:
            self.state.put(PALLET, "bond", who, b - from_bond)
            taken += from_bond
        if taken < amount:
            chunks = list(self.state.get(PALLET, "unlocking", who,
                                         default=()))
            kept = []
            for a, e in chunks:
                cut = min(a, amount - taken)
                taken += cut
                if a - cut:
                    kept.append((a - cut, e))
            if kept:
                self.state.put(PALLET, "unlocking", who, tuple(kept))
            else:
                self.state.delete(PALLET, "unlocking", who)
        if taken:
            self.balances.slash_reserved(who, taken, TREASURY)
            self._bags_update(who)
        return taken

    def _slash_one(self, who: str, permill: int) -> int:
        want = (self.bonded(who)
                + sum(a for a, _ in self.unlocking(who))) * permill // 1000
        taken = self._drain(who, want)
        self.state.deposit_event(PALLET, "Slashed", who=who, amount=taken,
                                 permill=permill)
        return taken

    def _slash_amount(self, who: str, amount: int) -> int:
        """Take up to ``amount`` from active bond + unlocking chunks
        (exposure-based slash: the EXPOSED stake is liable, wherever
        it currently sits in the ledger)."""
        taken = self._drain(who, amount)
        self.state.deposit_event(PALLET, "Slashed", who=who, amount=taken,
                                 permill=0)
        return taken

    def slash_fraction(self, who: str, permill: int,
                       era: int | None = None) -> int:
        if self.slash_defer_eras:
            # deferred application (SlashDeferDuration): queue now,
            # apply at era + defer unless governance cancels
            offence_era = self.current_era() if era is None else era
            apply_era = self.current_era() + self.slash_defer_eras
            sid = self.state.get(PALLET, "next_unapplied", default=0)
            self.state.put(PALLET, "next_unapplied", sid + 1)
            self.state.put(PALLET, "unapplied", sid,
                           (who, permill, offence_era, apply_era))
            self.state.deposit_event(PALLET, "SlashDeferred", id=sid,
                                     who=who, permill=permill,
                                     apply_era=apply_era)
            return 0
        return self._slash_now(who, permill, era)

    def cancel_deferred_slash(self, sid: int) -> None:
        """COUNCIL-ONLY (via motion): drop a queued slash before it
        applies (the reference's governance cancel path)."""
        if not self.state.contains(PALLET, "unapplied", sid):
            raise DispatchError("staking.NoSuchSlash", str(sid))
        self.state.delete(PALLET, "unapplied", sid)
        self.state.deposit_event(PALLET, "SlashCancelled", id=sid)

    def apply_due_slashes(self) -> None:
        """Era hook: apply queued slashes whose deferral elapsed."""
        now = self.current_era()
        for (sid,), (who, permill, offence_era, apply_era) in sorted(
                self.state.iter_prefix(PALLET, "unapplied")):
            if apply_era <= now:
                self.state.delete(PALLET, "unapplied", sid)
                self._slash_now(who, permill, offence_era)

    def _slash_now(self, who: str, permill: int,
                   era: int | None = None) -> int:
        """Slash ``permill``/1000 of the offender's exposure in the
        OFFENCE era (``era``; defaults to the current one) — own stake
        and every exposed nominator (Substrate slashes the offending
        era's exposure, so post-offence unbonding cannot dodge it
        beyond what already left the bond). Falls back to the live
        bond when no exposure snapshot exists (pruned or genesis).
        Returns the total taken."""
        e = self.exposure(self.current_era() if era is None else era, who)
        if e is None:
            taken = self._slash_one(who, permill)
            for nom, _ in self.nominators_of(who):
                # fraction of the nominator's WHOLE ledger (active +
                # unlocking): queued withdrawals stay liable here too
                taken += self._slash_one(nom, permill)
            return taken
        taken = self._slash_amount(who, e.own * permill // 1000)
        for nom, amount in e.nominators:
            taken += self._slash_amount(nom, amount * permill // 1000)
        return taken

    # -- scheduler slash (slashing.rs:694-705) ------------------------------------
    def slash_scheduler(self, stash: str) -> None:
        """5% of MinValidatorBond from the stash's ledger (active bond
        first, then unlocking chunks — unbonding does not shelter a
        misbehaving scheduler's stake) -> treasury."""
        amount = MIN_VALIDATOR_BOND * constants.SCHEDULER_SLASH_PERMILL // 1000
        taken = self._drain(stash, amount)
        self.state.deposit_event(PALLET, "SchedulerSlashed", stash=stash,
                                 amount=taken)
