"""Staking with CESS economics (reference: c-pallets/cess-staking).

The reference forks Substrate pallet-staking, changing the reward
schedule to a fixed yearly issuance split validator/sminer
(238.5M / 477M DOLLARS year 1, x0.841 per year for 30 years) with the
sminer share pushed into the sminer reward pool each era, and adding
``slash_scheduler`` = 5% of MinValidatorBond for TEE punishment.
Mirrors /root/reference/c-pallets/staking/src/: reward schedule
pallet/impls.rs:452-474, end_era sminer issuance :430-449,
slash_scheduler slashing.rs:694-705, config runtime/src/lib.rs:585-589.

Nominator/era-exposure machinery is intentionally collapsed to
validator self-bonds; the election itself is credit-weighted and lives
in cess_tpu/node/consensus.py (the reference's VrfSolver).
"""
from __future__ import annotations

from .. import constants
from .balances import Balances
from .sminer import REWARD_POOL
from .state import DispatchError, State

PALLET = "staking"
TREASURY = "treasury"

MIN_VALIDATOR_BOND = 1_000_000 * constants.DOLLARS   # runtime :585-589
ERAS_PER_YEAR = 365 * 4   # 6-hour eras (1h epochs x 6 sessions)


class Staking:
    def __init__(self, state: State, balances: Balances):
        self.state = state
        self.balances = balances

    # -- bonding --------------------------------------------------------------
    def bond(self, who: str, amount: int) -> None:
        if amount <= 0:
            raise DispatchError("staking.InvalidAmount")
        self.balances.reserve(who, amount)
        self.state.put(PALLET, "bond", who, self.bonded(who) + amount)
        self.state.deposit_event(PALLET, "Bonded", who=who, amount=amount)

    def unbond(self, who: str, amount: int) -> None:
        b = self.bonded(who)
        if amount <= 0 or amount > b:
            raise DispatchError("staking.InvalidAmount")
        if who in self.validators() and b - amount < MIN_VALIDATOR_BOND:
            raise DispatchError("staking.InsufficientBond",
                                "would fall below MinValidatorBond")
        self.balances.unreserve(who, amount)
        self.state.put(PALLET, "bond", who, b - amount)

    def bonded(self, who: str) -> int:
        return self.state.get(PALLET, "bond", who, default=0)

    def validate(self, who: str) -> None:
        """Declare validator intent (needs MinValidatorBond)."""
        if self.bonded(who) < MIN_VALIDATOR_BOND:
            raise DispatchError("staking.InsufficientBond")
        vals = self.validators()
        if who not in vals:
            self.state.put(PALLET, "validators", vals + (who,))

    def chill(self, who: str) -> None:
        vals = self.validators()
        if who in vals:
            self.state.put(PALLET, "validators",
                           tuple(v for v in vals if v != who))

    def validators(self) -> tuple[str, ...]:
        return self.state.get(PALLET, "validators", default=())

    def electable(self) -> list[str]:
        """Stake floor for election: MIN_ELECTABLE_STAKE = 3M DOLLARS
        (runtime/src/lib.rs:764-772)."""
        return [v for v in self.validators()
                if self.bonded(v) >= constants.MIN_ELECTABLE_STAKE]

    # -- era rewards (impls.rs:430-474) -----------------------------------------
    @staticmethod
    def rewards_in_year(year: int) -> tuple[int, int]:
        """(validator_total, sminer_total) issued across that year's
        eras; x0.841 decay, 30-year horizon."""
        if year >= constants.REWARD_YEARS:
            return 0, 0
        v = constants.VALIDATOR_REWARD_YEAR1
        s = constants.SMINER_REWARD_YEAR1
        for _ in range(year):
            v = v * constants.REWARD_DECAY_NUM // constants.REWARD_DECAY_DEN
            s = s * constants.REWARD_DECAY_NUM // constants.REWARD_DECAY_DEN
        return v, s

    def end_era(self, era_index: int) -> None:
        """Mint the era's issuance: validator share pro-rata by bond,
        sminer share into the reward pool."""
        year = era_index // ERAS_PER_YEAR
        v_year, s_year = self.rewards_in_year(year)
        v_era = v_year // ERAS_PER_YEAR
        s_era = s_year // ERAS_PER_YEAR
        self.balances.mint(REWARD_POOL, s_era)
        active = self.electable() or list(self.validators())
        total_bond = sum(self.bonded(v) for v in active)
        if total_bond > 0:
            for v in active:
                share = v_era * self.bonded(v) // total_bond
                self.balances.mint(v, share)
        self.state.put(PALLET, "era", era_index + 1)
        self.state.deposit_event(PALLET, "EraPaid", era=era_index,
                                 validator_payout=v_era, sminer_payout=s_era)

    def current_era(self) -> int:
        return self.state.get(PALLET, "era", default=0)

    # -- offence slashing ---------------------------------------------------------
    def slash_fraction(self, who: str, permill: int) -> int:
        """Slash ``permill``/1000 of the current bond to treasury
        (consensus-fault punishment; the reference routes offences
        through pallet-staking's slashing machinery). Returns the
        amount taken."""
        b = self.bonded(who)
        taken = b * permill // 1000
        if taken:
            self.state.put(PALLET, "bond", who, b - taken)
            self.balances.slash_reserved(who, taken, TREASURY)
        self.state.deposit_event(PALLET, "Slashed", who=who, amount=taken,
                                 permill=permill)
        return taken

    # -- scheduler slash (slashing.rs:694-705) ------------------------------------
    def slash_scheduler(self, stash: str) -> None:
        """5% of MinValidatorBond from the stash's bond -> treasury."""
        amount = MIN_VALIDATOR_BOND * constants.SCHEDULER_SLASH_PERMILL // 1000
        b = self.bonded(stash)
        taken = min(b, amount)
        if taken:
            self.state.put(PALLET, "bond", stash, b - taken)
            self.balances.slash_reserved(stash, taken, TREASURY)
        self.state.deposit_event(PALLET, "SchedulerSlashed", stash=stash,
                                 amount=taken)
