"""Governance: council motions + treasury spending + sudo retirement.

The reference composes Substrate governance — Council/
TechnicalCommittee collectives, Treasury with spend proposals and
approvals, Bounties (/root/reference/runtime/src/lib.rs:1516-1521) —
and a sudo pallet for the bootstrap phase. This module is the
minimum viable surface with the same control flow:

- **Council**: a root-set membership; members open motions that name a
  whitelisted governance call, vote aye/nay, and close — a strict
  majority of the membership executes the call with COUNCIL origin.
  (The whitelist is the analog of the collective's origin filter: the
  council cannot dispatch arbitrary runtime calls.)
- **Treasury**: anyone proposes a spend (bonding 5%, min 1 DOLLAR,
  the reference's ProposalBond); ONLY a council motion can approve or
  reject; approved spends pay out from the treasury account at the
  next era boundary (SpendPeriod analog); rejection slashes the bond
  to the treasury.
- **Sudo retirement**: a council motion can retire the sudo key
  permanently — the chain's path from bootstrap to collective
  control.
"""
from __future__ import annotations

from .. import constants
from .state import DispatchError, State

PALLET = "council"
TREASURY_PALLET = "treasury"
TREASURY_ACCOUNT = "treasury"

PROPOSAL_BOND_PERMILL = 50          # 5% (ref ProposalBond)
PROPOSAL_BOND_MIN = 1 * constants.DOLLARS
MOTION_LIFE_BLOCKS = 7 * constants.ONE_DAY_BLOCKS   # ref MotionDuration

# the only calls a council motion may execute (collective origin filter)
COUNCIL_CALLS = {
    "treasury.approve_spend",
    "treasury.reject_spend",
    "treasury.approve_bounty",
    "treasury.award_bounty",
    "treasury.close_bounty",
    "council.set_members",
    "system.retire_sudo",
    "system.apply_runtime_upgrade",
    "staking.cancel_deferred_slash",
}


class Council:
    def __init__(self, state: State, runtime):
        self.state = state
        self.runtime = runtime   # dispatch target for approved motions

    # -- membership (root) ---------------------------------------------------
    def set_members(self, members: tuple[str, ...]) -> None:
        if not isinstance(members, tuple) \
                or not all(isinstance(m, str) for m in members) \
                or len(set(members)) != len(members):
            raise DispatchError("council.BadMembers")
        new = tuple(sorted(members))
        self.state.put(PALLET, "members", new)
        # purge outgoing members' votes from open motions — stale ayes
        # must never carry a motion the sitting council does not back
        # (Substrate change_members_sorted does the same)
        for (mid,), (ayes, nays) in list(self.state.iter_prefix(PALLET,
                                                                "votes")):
            kept = (tuple(a for a in ayes if a in new),
                    tuple(x for x in nays if x in new))
            if kept != (ayes, nays):
                self.state.put(PALLET, "votes", mid, kept)
        self.state.deposit_event(PALLET, "MembersSet",
                                 count=len(members))

    def members(self) -> tuple[str, ...]:
        return self.state.get(PALLET, "members", default=())

    def _require_member(self, who: str) -> None:
        if who not in self.members():
            raise DispatchError("council.NotMember", who)

    # -- motions ---------------------------------------------------------------
    def propose(self, who: str, call: str, args: tuple) -> int:
        self._require_member(who)
        if call not in COUNCIL_CALLS:
            raise DispatchError("council.CallNotAllowed", call)
        if not isinstance(args, tuple):
            raise DispatchError("council.BadArgs")
        mid = self.state.get(PALLET, "next_motion", default=0)
        self.state.put(PALLET, "next_motion", mid + 1)
        self.state.put(PALLET, "motion", mid,
                       (call, args, self.state.block + MOTION_LIFE_BLOCKS))
        self.state.put(PALLET, "votes", mid, ((who,), ()))   # ayes, nays
        self.state.deposit_event(PALLET, "Proposed", motion=mid,
                                 call=call, who=who)
        return mid

    def motion(self, mid: int):
        return self.state.get(PALLET, "motion", mid)

    def vote(self, who: str, mid: int, approve: bool) -> None:
        self._require_member(who)
        if self.motion(mid) is None:
            raise DispatchError("council.NoMotion", str(mid))
        ayes, nays = self.state.get(PALLET, "votes", mid)
        if who in ayes or who in nays:
            raise DispatchError("council.AlreadyVoted", who)
        if approve:
            ayes = tuple(sorted((*ayes, who)))
        else:
            nays = tuple(sorted((*nays, who)))
        self.state.put(PALLET, "votes", mid, (ayes, nays))
        self.state.deposit_event(PALLET, "Voted", motion=mid, who=who,
                                 approve=bool(approve))

    def close(self, who: str, mid: int) -> None:
        """Execute (strict majority aye), or drop (majority nay /
        expired). Anyone may close."""
        m = self.motion(mid)
        if m is None:
            raise DispatchError("council.NoMotion", str(mid))
        call, args, deadline = m
        ayes, nays = self.state.get(PALLET, "votes", mid)
        n = len(self.members())
        if 2 * len(ayes) > n:
            self.state.delete(PALLET, "motion", mid)
            self.state.delete(PALLET, "votes", mid)
            # execute in a SUB-transaction: a failing call (e.g. the
            # spend was already approved by another motion) must not
            # roll back the motion's removal and brick it open forever
            pallet_name, _, method = call.partition(".")
            self.state.begin_tx()
            try:
                getattr(self.runtime.pallets[pallet_name], method)(*args)
            except DispatchError as e:
                self.state.rollback_tx()
                self.state.deposit_event(PALLET, "ExecutionFailed",
                                         motion=mid, call=call,
                                         error=e.name)
            except Exception as e:
                # arity/type errors from motion args must not leak the
                # open tx mark (that would desync block undo logs)
                self.state.rollback_tx()
                self.state.deposit_event(
                    PALLET, "ExecutionFailed", motion=mid, call=call,
                    error=f"council.BadMotionArgs:{type(e).__name__}")
            else:
                self.state.commit_tx()
                self.state.deposit_event(PALLET, "Executed", motion=mid,
                                         call=call)
        elif 2 * len(nays) >= n or self.state.block > deadline:
            self.state.delete(PALLET, "motion", mid)
            self.state.delete(PALLET, "votes", mid)
            self.state.deposit_event(PALLET, "Disapproved", motion=mid)
        else:
            raise DispatchError("council.TooEarly", str(mid))


class Treasury:
    """Spend proposals against the treasury account. Fees already
    accumulate here (80% split, runtime/src/lib.rs:190-204); this
    pallet lets the council actually spend them — round-2 VERDICT:
    'Treasury here is just an account that absorbs fees; nothing can
    ever spend it'."""

    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    def propose_spend(self, who: str, beneficiary: str,
                      amount: int) -> int:
        if not isinstance(amount, int) or amount <= 0 \
                or not isinstance(beneficiary, str) or not beneficiary:
            raise DispatchError("treasury.InvalidProposal")
        bond = max(amount * PROPOSAL_BOND_PERMILL // 1000,
                   PROPOSAL_BOND_MIN)
        self.balances.reserve(who, bond)
        pid = self.state.get(TREASURY_PALLET, "next_proposal", default=0)
        self.state.put(TREASURY_PALLET, "next_proposal", pid + 1)
        self.state.put(TREASURY_PALLET, "proposal", pid,
                       (who, beneficiary, amount, bond))
        self.state.deposit_event(TREASURY_PALLET, "SpendProposed",
                                 proposal=pid, beneficiary=beneficiary,
                                 amount=amount)
        return pid

    def proposal(self, pid: int):
        return self.state.get(TREASURY_PALLET, "proposal", pid)

    # COUNCIL-ONLY (not in the dispatch surface; reachable only via a
    # council motion — the collective's ApproveOrigin)
    def approve_spend(self, pid: int) -> None:
        p = self.proposal(pid)
        if p is None:
            raise DispatchError("treasury.NoProposal", str(pid))
        who, beneficiary, amount, bond = p
        self.balances.unreserve(who, bond)
        self.state.delete(TREASURY_PALLET, "proposal", pid)
        approved = self.state.get(TREASURY_PALLET, "approved", default=())
        self.state.put(TREASURY_PALLET, "approved",
                       approved + ((beneficiary, amount),))
        self.state.deposit_event(TREASURY_PALLET, "SpendApproved",
                                 proposal=pid)

    def reject_spend(self, pid: int) -> None:
        p = self.proposal(pid)
        if p is None:
            raise DispatchError("treasury.NoProposal", str(pid))
        who, _, _, bond = p
        self.state.delete(TREASURY_PALLET, "proposal", pid)
        self.balances.slash_reserved(who, bond, TREASURY_ACCOUNT)
        self.state.deposit_event(TREASURY_PALLET, "SpendRejected",
                                 proposal=pid, bond_slashed=bond)

    def on_spend_period(self) -> None:
        """Era hook (SpendPeriod analog): pay out approved spends from
        the treasury balance, requeueing what cannot be afforded."""
        approved = self.state.get(TREASURY_PALLET, "approved", default=())
        if not approved:
            return
        left = []
        for beneficiary, amount in approved:
            if self.balances.free(TREASURY_ACCOUNT) >= amount:
                self.balances.transfer(TREASURY_ACCOUNT, beneficiary,
                                       amount)
                self.state.deposit_event(TREASURY_PALLET, "Spent",
                                         beneficiary=beneficiary,
                                         amount=amount)
            else:
                left.append((beneficiary, amount))
        self.state.put(TREASURY_PALLET, "approved", tuple(left))

    # -- bounties (the reference composes pallet_bounties,
    # runtime/src/lib.rs:1521) ------------------------------------------------
    def propose_bounty(self, who: str, description: bytes,
                       value: int) -> int:
        """Anyone proposes a bounty (bonding like a spend proposal);
        it becomes fundable only via council approval."""
        if not isinstance(value, int) or value <= 0 \
                or not isinstance(description, bytes) \
                or len(description) > 128:
            raise DispatchError("treasury.InvalidBounty")
        bond = max(value * PROPOSAL_BOND_PERMILL // 1000,
                   PROPOSAL_BOND_MIN)
        self.balances.reserve(who, bond)
        bid = self.state.get(TREASURY_PALLET, "next_bounty", default=0)
        self.state.put(TREASURY_PALLET, "next_bounty", bid + 1)
        self.state.put(TREASURY_PALLET, "bounty", bid,
                       (who, description, value, bond, "proposed"))
        self.state.deposit_event(TREASURY_PALLET, "BountyProposed",
                                 bounty=bid, value=value)
        return bid

    def bounty(self, bid: int):
        return self.state.get(TREASURY_PALLET, "bounty", bid)

    # COUNCIL-ONLY (reachable only through motions)
    def approve_bounty(self, bid: int) -> None:
        b = self.bounty(bid)
        if b is None or b[4] != "proposed":
            raise DispatchError("treasury.NoBounty", str(bid))
        who, desc, value, bond, _ = b
        self.balances.unreserve(who, bond)
        self.state.put(TREASURY_PALLET, "bounty", bid,
                       (who, desc, value, 0, "active"))
        self.state.deposit_event(TREASURY_PALLET, "BountyApproved",
                                 bounty=bid)

    def award_bounty(self, bid: int, beneficiary: str) -> None:
        """Council awards an active bounty: the value joins the
        spend-period queue for the beneficiary."""
        if not isinstance(beneficiary, str) or not beneficiary:
            raise DispatchError("treasury.InvalidBounty", "beneficiary")
        b = self.bounty(bid)
        if b is None or b[4] != "active":
            raise DispatchError("treasury.NoBounty", str(bid))
        _, _, value, _, _ = b
        self.state.delete(TREASURY_PALLET, "bounty", bid)
        approved = self.state.get(TREASURY_PALLET, "approved", default=())
        self.state.put(TREASURY_PALLET, "approved",
                       approved + ((beneficiary, value),))
        self.state.deposit_event(TREASURY_PALLET, "BountyAwarded",
                                 bounty=bid, beneficiary=beneficiary,
                                 amount=value)

    def close_bounty(self, bid: int) -> None:
        """Council drops a bounty; a still-'proposed' bounty's bond is
        slashed to the treasury (spurious proposal), an active one is
        simply retired."""
        b = self.bounty(bid)
        if b is None:
            raise DispatchError("treasury.NoBounty", str(bid))
        who, _, _, bond, status = b
        self.state.delete(TREASURY_PALLET, "bounty", bid)
        if status == "proposed" and bond:
            self.balances.slash_reserved(who, bond, TREASURY_ACCOUNT)
        self.state.deposit_event(TREASURY_PALLET, "BountyClosed",
                                 bounty=bid)
