"""Governance: collectives (Council + TechnicalCommittee), treasury,
bounties, sudo retirement.

The reference composes Substrate governance — Council/
TechnicalCommittee collectives (both pallet_collective instances with
PrimeDefaultVote, /root/reference/runtime/src/lib.rs:390-418),
Treasury with spend proposals and approvals, Bounties
(/root/reference/runtime/src/lib.rs:1516-1521) — and a sudo pallet for
the bootstrap phase. This module is the minimum viable surface with
the same control flow:

- **Collective** (shared machinery): a root-set membership; members
  open motions that name a whitelisted governance call, vote aye/nay,
  and close — a strict majority of the membership executes the call
  with the collective's origin. (The whitelist is the analog of the
  collective's origin filter: a collective cannot dispatch arbitrary
  runtime calls.) An optional PRIME member supplies the default vote
  of absent members at close (Substrate PrimeDefaultVote,
  runtime/src/lib.rs:404,417).
- **Council**: approves/rejects treasury spends + bounties, rotates
  its membership, retires sudo, applies runtime upgrades, cancels
  deferred slashes.
- **TechnicalCommittee**: the second chamber — can VETO an open
  council motion (the analog of its democracy-cancel role), fast-track
  runtime upgrades, and manage the TEE MRENCLAVE whitelist.
- **Treasury**: anyone proposes a spend (bonding 5%, min 1 DOLLAR,
  the reference's ProposalBond); ONLY a council motion can approve or
  reject; approved spends pay out from the treasury account at the
  next era boundary (SpendPeriod analog); rejection slashes the bond
  to the treasury.
- **Sudo retirement**: a council motion can retire the sudo key
  permanently — the chain's path from bootstrap to collective
  control.
"""
from __future__ import annotations

from .. import constants
from .state import DispatchError, State

PALLET = "council"
TC_PALLET = "technical_committee"
TREASURY_PALLET = "treasury"
TREASURY_ACCOUNT = "treasury"

PROPOSAL_BOND_PERMILL = 50          # 5% (ref ProposalBond)
PROPOSAL_BOND_MIN = 1 * constants.DOLLARS
MOTION_LIFE_BLOCKS = 7 * constants.ONE_DAY_BLOCKS   # ref MotionDuration

# the only calls a council motion may execute (collective origin filter)
COUNCIL_CALLS = {
    "treasury.approve_spend",
    "treasury.reject_spend",
    "treasury.approve_bounty",
    "treasury.award_bounty",
    "treasury.close_bounty",
    "treasury.assign_curator",
    "council.set_members",
    # TC membership curation (pallet_membership role): council motions
    # manage the second chamber incrementally
    "technical_committee.add_member",
    "technical_committee.remove_member",
    "technical_committee.swap_member",
    "system.retire_sudo",
    "system.apply_runtime_upgrade",
    "staking.cancel_deferred_slash",
}

# the technical committee's narrower surface (ref: TC origins gate
# democracy cancellation + technical paths, runtime/src/lib.rs:406-418)
TC_CALLS = {
    "council.veto_motion",
    "system.apply_runtime_upgrade",
    "tee_worker.update_whitelist",
}


class Collective:
    """One pallet_collective instance: motions over a whitelisted call
    set, strict-majority close, prime default vote."""

    PALLET = PALLET
    ALLOWED = COUNCIL_CALLS

    def __init__(self, state: State, runtime):
        self.state = state
        self.runtime = runtime   # dispatch target for approved motions

    # -- membership (root) ---------------------------------------------------
    def set_members(self, members: tuple[str, ...],
                    prime: str | None = None) -> None:
        if not isinstance(members, tuple) \
                or not all(isinstance(m, str) and m for m in members) \
                or len(set(members)) != len(members):
            raise DispatchError(f"{self.PALLET}.BadMembers")
        if prime is not None and prime not in members:
            raise DispatchError(f"{self.PALLET}.BadPrime")
        new = tuple(sorted(members))
        self.state.put(self.PALLET, "members", new)
        self.state.put(self.PALLET, "prime", prime)
        # purge outgoing members' votes from open motions — stale ayes
        # must never carry a motion the sitting membership does not
        # back (Substrate change_members_sorted does the same)
        for (mid,), (ayes, nays) in list(self.state.iter_prefix(self.PALLET,
                                                                "votes")):
            kept = (tuple(a for a in ayes if a in new),
                    tuple(x for x in nays if x in new))
            if kept != (ayes, nays):
                self.state.put(self.PALLET, "votes", mid, kept)
        self.state.deposit_event(self.PALLET, "MembersSet",
                                 count=len(members))

    def members(self) -> tuple[str, ...]:
        return self.state.get(self.PALLET, "members", default=())

    def prime(self) -> str | None:
        return self.state.get(self.PALLET, "prime", default=None)

    def _require_member(self, who: str) -> None:
        if who not in self.members():
            raise DispatchError(f"{self.PALLET}.NotMember", who)

    # -- motions ---------------------------------------------------------------
    def propose(self, who: str, call: str, args: tuple) -> int:
        self._require_member(who)
        if call not in self.ALLOWED:
            raise DispatchError(f"{self.PALLET}.CallNotAllowed", call)
        if not isinstance(args, tuple):
            raise DispatchError(f"{self.PALLET}.BadArgs")
        mid = self.state.get(self.PALLET, "next_motion", default=0)
        self.state.put(self.PALLET, "next_motion", mid + 1)
        self.state.put(self.PALLET, "motion", mid,
                       (call, args, self.state.block + MOTION_LIFE_BLOCKS))
        self.state.put(self.PALLET, "votes", mid, ((who,), ()))  # ayes, nays
        self.state.deposit_event(self.PALLET, "Proposed", motion=mid,
                                 call=call, who=who)
        return mid

    def motion(self, mid: int):
        return self.state.get(self.PALLET, "motion", mid)

    def vote(self, who: str, mid: int, approve: bool) -> None:
        self._require_member(who)
        if self.motion(mid) is None:
            raise DispatchError(f"{self.PALLET}.NoMotion", str(mid))
        ayes, nays = self.state.get(self.PALLET, "votes", mid)
        if who in ayes or who in nays:
            raise DispatchError(f"{self.PALLET}.AlreadyVoted", who)
        if approve:
            ayes = tuple(sorted((*ayes, who)))
        else:
            nays = tuple(sorted((*nays, who)))
        self.state.put(self.PALLET, "votes", mid, (ayes, nays))
        self.state.deposit_event(self.PALLET, "Voted", motion=mid, who=who,
                                 approve=bool(approve))

    def close(self, who: str, mid: int) -> None:
        """Execute (strict majority aye), or drop (majority nay /
        expired). Anyone may close. With a prime member set, absent
        members count as voting the prime's way (PrimeDefaultVote) —
        but ONLY once the motion's voting window has ended (Substrate
        semantics): before the deadline a close needs enough ACTUAL
        votes, so a prime can never propose-and-execute alone in one
        block, denying other members (and the TC veto) their window."""
        m = self.motion(mid)
        if m is None:
            raise DispatchError(f"{self.PALLET}.NoMotion", str(mid))
        call, args, deadline = m
        ayes, nays = self.state.get(self.PALLET, "votes", mid)
        members = self.members()
        n = len(members)
        prime = self.prime()
        absent = sum(1 for x in members if x not in ayes and x not in nays)
        n_ayes, n_nays = len(ayes), len(nays)
        if prime is not None and absent and self.state.block >= deadline:
            if prime in ayes:
                n_ayes += absent
            elif prime in nays:
                n_nays += absent
        if 2 * n_ayes > n:
            self.state.delete(self.PALLET, "motion", mid)
            self.state.delete(self.PALLET, "votes", mid)
            # execute in a SUB-transaction: a failing call (e.g. the
            # spend was already approved by another motion) must not
            # roll back the motion's removal and brick it open forever
            pallet_name, _, method = call.partition(".")
            self.state.begin_tx()
            try:
                getattr(self.runtime.pallets[pallet_name], method)(*args)
            except DispatchError as e:
                self.state.rollback_tx()
                self.state.deposit_event(self.PALLET, "ExecutionFailed",
                                         motion=mid, call=call,
                                         error=e.name)
            except Exception as e:
                # arity/type errors from motion args must not leak the
                # open tx mark (that would desync block undo logs)
                self.state.rollback_tx()
                self.state.deposit_event(
                    self.PALLET, "ExecutionFailed", motion=mid, call=call,
                    error=f"{self.PALLET}.BadMotionArgs:{type(e).__name__}")
            else:
                self.state.commit_tx()
                self.state.deposit_event(self.PALLET, "Executed",
                                         motion=mid, call=call)
        elif 2 * n_nays >= n or self.state.block > deadline:
            self.state.delete(self.PALLET, "motion", mid)
            self.state.delete(self.PALLET, "votes", mid)
            self.state.deposit_event(self.PALLET, "Disapproved", motion=mid)
        else:
            raise DispatchError(f"{self.PALLET}.TooEarly", str(mid))


class Council(Collective):
    # TC-ONLY (not in any dispatch surface or COUNCIL_CALLS; reachable
    # only through a TechnicalCommittee motion — its democracy-cancel
    # analog, runtime/src/lib.rs:406-418)
    def veto_motion(self, mid: int) -> None:
        if self.motion(mid) is None:
            raise DispatchError("council.NoMotion", str(mid))
        self.state.delete(PALLET, "motion", mid)
        self.state.delete(PALLET, "votes", mid)
        self.state.deposit_event(PALLET, "Vetoed", motion=mid)


class TechnicalCommittee(Collective):
    PALLET = TC_PALLET
    ALLOWED = TC_CALLS

    # -- membership management (pallet_membership::<Instance1>, ref
    # runtime/src/lib.rs:1520: the council curates TC membership via
    # motions, incremental ops instead of wholesale root set_members) --
    def add_member(self, who: str) -> None:
        members = self.members()
        if who in members:
            raise DispatchError(f"{self.PALLET}.AlreadyMember", who)
        self.set_members(members + (who,), prime=self.prime())

    def remove_member(self, who: str) -> None:
        members = self.members()
        if who not in members:
            raise DispatchError(f"{self.PALLET}.NotMember", who)
        prime = self.prime()
        self.set_members(tuple(m for m in members if m != who),
                         prime=None if prime == who else prime)

    def swap_member(self, out: str, new: str) -> None:
        members = self.members()
        if out not in members:
            raise DispatchError(f"{self.PALLET}.NotMember", out)
        if out == new:
            return            # pallet_membership: self-swap is a no-op
        if new in members:
            raise DispatchError(f"{self.PALLET}.AlreadyMember", new)
        prime = self.prime()
        self.set_members(
            tuple(new if m == out else m for m in members),
            prime=new if prime == out else prime)


class Treasury:
    """Spend proposals against the treasury account. Fees already
    accumulate here (80% split, runtime/src/lib.rs:190-204); this
    pallet lets the council actually spend them — round-2 VERDICT:
    'Treasury here is just an account that absorbs fees; nothing can
    ever spend it'."""

    def __init__(self, state: State, balances):
        self.state = state
        self.balances = balances

    def propose_spend(self, who: str, beneficiary: str,
                      amount: int) -> int:
        if not isinstance(amount, int) or amount <= 0 \
                or not isinstance(beneficiary, str) or not beneficiary:
            raise DispatchError("treasury.InvalidProposal")
        bond = max(amount * PROPOSAL_BOND_PERMILL // 1000,
                   PROPOSAL_BOND_MIN)
        self.balances.reserve(who, bond)
        pid = self.state.get(TREASURY_PALLET, "next_proposal", default=0)
        self.state.put(TREASURY_PALLET, "next_proposal", pid + 1)
        self.state.put(TREASURY_PALLET, "proposal", pid,
                       (who, beneficiary, amount, bond))
        self.state.deposit_event(TREASURY_PALLET, "SpendProposed",
                                 proposal=pid, beneficiary=beneficiary,
                                 amount=amount)
        return pid

    def proposal(self, pid: int):
        return self.state.get(TREASURY_PALLET, "proposal", pid)

    # COUNCIL-ONLY (not in the dispatch surface; reachable only via a
    # council motion — the collective's ApproveOrigin)
    def approve_spend(self, pid: int) -> None:
        p = self.proposal(pid)
        if p is None:
            raise DispatchError("treasury.NoProposal", str(pid))
        who, beneficiary, amount, bond = p
        self.balances.unreserve(who, bond)
        self.state.delete(TREASURY_PALLET, "proposal", pid)
        approved = self.state.get(TREASURY_PALLET, "approved", default=())
        self.state.put(TREASURY_PALLET, "approved",
                       approved + ((beneficiary, amount),))
        self.state.deposit_event(TREASURY_PALLET, "SpendApproved",
                                 proposal=pid)

    def reject_spend(self, pid: int) -> None:
        p = self.proposal(pid)
        if p is None:
            raise DispatchError("treasury.NoProposal", str(pid))
        who, _, _, bond = p
        self.state.delete(TREASURY_PALLET, "proposal", pid)
        self.balances.slash_reserved(who, bond, TREASURY_ACCOUNT)
        self.state.deposit_event(TREASURY_PALLET, "SpendRejected",
                                 proposal=pid, bond_slashed=bond)

    def on_spend_period(self) -> None:
        """Era hook (SpendPeriod analog): pay out approved spends from
        the treasury balance, requeueing what cannot be afforded."""
        approved = self.state.get(TREASURY_PALLET, "approved", default=())
        if not approved:
            return
        left = []
        for beneficiary, amount in approved:
            if self.balances.free(TREASURY_ACCOUNT) >= amount:
                self.balances.transfer(TREASURY_ACCOUNT, beneficiary,
                                       amount)
                self.state.deposit_event(TREASURY_PALLET, "Spent",
                                         beneficiary=beneficiary,
                                         amount=amount)
            else:
                left.append((beneficiary, amount))
        self.state.put(TREASURY_PALLET, "approved", tuple(left))

    # -- bounties (the reference composes pallet_bounties,
    # runtime/src/lib.rs:1521) ------------------------------------------------
    def propose_bounty(self, who: str, description: bytes,
                       value: int) -> int:
        """Anyone proposes a bounty (bonding like a spend proposal);
        it becomes fundable only via council approval."""
        if not isinstance(value, int) or value <= 0 \
                or not isinstance(description, bytes) \
                or len(description) > 128:
            raise DispatchError("treasury.InvalidBounty")
        bond = max(value * PROPOSAL_BOND_PERMILL // 1000,
                   PROPOSAL_BOND_MIN)
        self.balances.reserve(who, bond)
        bid = self.state.get(TREASURY_PALLET, "next_bounty", default=0)
        self.state.put(TREASURY_PALLET, "next_bounty", bid + 1)
        self.state.put(TREASURY_PALLET, "bounty", bid,
                       (who, description, value, bond, "proposed"))
        self.state.deposit_event(TREASURY_PALLET, "BountyProposed",
                                 bounty=bid, value=value)
        return bid

    def bounty(self, bid: int):
        return self.state.get(TREASURY_PALLET, "bounty", bid)

    # COUNCIL-ONLY (reachable only through motions)
    def approve_bounty(self, bid: int) -> None:
        b = self.bounty(bid)
        if b is None or b[4] != "proposed":
            raise DispatchError("treasury.NoBounty", str(bid))
        who, desc, value, bond, _ = b
        self.balances.unreserve(who, bond)
        self.state.put(TREASURY_PALLET, "bounty", bid,
                       (who, desc, value, 0, "active"))
        self.state.deposit_event(TREASURY_PALLET, "BountyApproved",
                                 bounty=bid)

    def award_bounty(self, bid: int, beneficiary: str) -> None:
        """Council awards an active bounty: the value joins the
        spend-period queue for the beneficiary."""
        if not isinstance(beneficiary, str) or not beneficiary:
            raise DispatchError("treasury.InvalidBounty", "beneficiary")
        b = self.bounty(bid)
        if b is None or b[4] != "active":
            raise DispatchError("treasury.NoBounty", str(bid))
        if self._active_children(bid):
            raise DispatchError("treasury.HasActiveChildBounty", str(bid))
        _, _, value, _, _ = b
        # children carved value out of the parent; award the remainder
        value -= self.state.get(TREASURY_PALLET, "children_value", bid,
                                default=0)
        self._clear_bounty_state(bid)
        if value > 0:
            approved = self.state.get(TREASURY_PALLET, "approved",
                                      default=())
            self.state.put(TREASURY_PALLET, "approved",
                           approved + ((beneficiary, value),))
        self.state.deposit_event(TREASURY_PALLET, "BountyAwarded",
                                 bounty=bid, beneficiary=beneficiary,
                                 amount=value)

    # -- child bounties (pallet_child_bounties, runtime/src/lib.rs:1522) ------
    # A council-assigned CURATOR subdivides an active bounty: children
    # carve value out of the parent, the curator awards them directly
    # (no council motion per child), and the parent can only be awarded
    # once no child is active — for what remains of its value.
    def assign_curator(self, bid: int, curator: str) -> None:
        """Council-only (via motion): curator gains child-bounty rights."""
        b = self.bounty(bid)
        if b is None or b[4] != "active":
            raise DispatchError("treasury.NoBounty", str(bid))
        if not isinstance(curator, str) or not curator:
            raise DispatchError("treasury.InvalidBounty", "curator")
        self.state.put(TREASURY_PALLET, "curator", bid, curator)
        self.state.deposit_event(TREASURY_PALLET, "CuratorAssigned",
                                 bounty=bid, curator=curator)

    def _require_curator(self, who: str, bid: int):
        b = self.bounty(bid)
        if b is None or b[4] != "active":
            raise DispatchError("treasury.NoBounty", str(bid))
        if self.state.get(TREASURY_PALLET, "curator", bid) != who:
            raise DispatchError("treasury.NotCurator", str(bid))
        return b

    def child_bounty(self, bid: int, cid: int):
        return self.state.get(TREASURY_PALLET, "child", bid, cid)

    def add_child_bounty(self, who: str, bid: int, description: bytes,
                         value: int) -> int:
        b = self._require_curator(who, bid)
        if not isinstance(value, int) or value <= 0 \
                or not isinstance(description, bytes) \
                or len(description) > 128:
            raise DispatchError("treasury.InvalidBounty")
        carved = self.state.get(TREASURY_PALLET, "children_value", bid,
                                default=0)
        if carved + value > b[2]:
            raise DispatchError("treasury.InsufficientBountyValue")
        cid = self.state.get(TREASURY_PALLET, "next_child", bid, default=0)
        self.state.put(TREASURY_PALLET, "next_child", bid, cid + 1)
        self.state.put(TREASURY_PALLET, "child", bid, cid,
                       (description, value, "active"))
        self.state.put(TREASURY_PALLET, "children_value", bid,
                       carved + value)
        self.state.deposit_event(TREASURY_PALLET, "ChildBountyAdded",
                                 bounty=bid, child=cid, value=value)
        return cid

    def award_child_bounty(self, who: str, bid: int, cid: int,
                           beneficiary: str) -> None:
        self._require_curator(who, bid)
        c = self.child_bounty(bid, cid)
        if c is None or c[2] != "active":
            raise DispatchError("treasury.NoBounty", f"{bid}/{cid}")
        if not isinstance(beneficiary, str) or not beneficiary:
            raise DispatchError("treasury.InvalidBounty", "beneficiary")
        self.state.delete(TREASURY_PALLET, "child", bid, cid)
        # carved value stays carved: the parent award pays the REMAINDER
        approved = self.state.get(TREASURY_PALLET, "approved", default=())
        self.state.put(TREASURY_PALLET, "approved",
                       approved + ((beneficiary, c[1]),))
        self.state.deposit_event(TREASURY_PALLET, "ChildBountyAwarded",
                                 bounty=bid, child=cid,
                                 beneficiary=beneficiary, amount=c[1])

    def close_child_bounty(self, who: str, bid: int, cid: int) -> None:
        self._require_curator(who, bid)
        c = self.child_bounty(bid, cid)
        if c is None:
            raise DispatchError("treasury.NoBounty", f"{bid}/{cid}")
        self.state.delete(TREASURY_PALLET, "child", bid, cid)
        carved = self.state.get(TREASURY_PALLET, "children_value", bid,
                                default=0)
        self.state.put(TREASURY_PALLET, "children_value", bid,
                       max(0, carved - c[1]))    # uncarve: back to parent
        self.state.deposit_event(TREASURY_PALLET, "ChildBountyClosed",
                                 bounty=bid, child=cid)

    def _active_children(self, bid: int) -> bool:
        return any(True for _ in self.state.iter_prefix(
            TREASURY_PALLET, "child", bid))

    def _clear_bounty_state(self, bid: int) -> None:
        """Symmetric cleanup on every bounty-ending path: curator and
        child-accounting keys must not outlive the bounty row."""
        self.state.delete(TREASURY_PALLET, "bounty", bid)
        self.state.delete(TREASURY_PALLET, "curator", bid)
        self.state.delete(TREASURY_PALLET, "children_value", bid)
        self.state.delete(TREASURY_PALLET, "next_child", bid)

    def close_bounty(self, bid: int) -> None:
        """Council drops a bounty; a still-'proposed' bounty's bond is
        slashed to the treasury (spurious proposal), an active one is
        simply retired. A bounty with ACTIVE child bounties cannot be
        closed — close or award the children first, or their carved
        value would be orphaned (pallet_child_bounties' rule)."""
        b = self.bounty(bid)
        if b is None:
            raise DispatchError("treasury.NoBounty", str(bid))
        if self._active_children(bid):
            raise DispatchError("treasury.HasActiveChildBounty", str(bid))
        who, _, _, bond, status = b
        self._clear_bounty_state(bid)
        if status == "proposed" and bond:
            self.balances.slash_reserved(who, bond, TREASURY_ACCOUNT)
        self.state.deposit_event(TREASURY_PALLET, "BountyClosed",
                                 bounty=bid)
