"""Scheduler (TEE) credit scoring feeding validator election.

Reference: c-pallets/scheduler-credit — per-period accumulation of
bytes processed + punishment counts; score = share-of-work x 1000
- (10 x punish)^2; 5-period weighted history 50/20/15/10/5%.
Mirrors src/lib.rs: figure_credit_value :61-75, period rollover
:113-125, figure_credit_scores :187-227, ValidatorCredits :242-251,
weights :36-42.
"""
from __future__ import annotations

from .. import constants
from .state import State

PALLET = "scheduler_credit"

PERIOD_BLOCKS = constants.EPOCH_DURATION_BLOCKS * constants.SESSIONS_PER_ERA


class SchedulerCredit:
    def __init__(self, state: State, period_blocks: int = PERIOD_BLOCKS):
        self.state = state
        self.period_blocks = period_blocks

    # -- SchedulerCreditCounter trait ---------------------------------------
    def record_proceed_block_size(self, scheduler: str, size: int) -> None:
        cur = self.state.get(PALLET, "current", scheduler,
                             default=(0, 0))  # (bytes, punish)
        self.state.put(PALLET, "current", scheduler, (cur[0] + size, cur[1]))

    def record_punishment(self, scheduler: str) -> None:
        cur = self.state.get(PALLET, "current", scheduler, default=(0, 0))
        self.state.put(PALLET, "current", scheduler, (cur[0], cur[1] + 1))

    # -- scoring -------------------------------------------------------------
    @staticmethod
    def figure_credit_value(total_size: int, entry: tuple[int, int]) -> int:
        """share-of-work x 1000 - (10*punish)^2, floored at 0
        (lib.rs:61-75)."""
        size, punish = entry
        score = 0
        if total_size > 0:
            score = size * constants.CREDIT_SCORE_SCALE // total_size
        penalty = (10 * punish) ** 2
        return max(0, score - penalty)

    def _rollover(self) -> None:
        """Close the current period into each scheduler's history
        (most-recent first, 5 kept)."""
        entries = list(self.state.iter_prefix(PALLET, "current"))
        total = sum(e[0] for _, e in entries)
        for (who,), entry in entries:
            value = self.figure_credit_value(total, entry)
            hist = self.state.get(PALLET, "history", who, default=())
            hist = (value,) + hist[:len(constants.CREDIT_HISTORY_WEIGHTS) - 1]
            self.state.put(PALLET, "history", who, hist)
            self.state.delete(PALLET, "current", who)
        self.state.deposit_event(PALLET, "PeriodRollover",
                                 schedulers=len(entries), total=total)

    def credits(self) -> dict[str, int]:
        """Weighted 5-period credit per scheduler (ValidatorCredits
        impl, figure_credit_scores :187-227)."""
        out = {}
        for (who,), hist in self.state.iter_prefix(PALLET, "history"):
            score = 0
            for value, weight in zip(hist, constants.CREDIT_HISTORY_WEIGHTS):
                score += value * weight // 100
            out[who] = score
        return out

    # -- hook -----------------------------------------------------------------
    def on_initialize(self) -> None:
        if self.state.block > 0 and self.state.block % self.period_blocks == 0:
            self._rollover()
