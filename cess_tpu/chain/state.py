"""Journaled key-value state with transactional extrinsic semantics.

The reference runs on Substrate's overlay-changes storage with
transactional rollback per extrinsic; this is the same contract in
plain Python: ``get/put/delete`` over ``(pallet, item, *key)`` tuples,
a journal of old values, and nested begin/commit/rollback marks.

Discipline: stored values are treated as immutable — pallets write new
instances (dataclasses.replace / new dicts) instead of mutating in
place, so journal entries stay valid. ``get`` of a mutable value that
the caller intends to modify must be followed by ``put``.

State root: an INCREMENTALLY-maintained additive multiset hash
(AdHash): root = sum over entries of SHA-256(codec(key) || codec(value))
mod 2^256. Each put/delete/rollback is O(entry size), so per-block root
cost is O(changes) — independent of total state size (round-1 Weak #5:
the full O(n log n) rescan per block per replica). The reference's
analog is Substrate's Merkle trie; AdHash trades Merkle proofs (not
needed here — replicas re-execute everything) for O(1) updates. Its
collision resistance is that of the generalized-birthday bound, fine
for divergence DETECTION between honest replicas; a trie is the
upgrade path if light-client proofs are ever needed.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Iterator

from .. import codec


class DispatchError(Exception):
    """An extrinsic failed; the runtime rolls back its changes.

    Mirrors FRAME's DispatchError: carries a module-scoped error name
    (e.g. "sminer.InsufficientBalance") used by tests the way the
    reference uses assert_noop! error matching.
    """

    def __init__(self, name: str, detail: str = ""):
        self.name = name
        self.detail = detail
        super().__init__(f"{name}{': ' + detail if detail else ''}")


@codec.register
@dataclasses.dataclass(frozen=True)
class Event:
    pallet: str
    name: str
    data: tuple  # (key, value) pairs, hashable for equality checks


_TOMBSTONE = object()
_ROOT_MOD = 1 << 256


class State:
    """The chain state: KV store + events + block context."""

    EVENT_HISTORY_CAP = 10_000

    def __init__(self):
        self.kv: dict[tuple, Any] = {}
        self.events: list[Event] = []          # current block (cleared per block)
        self.event_history: list[tuple[int, Event]] = []  # (block, event), capped
        self.block: int = 0
        self._journal: list[tuple[tuple, Any]] = []  # (key, old or _TOMBSTONE)
        self._tx_marks: list[tuple[int, int]] = []   # (journal len, events len)
        self._root_acc: int = 0
        self._key_hash: dict[tuple, int] = {}        # key -> current entry hash
        self._key_enc: dict[tuple, bytes] = {}       # key -> codec encoding
        # (pallet, item) -> keys under that pair: iter_prefix/count_prefix
        # are O(bucket), not O(total state) — the per-block pallet scans
        # (lease GC, deal sweeps) are the hot callers
        self._pfx: dict[tuple, set[tuple]] = {}
        # (pallet, name|None) -> [(block, event)]; lazily pruned to the
        # history floor (may briefly retain a superset of a partially
        # trimmed block — a query-index property, not consensus state)
        self._event_index: dict[tuple, list[tuple[int, Event]]] = {}
        self._hist_floor: int = 0

    # -- root accounting -----------------------------------------------------
    def _entry_hash(self, key: tuple, value: Any) -> int:
        # keys are immutable tuples re-hashed on every put of the same
        # slot (block context, base fee, ...) — cache their encoding;
        # values change between puts and are encoded fresh
        enc = self._key_enc.get(key)
        if enc is None:
            enc = self._key_enc[key] = codec.encode(key)
        data = enc + b"\x00" + codec.encode(value)
        return int.from_bytes(hashlib.sha256(data).digest(), "little")

    def _root_add(self, key: tuple, value: Any) -> None:
        h = self._entry_hash(key, value)
        self._key_hash[key] = h
        self._root_acc = (self._root_acc + h) % _ROOT_MOD

    def _root_sub(self, key: tuple) -> None:
        h = self._key_hash.pop(key, None)
        if h is not None:
            self._root_acc = (self._root_acc - h) % _ROOT_MOD

    # -- prefix index --------------------------------------------------------
    def _index_add(self, key: tuple) -> None:
        self._pfx.setdefault(key[:2], set()).add(key)

    def _index_del(self, key: tuple) -> None:
        bucket = self._pfx.get(key[:2])
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._pfx[key[:2]]
        # the key is gone: drop its cached encoding too, or pruned
        # history keys (eth receipts, ...) grow the cache forever
        self._key_enc.pop(key, None)

    # -- kv ----------------------------------------------------------------
    def get(self, *key, default=None):
        return self.kv.get(key, default)

    def require(self, *key, err: str):
        if key not in self.kv:
            raise DispatchError(err, f"missing {key}")
        return self.kv[key]

    def contains(self, *key) -> bool:
        return key in self.kv

    def put(self, *key_and_value) -> None:
        *key, value = key_and_value
        key = tuple(key)
        self._journal.append((key, self.kv.get(key, _TOMBSTONE)))
        self._root_sub(key)
        self._root_add(key, value)
        self._index_add(key)
        self.kv[key] = value

    def delete(self, *key) -> None:
        key = tuple(key)
        if key in self.kv:
            self._journal.append((key, self.kv[key]))
            self._root_sub(key)
            self._index_del(key)
            del self.kv[key]

    def _prefix_keys(self, prefix: tuple) -> list[tuple]:
        """Candidate keys for a prefix, via the (pallet, item) index."""
        if len(prefix) >= 2:
            return list(self._pfx.get(prefix[:2], ()))
        # 0- or 1-element prefix: walk the (small) bucket directory
        # cesslint: disable=consensus-unordered-iter — callers sort
        return [k for b, keys in self._pfx.items()
                if not prefix or b[0] == prefix[0] for k in keys]

    def iter_prefix(self, *prefix) -> Iterator[tuple[tuple, Any]]:
        """Iterate (suffix, value) for all keys under a prefix, sorted
        (determinism: iteration order is part of consensus)."""
        n = len(prefix)
        items = [(k[n:], self.kv[k]) for k in self._prefix_keys(prefix)
                 if len(k) > n and k[:n] == prefix]
        items.sort(key=lambda kv: repr(kv[0]))
        return iter(items)

    def count_prefix(self, *prefix) -> int:
        n = len(prefix)
        return sum(1 for k in self._prefix_keys(prefix)
                   if len(k) > n and k[:n] == prefix)

    # -- events ------------------------------------------------------------
    def deposit_event(self, _pallet: str, _name: str, **data) -> None:
        # leading-underscore positionals keep e.g. name=... usable as a field
        self.events.append(Event(_pallet, _name, tuple(sorted(data.items()))))

    def events_of(self, pallet: str, name: str | None = None) -> list[Event]:
        """Match against the (capped) history + current block, oldest
        first. Indexed: O(matches), not O(history)."""
        idx_key = (pallet, name)
        idx = self._event_index.get(idx_key, [])
        if idx and idx[0][0] < self._hist_floor:
            idx = [e for e in idx if e[0] >= self._hist_floor]
            self._event_index[idx_key] = idx
        return [e for _, e in idx] \
            + [e for e in self.events
               if e.pallet == pallet and (name is None or e.name == name)]

    def archive_events(self) -> None:
        """Block boundary: move current events into the rolling history."""
        for e in self.events:
            entry = (self.block, e)
            self.event_history.append(entry)
            self._event_index.setdefault((e.pallet, e.name), []).append(entry)
            self._event_index.setdefault((e.pallet, None), []).append(entry)
        if len(self.event_history) > self.EVENT_HISTORY_CAP:
            del self.event_history[:len(self.event_history)
                                   - self.EVENT_HISTORY_CAP]
            self._hist_floor = self.event_history[0][0]
        self.events.clear()

    def truncate_history(self, min_block: int) -> None:
        """Abort-proposal support: drop every history/index entry
        stamped >= min_block (they were archived during the rolled-back
        block). Stamp-based, not length-based — a cap trim during the
        aborted proposal shifts positions but never stamps."""
        if not self.event_history \
                or self.event_history[-1][0] < min_block:
            return
        self.event_history[:] = [e for e in self.event_history
                                 if e[0] < min_block]
        # per-key filtering is order-independent and never feeds a hash
        # cesslint: disable=consensus-unordered-iter
        for k, lst in self._event_index.items():
            if lst and lst[-1][0] >= min_block:
                self._event_index[k] = [e for e in lst if e[0] < min_block]

    # -- transactions -------------------------------------------------------
    def begin_tx(self) -> None:
        self._tx_marks.append((len(self._journal), len(self.events)))

    def commit_tx(self) -> None:
        self._tx_marks.pop()

    def rollback_tx(self) -> None:
        jmark, emark = self._tx_marks.pop()
        while len(self._journal) > jmark:
            key, old = self._journal.pop()
            self._root_sub(key)
            if old is _TOMBSTONE:
                self._index_del(key)
                self.kv.pop(key, None)
            else:
                self.kv[key] = old
                self._root_add(key, old)
                self._index_add(key)
        del self.events[emark:]

    # -- block undo (fork-choice support) -----------------------------------
    def commit_tx_undo(self) -> list[tuple[tuple, Any]]:
        """Commit the open transaction but RETURN its journal segment
        as an undo log. Fork choice keeps one per non-finalized block
        so a reorg can rewind state to the fork point in O(changes)
        instead of replaying the whole chain (the role of Substrate's
        tree-backed storage overlays in the reference)."""
        jmark, _ = self._tx_marks.pop()
        undo = self._journal[jmark:]
        del self._journal[jmark:]
        return undo

    def apply_undo(self, undo: list[tuple[tuple, Any]]) -> None:
        """Rewind one committed block: restore every journaled old
        value (reverse order), maintaining the incremental root."""
        for key, old in reversed(undo):
            self._root_sub(key)
            if old is _TOMBSTONE:
                self._index_del(key)
                self.kv.pop(key, None)
            else:
                self.kv[key] = old
                self._root_add(key, old)
                self._index_add(key)

    # -- roots --------------------------------------------------------------
    def state_root(self) -> bytes:
        """The incrementally-maintained multiset root (see module
        docstring). O(1) per call."""
        return self._root_acc.to_bytes(32, "little")

    def _fold_root(self) -> tuple[int, dict[tuple, int]]:
        # the root is a commutative MULTISET sum (module docstring):
        # iteration order provably cannot change it
        # cesslint: disable=consensus-unordered-iter
        hashes = {k: self._entry_hash(k, v) for k, v in self.kv.items()}
        return sum(hashes.values()) % _ROOT_MOD, hashes

    def recompute_root(self) -> bytes:
        """Full O(n) rescan — the oracle the incremental root must
        match (tests). Does not touch the cache."""
        acc, _ = self._fold_root()
        return acc.to_bytes(32, "little")

    def rebuild_root_cache(self) -> None:
        """Rebuild the per-key hash cache + accumulator + prefix index
        from kv (used by the persistence layer after swapping in a
        snapshot's kv wholesale)."""
        self._key_enc = {}
        self._root_acc, self._key_hash = self._fold_root()
        self._pfx = {}
        for k in self.kv:
            self._index_add(k)
