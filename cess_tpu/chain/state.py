"""Journaled key-value state with transactional extrinsic semantics.

The reference runs on Substrate's overlay-changes storage with
transactional rollback per extrinsic; this is the same contract in
plain Python: ``get/put/delete`` over ``(pallet, item, *key)`` tuples,
a journal of old values, and nested begin/commit/rollback marks.

Discipline: stored values are treated as immutable — pallets write new
instances (dataclasses.replace / new dicts) instead of mutating in
place, so journal entries stay valid. ``get`` of a mutable value that
the caller intends to modify must be followed by ``put``.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Iterator


class DispatchError(Exception):
    """An extrinsic failed; the runtime rolls back its changes.

    Mirrors FRAME's DispatchError: carries a module-scoped error name
    (e.g. "sminer.InsufficientBalance") used by tests the way the
    reference uses assert_noop! error matching.
    """

    def __init__(self, name: str, detail: str = ""):
        self.name = name
        self.detail = detail
        super().__init__(f"{name}{': ' + detail if detail else ''}")


@dataclasses.dataclass(frozen=True)
class Event:
    pallet: str
    name: str
    data: tuple  # (key, value) pairs, hashable for equality checks


_TOMBSTONE = object()


class State:
    """The chain state: KV store + events + block context."""

    EVENT_HISTORY_CAP = 10_000

    def __init__(self):
        self.kv: dict[tuple, Any] = {}
        self.events: list[Event] = []          # current block (cleared per block)
        self.event_history: list[tuple[int, Event]] = []  # (block, event), capped
        self.block: int = 0
        self._journal: list[tuple[tuple, Any]] = []  # (key, old or _TOMBSTONE)
        self._tx_marks: list[tuple[int, int]] = []   # (journal len, events len)

    # -- kv ----------------------------------------------------------------
    def get(self, *key, default=None):
        return self.kv.get(key, default)

    def require(self, *key, err: str):
        if key not in self.kv:
            raise DispatchError(err, f"missing {key}")
        return self.kv[key]

    def contains(self, *key) -> bool:
        return key in self.kv

    def put(self, *key_and_value) -> None:
        *key, value = key_and_value
        key = tuple(key)
        self._journal.append((key, self.kv.get(key, _TOMBSTONE)))
        self.kv[key] = value

    def delete(self, *key) -> None:
        key = tuple(key)
        if key in self.kv:
            self._journal.append((key, self.kv[key]))
            del self.kv[key]

    def iter_prefix(self, *prefix) -> Iterator[tuple[tuple, Any]]:
        """Iterate (suffix, value) for all keys under a prefix, sorted
        (determinism: iteration order is part of consensus)."""
        n = len(prefix)
        items = [(k[n:], v) for k, v in self.kv.items()
                 if len(k) > n and k[:n] == prefix]
        items.sort(key=lambda kv: repr(kv[0]))
        return iter(items)

    def count_prefix(self, *prefix) -> int:
        n = len(prefix)
        return sum(1 for k in self.kv if len(k) > n and k[:n] == prefix)

    # -- events ------------------------------------------------------------
    def deposit_event(self, _pallet: str, _name: str, **data) -> None:
        # leading-underscore positionals keep e.g. name=... usable as a field
        self.events.append(Event(_pallet, _name, tuple(sorted(data.items()))))

    def events_of(self, pallet: str, name: str | None = None) -> list[Event]:
        """Match against the full (capped) history, oldest first."""
        hist = [e for _, e in self.event_history] + self.events
        return [e for e in hist
                if e.pallet == pallet and (name is None or e.name == name)]

    def archive_events(self) -> None:
        """Block boundary: move current events into the rolling history."""
        self.event_history.extend((self.block, e) for e in self.events)
        if len(self.event_history) > self.EVENT_HISTORY_CAP:
            del self.event_history[:len(self.event_history)
                                   - self.EVENT_HISTORY_CAP]
        self.events.clear()

    # -- transactions -------------------------------------------------------
    def begin_tx(self) -> None:
        self._tx_marks.append((len(self._journal), len(self.events)))

    def commit_tx(self) -> None:
        self._tx_marks.pop()

    def rollback_tx(self) -> None:
        jmark, emark = self._tx_marks.pop()
        while len(self._journal) > jmark:
            key, old = self._journal.pop()
            if old is _TOMBSTONE:
                self.kv.pop(key, None)
            else:
                self.kv[key] = old
        del self.events[emark:]

    # -- roots --------------------------------------------------------------
    def state_root(self) -> bytes:
        """sha256 over the sorted key/value reprs (cheap determinism
        check between replicas; not a Merkle trie)."""
        h = hashlib.sha256()
        for k in sorted(self.kv, key=repr):
            h.update(repr(k).encode())
            h.update(repr(self.kv[k]).encode())
        return h.digest()
