"""System pallet: account identity, nonces, session keys, sudo.

The reference authenticates every extrinsic through the frame-system +
SignedExtra pipeline (signature over (call, extra, genesis), nonce
check, fee withdrawal; /root/reference/runtime/src/lib.rs:1564-1590).
Here the same responsibilities live in one pallet:

- account-key binding: an account (a human-readable alias; the
  reference's AccountId IS the pubkey, the alias is this framework's
  dev ergonomics) is bound to an ed25519 public key at genesis or on
  first signed use; every later extrinsic must verify against it.
- nonce: strictly sequential per account, consumed even when the
  dispatch itself fails (replay protection, like frame-system).
- session keys: validators register the ed25519 key their offchain
  worker signs audit proposals with (the reference's SessionKeys
  ``audit`` entry, runtime/src/lib.rs:150-157).
- sudo: dev-chain root origin (the reference's pallet-sudo role);
  governance (round 2+) layers council approval on top.
"""
from __future__ import annotations

from .state import DispatchError, State

PALLET = "system"


class System:
    def __init__(self, state: State):
        self.state = state

    # -- account keys ---------------------------------------------------------
    def account_key(self, who: str) -> bytes | None:
        return self.state.get(PALLET, "account_key", who)

    def bind_account_key(self, who: str, public: bytes) -> None:
        """Genesis / first-use binding. Once bound, immutable."""
        cur = self.account_key(who)
        if cur is not None and cur != public:
            raise DispatchError("system.AccountKeyMismatch", who)
        self.state.put(PALLET, "account_key", who, public)

    # -- nonces ----------------------------------------------------------------
    def nonce(self, who: str) -> int:
        return self.state.get(PALLET, "nonce", who, default=0)

    def bump_nonce(self, who: str) -> None:
        self.state.put(PALLET, "nonce", who, self.nonce(who) + 1)

    # -- session keys ----------------------------------------------------------
    def session_key(self, who: str) -> bytes | None:
        return self.state.get(PALLET, "session_key", who)

    def set_session_key(self, who: str, public: bytes) -> None:
        """Extrinsic: a validator (re)registers its session key."""
        if not isinstance(public, bytes) or len(public) != 32:
            raise DispatchError("system.BadSessionKey", who)
        self.state.put(PALLET, "session_key", who, public)
        self.state.deposit_event(PALLET, "SessionKeySet", who=who)

    def now_ms(self) -> int:
        """Chain clock (the pallet_timestamp role): derived from block
        height at the fixed 6 s slot duration, written by init_block."""
        return self.state.get(PALLET, "now_ms", default=0)

    # -- sudo ------------------------------------------------------------------
    def sudo(self) -> str | None:
        return self.state.get(PALLET, "sudo")

    def set_sudo(self, who: str | None) -> None:
        self.state.put(PALLET, "sudo", who)

    def retire_sudo(self) -> None:
        """Permanently clear the sudo key (council-motion-only; the
        chain's bootstrap->collective-control transition, the
        reference's sudo removal path)."""
        self.state.put(PALLET, "sudo", None)
        self.state.deposit_event(PALLET, "SudoRetired")

    # -- runtime upgrade -------------------------------------------------------
    def apply_runtime_upgrade(self) -> None:
        """Root/council: activate the running code's pending storage
        migrations in-band (the set_code + on_runtime_upgrade analog).
        No-op if already current."""
        from . import migrations

        for name in migrations.run_pending(self.state):
            self.state.deposit_event(PALLET, "MigrationApplied",
                                     migration=name)
        self.state.deposit_event(
            PALLET, "RuntimeUpgraded",
            spec_version=migrations.spec_version(self.state))

    # -- misc ------------------------------------------------------------------
    def remark(self, who: str, data: bytes) -> None:
        self.state.deposit_event(PALLET, "Remark", who=who, size=len(data))
