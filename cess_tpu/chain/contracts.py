"""User-programmable contracts: a gas-metered VM over codec values.

The reference runs pallet-contracts (Wasm) ALONGSIDE the EVM
(/root/reference/runtime/src/lib.rs:1191-1207, composed at :1525).
This module is the framework-native second execution layer with the
same role split: where the EVM boundary (cess_tpu/chain/evm.py)
executes 256-bit-word bytecode for Ethereum-shaped tooling, the
contracts VM executes structured programs over the framework's OWN
canonical value model — ints (arbitrary precision), bytes, strings and
tuples — with per-contract KV storage, host functions, and strict gas
metering. Programs are codec-encodable tuples of instructions, so
deploy/call arguments ride the normal extrinsic wire format.

Execution model: a stack machine. Each instruction is a tuple
``(op, *immediates)``; values on the stack are codec values. Control
flow is absolute instruction-index jumps, checked per step. Gas is
charged per instruction plus size-dependent costs (storage writes,
value construction), so an infinite loop burns its gas limit and
reverts — block production can never stall. All storage writes go
through the transactional ``State``; a trap/out-of-gas raises
DispatchError and the surrounding dispatch rolls back.

Instruction set (stack effects in comments):
  ("push", v)        -> v
  ("pop",)           v ->
  ("dup", i)         duplicate i-th from top (0 = top)
  ("swap",)          a b -> b a
  ("add"|"sub"|"mul"|"div"|"mod",)   a b -> (a OP b), ints only
  ("eq"|"lt"|"gt",)  a b -> bool as 0/1
  ("not",)           a -> 0/1
  ("len",)           seq -> int
  ("index",)         seq i -> seq[i]
  ("concat",)        a b -> a + b  (bytes/str/tuple)
  ("tuple", n)       v1..vn -> (v1, .., vn)
  ("jump", pc)       absolute jump
  ("jumpi", pc)      cond -> ; jump when cond truthy
  ("input",)         -> the full call-input tuple (method, *args)
  ("caller",)        -> calling account id (str)
  ("sget",)          key -> storage[key] (None when absent)
  ("sput",)          key value ->
  ("emit",)          value -> (deposits a ContractEvent)
  ("return",)        value -> halt, value is the call result
  ("revert",)        value -> halt + revert with message
"""
from __future__ import annotations

import hashlib

from .overlay import ChainedOverlay
from .state import DispatchError, State

PALLET = "contracts"
GAS_CAP = 2_000_000
DEFAULT_GAS = 200_000
MAX_CODE_INSTRS = 16_384
MAX_VALUE_BYTES = 64 * 1024     # bound on constructed values
MAX_STACK = 256

G_STEP = 1
G_SGET = 20
G_SPUT = 200
G_EMIT = 50
G_XCALL = 700
MAX_DEPTH = 32                   # nesting bound for constructed values


class _Trap(Exception):
    pass


class _Revert(Exception):
    def __init__(self, value):
        self.value = value


def _size_of(v) -> int:
    """Iterative (no Python recursion — outcome must depend on gas,
    never interpreter stack depth) size with a hard nesting cap."""
    total = 0
    stack = [(v, 0)]
    while stack:
        x, depth = stack.pop()
        if depth > MAX_DEPTH:
            raise _Trap("value nesting too deep")
        if isinstance(x, (bytes, str)):
            total += len(x)
        elif isinstance(x, tuple):
            total += 1
            stack.extend((e, depth + 1) for e in x)
        elif isinstance(x, int) and not isinstance(x, bool):
            total += 8 + abs(x).bit_length() // 8   # big ints cost more
        else:
            total += 8
    return total


def _exec(code: tuple, *, input_tuple: tuple, caller: str,
          gas_limit: int, sget, sput, emit, xcall=None) -> object:
    """``xcall(addr, method, args, fwd_gas) -> (ok, value)`` services
    cross-contract calls (never raises; the forwarded gas is consumed
    in full by the op itself); absent a host, the op pushes a failure
    tuple."""
    stack: list = []
    gas = gas_limit
    pc = 0

    def use(n: int) -> None:
        nonlocal gas
        gas -= n
        if gas < 0:
            raise _Trap("out of gas")

    def pop():
        if not stack:
            raise _Trap("stack underflow")
        return stack.pop()

    def push(v) -> None:
        if len(stack) >= MAX_STACK:
            raise _Trap("stack overflow")
        stack.append(v)

    def int2(op):
        b, a = pop(), pop()
        if not (isinstance(a, int) and isinstance(b, int)
                and not isinstance(a, bool) and not isinstance(b, bool)):
            raise _Trap(f"{op}: ints required")
        return a, b

    while pc < len(code):
        ins = code[pc]
        pc += 1
        if not (isinstance(ins, tuple) and ins
                and isinstance(ins[0], str)):
            raise _Trap(f"malformed instruction at {pc - 1}")
        op = ins[0]
        use(G_STEP)
        if op == "push":
            if len(ins) != 2:
                raise _Trap("push arity")
            sz = _size_of(ins[1])
            if sz > MAX_VALUE_BYTES:
                raise _Trap("value too large")
            use(sz)
            push(ins[1])
        elif op == "pop":
            pop()
        elif op == "dup":
            i = ins[1] if len(ins) > 1 else 0
            if not isinstance(i, int) or not 0 <= i < len(stack):
                raise _Trap("dup index")
            push(stack[-1 - i])
        elif op == "swap":
            a, b = pop(), pop()
            push(a); push(b)
        elif op in ("add", "sub", "mul", "div", "mod"):
            a, b = int2(op)
            if op == "add":
                r = a + b
            elif op == "sub":
                r = a - b
            elif op == "mul":
                use(max(a.bit_length(), b.bit_length()) // 8)
                r = a * b
            elif op == "div":
                if b == 0:
                    raise _Trap("division by zero")
                r = a // b
            else:
                if b == 0:
                    raise _Trap("division by zero")
                r = a % b
            if abs(r) >> (8 * MAX_VALUE_BYTES):
                raise _Trap("integer too large")
            push(r)
        elif op in ("eq", "lt", "gt"):
            b, a = pop(), pop()
            if op == "eq":
                push(1 if a == b else 0)
            else:
                if not (isinstance(a, int) and isinstance(b, int)):
                    raise _Trap(f"{op}: ints required")
                push(1 if ((a < b) if op == "lt" else (a > b)) else 0)
        elif op == "not":
            push(0 if pop() else 1)
        elif op == "len":
            v = pop()
            if not isinstance(v, (bytes, str, tuple)):
                raise _Trap("len: sequence required")
            push(len(v))
        elif op == "index":
            i, v = pop(), pop()
            if not isinstance(v, (bytes, str, tuple)) \
                    or not isinstance(i, int) or not 0 <= i < len(v):
                raise _Trap("index out of range")
            push(v[i])
        elif op == "concat":
            b, a = pop(), pop()
            if not (type(a) is type(b)
                    and isinstance(a, (bytes, str, tuple))):
                raise _Trap("concat: matching sequences required")
            if _size_of(a) + _size_of(b) > MAX_VALUE_BYTES:
                raise _Trap("value too large")
            use(_size_of(a) + _size_of(b))
            push(a + b)
        elif op == "tuple":
            n = ins[1] if len(ins) > 1 else 0
            if not isinstance(n, int) or not 0 <= n <= len(stack):
                raise _Trap("tuple arity")
            vs = tuple(reversed([pop() for _ in range(n)]))
            sz = _size_of(vs)
            if sz > MAX_VALUE_BYTES:
                raise _Trap("value too large")
            use(sz)
            push(vs)
        elif op in ("jump", "jumpi"):
            tgt = ins[1] if len(ins) > 1 else -1
            if op == "jumpi" and not pop():
                continue
            if not isinstance(tgt, int) or not 0 <= tgt < len(code):
                raise _Trap(f"bad jump target {tgt}")
            pc = tgt
        elif op == "input":
            push(input_tuple)
        elif op == "caller":
            push(caller)
        elif op == "sget":
            v = sget(pop())
            # loaded bytes cost gas like constructed bytes do, so a
            # cheap loop can't stream unbounded state through the VM
            use(G_SGET + _size_of(v))
            push(v)
        elif op == "sput":
            v, k = pop(), pop()
            if _size_of(v) > MAX_VALUE_BYTES:
                raise _Trap("value too large")
            use(G_SPUT + _size_of(v) + _size_of(k))
            sput(k, v)
        elif op == "emit":
            v = pop()
            use(G_EMIT + _size_of(v))
            emit(v)
        elif op == "xcall":
            # cross-contract call (pallet-contracts call-chain role):
            # pops gas, args(tuple), method(str), address(bytes);
            # pushes (1, result) on success, (0, reason) on failure —
            # an inner revert/trap NEVER traps the caller
            use(G_XCALL)
            g, ar, m, a = pop(), pop(), pop(), pop()
            if not (isinstance(g, int) and not isinstance(g, bool)
                    and g > 0 and isinstance(ar, tuple)
                    and isinstance(m, str) and isinstance(a, bytes)):
                raise _Trap("xcall: (addr, method, args, gas) required")
            # 63/64 forwarding; the forwarded budget is consumed in
            # full (no refund) — a strict upper bound, kept simple
            fwd = min(g, gas - gas // 64)
            use(fwd)
            if xcall is None:
                push((0, "no host"))
            else:
                ok, val = xcall(a, m, ar, fwd)
                push((1 if ok else 0, val))
        elif op == "return":
            return pop()
        elif op == "revert":
            raise _Revert(pop())
        else:
            raise _Trap(f"unknown op {op!r}")
    return None


def _storage_key(k) -> bytes:
    from .. import codec

    return hashlib.sha256(codec.encode(k)).digest()


def code_hash(code: tuple) -> bytes:
    """THE canonical serialized bytecode identity: sha256 of the codec
    encoding of the instruction tuple. The codec encoding is the wire
    format third-party toolchains target (deterministic, versioned,
    schema-checked on decode), so a code hash names exactly one
    byte-identical program on every replica."""
    from .. import codec

    return hashlib.sha256(b"cvm-code:" + codec.encode(code)).digest()


class Contracts:
    """The pallet boundary: upload/deploy/instantiate/call/query over
    the VM, matching evm.py's surface shape + pallet-contracts'
    code-hash model (runtime/src/lib.rs:1191-1207: upload_code,
    instantiate_with_code, instantiate — code stored ONCE per hash,
    contracts point at it)."""

    def __init__(self, state: State):
        self.state = state

    def _check_gas(self, gas_limit) -> int:
        if not isinstance(gas_limit, int) or gas_limit <= 0:
            raise DispatchError("contracts.InvalidGas")
        return min(gas_limit, GAS_CAP)

    @staticmethod
    def _check_code(code) -> None:
        if not (isinstance(code, tuple) and 0 < len(code)
                <= MAX_CODE_INSTRS
                and all(isinstance(i, tuple) and i
                        and isinstance(i[0], str) for i in code)):
            raise DispatchError("contracts.InvalidCode")

    # -- code store (pallet-contracts upload_code / CodeStorage) -------------
    def upload_code(self, who: str, code: tuple) -> bytes:
        """Store a program under its canonical hash (dedup: a second
        upload of identical code is a no-op returning the same hash).
        Returns the code hash for later instantiate()."""
        self._check_code(code)
        h = code_hash(code)
        if not self.state.contains(PALLET, "code_store", h):
            self.state.put(PALLET, "code_store", h, code)
            self.state.deposit_event(PALLET, "CodeStored", who=who,
                                     code_hash=h, instrs=len(code))
        return h

    def code_by_hash(self, h: bytes):
        return self.state.get(PALLET, "code_store", h)

    def _new_address(self, who: str) -> bytes:
        nonce = self.state.get(PALLET, "nonce", who, default=0)
        self.state.put(PALLET, "nonce", who, nonce + 1)
        return hashlib.sha256(b"cvm-create:" + who.encode()
                              + nonce.to_bytes(8, "little")).digest()[:20]

    def deploy(self, who: str, code: tuple) -> bytes:
        """instantiate_with_code: upload (deduped) + instantiate in
        one dispatch; constructors are an explicit follow-up
        ``call(addr, "init", ...)`` by convention (keeps deploy cost
        independent of program behavior, so no gas parameter).
        Returns the address."""
        h = self.upload_code(who, code)
        return self._instantiate(who, h, len(code))

    def instantiate(self, who: str, h: bytes) -> bytes:
        """Deploy-by-hash against previously uploaded code — the wire
        carries 32 bytes instead of the whole program."""
        code = self.code_by_hash(h) if isinstance(h, bytes) else None
        if code is None:
            raise DispatchError("contracts.CodeNotFound")
        return self._instantiate(who, h, len(code))

    def _instantiate(self, who: str, h: bytes, instrs: int) -> bytes:
        addr = self._new_address(who)
        self.state.put(PALLET, "code", addr, h)   # hash, not the body
        self.state.deposit_event(PALLET, "Deployed", who=who,
                                 address=addr, code_hash=h,
                                 instrs=instrs)
        return addr

    def code_at(self, address: bytes):
        ref = self.state.get(PALLET, "code", address)
        if isinstance(ref, bytes):                # hash indirection
            return self.code_by_hash(ref)
        return ref                                # pre-v2 inline body

    def call(self, who: str, address: bytes, method: str,
             args: tuple = (), gas_limit: int = DEFAULT_GAS):
        """Execute ``method(*args)``; storage writes and events commit
        with the surrounding dispatch transaction."""
        if not isinstance(method, str) or not isinstance(args, tuple):
            raise DispatchError("contracts.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        out = self._run(who, address, (method, *args), gas_limit)
        self.state.deposit_event(PALLET, "Called", who=who,
                                 address=address, method=method)
        return out

    def query(self, address: bytes, method: str, args: tuple = (),
              caller: str = "", gas_limit: int = DEFAULT_GAS):
        """Read-only call: storage reads from chain state, writes to a
        throwaway overlay, no events."""
        if not isinstance(method, str) or not isinstance(args, tuple):
            raise DispatchError("contracts.InvalidCall")
        gas_limit = self._check_gas(gas_limit)
        # the root session is simply never committed: every frame's
        # writes and events — inner xcalls included — are thrown away
        return self._run(caller, address, (method, *args), gas_limit,
                         commit=False)

    MAX_XCALL_DEPTH = 8

    class _Session(ChainedOverlay):
        """Frame-chained contract storage (keys are
        (address, hashed-slot)) PLUS pending events — events follow
        the same discipline as writes, so a reverted subtree's events
        vanish with it. See chain/overlay.py (shared with the EVM)."""

        def __init__(self, contracts: "Contracts", parent=None):
            st = contracts.state
            super().__init__(
                root_get=lambda ak: st.get(PALLET, "storage", ak[0],
                                           ak[1]),
                root_put=lambda ak, v: st.put(PALLET, "storage", ak[0],
                                              ak[1], v),
                parent=parent)
            self.c = contracts
            self.events: list[tuple[bytes, object]] = []

        def hooks(self, a: bytes):
            return (lambda k: self.get((a, _storage_key(k))),
                    lambda k, v: self.put((a, _storage_key(k)), v),
                    lambda v: self.events.append((a, v)))

        def commit(self) -> None:
            super().commit()
            if self.parent is not None:
                self.parent.events.extend(self.events)
            else:
                for a, v in self.events:
                    self.c.state.deposit_event(PALLET, "ContractEvent",
                                               address=a, data=v)

    # -- engine bridge -------------------------------------------------------
    def _run(self, who: str, address: bytes, input_tuple: tuple,
             gas_limit: int, session: "Contracts._Session | None" = None,
             depth: int = 0, commit: bool = True):
        """One exec bridge for call, query, and recursive xcall frames
        (see _Session for the commit discipline). ``commit=False``
        (query) discards the root session."""
        code = self.code_at(address)
        if code is None:
            raise DispatchError("contracts.NoContract")
        if session is None:
            session = Contracts._Session(self)
        sget, sput, emit = session.hooks(address)

        def xcall(a: bytes, method: str, args: tuple, fwd: int):
            if depth >= self.MAX_XCALL_DEPTH:
                return 0, "call depth exceeded"
            if self.code_at(a) is None:
                return 0, "no contract"
            child = Contracts._Session(self, parent=session)
            try:
                out = self._run(
                    # the CALLER of the inner frame is this contract
                    "contract:" + address.hex(), a, (method, *args),
                    fwd, session=child, depth=depth + 1, commit=False)
            except DispatchError as e:
                return 0, str(e)
            child.commit()             # into the PARENT frame's session
            return 1, out

        try:
            out = _exec(code, input_tuple=input_tuple, caller=who,
                        gas_limit=gas_limit, sget=sget, sput=sput,
                        emit=emit, xcall=xcall)
        except _Revert as e:
            raise DispatchError("contracts.Reverted", repr(e.value)) from e
        except _Trap as e:
            raise DispatchError("contracts.Trapped", str(e)) from e
        if commit and session.parent is None:
            session.commit()
        return out
