"""Offences: consensus-fault reporting with on-chain evidence checks.

The reference composes pallet_offences + pallet_grandpa's equivocation
reporting + pallet_im_online liveness offences
(/root/reference/runtime/src/lib.rs:507-540): misbehaviour observed by
the consensus layer is submitted back on chain as a report with
self-contained cryptographic evidence, verified in the runtime, then
punished through staking slashing.

Here the same shape, TPU-framework-native: the finality gadget's
signed votes (cess_tpu/node/finality.py uses the Vote type below) ARE
the evidence — two votes by one voter for different blocks in the same
round prove equivocation to any replica, no trust in the reporter.

Slash fractions mirror the reference's order of magnitude (GRANDPA
equivocation slashes a stake proportion and chills; im-online offences
are mild): equivocation = 10% of bond + chill; liveness (unresponsive
in era, reported by the era rotation) = 1% of bond.
"""
from __future__ import annotations

import dataclasses

from .. import codec
from .state import DispatchError, State

PALLET = "offences"

VOTE_SIGNING_CONTEXT = b"cess-tpu/finality-vote-v1:"

EQUIVOCATION_SLASH_PERMILL = 100   # 10% of bond
LIVENESS_SLASH_PERMILL = 10        # 1% of bond


@codec.register
@dataclasses.dataclass(frozen=True)
class Vote:
    """One finality vote: ``voter`` commits to ``target`` at ``round``.

    Signed with the voter's SESSION key (the on-chain
    ("system", "session_key") registry — the same keys that sign audit
    proposals), domain-separated by genesis so votes cannot replay
    across chains."""

    voter: str
    round: int
    target_hash: bytes
    target_number: int
    signature: bytes

    def signing_payload(self, genesis: bytes) -> bytes:
        return VOTE_SIGNING_CONTEXT + codec.encode(
            (genesis, self.voter, self.round, self.target_hash,
             self.target_number))


def sign_vote(key, genesis: bytes, voter: str, round_: int,
              target_hash: bytes, target_number: int) -> Vote:
    v = Vote(voter=voter, round=round_, target_hash=target_hash,
             target_number=target_number, signature=b"")
    return dataclasses.replace(
        v, signature=key.sign(v.signing_payload(genesis)))


class Offences:
    def __init__(self, state: State, staking, genesis_fn):
        self.state = state
        self.staking = staking
        self._genesis = genesis_fn   # late-bound: genesis set post-init

    def _verify_vote(self, vote: Vote) -> None:
        from ..crypto import ed25519

        if not isinstance(vote, Vote):
            raise DispatchError("offences.BadEvidence", "not a Vote")
        ok = (isinstance(vote.voter, str)
              and isinstance(vote.round, int)
              and isinstance(vote.target_hash, bytes)
              and isinstance(vote.target_number, int)
              and isinstance(vote.signature, bytes))
        if not ok:
            raise DispatchError("offences.BadEvidence", "malformed vote")
        pub = self.state.get("system", "session_key", vote.voter)
        if pub is None:
            raise DispatchError("offences.UnknownVoter", vote.voter)
        if not ed25519.verify(pub, vote.signing_payload(self._genesis()),
                              vote.signature):
            raise DispatchError("offences.BadVoteSignature", vote.voter)

    def report_equivocation(self, reporter: str, vote_a: Vote,
                            vote_b: Vote) -> None:
        """Anyone may report; the report carries both conflicting
        votes and is verified entirely on chain (the reference's
        report_equivocation_unsigned path)."""
        self._verify_vote(vote_a)
        self._verify_vote(vote_b)
        if vote_a.voter != vote_b.voter or vote_a.round != vote_b.round:
            raise DispatchError("offences.NotEquivocation",
                                "different voter or round")
        if vote_a.target_hash == vote_b.target_hash:
            raise DispatchError("offences.NotEquivocation", "same target")
        offender = vote_a.voter
        if self.state.contains(PALLET, "reported", offender, vote_a.round):
            raise DispatchError("offences.AlreadyReported", offender)
        self.state.put(PALLET, "reported", offender, vote_a.round, reporter)
        slashed = self.staking.slash_fraction(
            offender, EQUIVOCATION_SLASH_PERMILL)
        self.staking.chill(offender)
        self.state.deposit_event(
            PALLET, "EquivocationReported", offender=offender,
            round=vote_a.round, reporter=reporter, slashed=slashed)

    def report_liveness_fault(self, offender: str, era: int) -> None:
        """Internal hook (era rotation / im-online analog): an
        authority that produced no heartbeat all era."""
        if self.state.contains(PALLET, "reported", offender, ("era", era)):
            return
        self.state.put(PALLET, "reported", offender, ("era", era), "system")
        slashed = self.staking.slash_fraction(
            offender, LIVENESS_SLASH_PERMILL, era=era)
        self.state.deposit_event(PALLET, "LivenessFault", offender=offender,
                                 era=era, slashed=slashed)
