"""Multi-phase validator election (ElectionProviderMultiPhase analog).

The reference elects validators through ElectionProviderMultiPhase:
during a signed submission window, anyone may submit a pre-computed
election solution with a claimed score and a deposit; solutions are
feasibility-checked on admission, the best claim wins, false claims
are slashed, and an on-chain solver is the fallback when the phase
closes empty (/root/reference/runtime/src/lib.rs:613,834-863). The
solver objective here is the credit-weighted VrfSolver ranking
(cess_tpu/node/consensus.py:elect_validators; runtime lib.rs:764-786).

Flow per era:
- the SIGNED PHASE is the ``signed_phase_blocks`` window before the
  unsigned phase; ``submit_solution(validators, claimed_score)``
  reserves a deposit, cheap-checks feasibility (distinct bonded
  validators over the stake floor, within max size), and keeps only
  the highest claimed score;
- the UNSIGNED PHASE is the last ``unsigned_phase_blocks`` of the era
  (the reference's unsigned submission window, lib.rs:834-863):
  validator OCWs mine a solution locally and submit it FEELESS and
  deposit-free via ``submit_unsigned`` — evidence-carrying, like
  audit.save_challenge_info: the payload is signed by the submitting
  validator's SESSION key and fully verified on admission (phase,
  eligibility, and the claimed score recomputed exactly — cheap at
  this scale, where the reference defers to validate_unsigned), so a
  forged or mis-scored submission can never occupy the queue;
- at the era boundary ``resolve`` (called INSIDE block execution by
  the runtime's era hook, so deposit moves and the queue sweep are
  covered by the block's undo log — a reorg rewinds them) re-scores
  the stored solution against CURRENT stakes/credits: an OVERCLAIM
  (actual < claimed on a feasible solution) is provably false and
  slashes the whole deposit to the treasury; a solution that merely
  went infeasible through third-party stake churn is refunded and
  discarded (honest submission must not be griefable); an honest
  solution scoring at least the on-chain solver's is adopted and
  refunded; otherwise the solver result stands (fallback). The node's
  session-rotation hook only READS the stored result.

Scoring: score(set) = sum over members of (credit * 2^40 + stake in
DOLLARS) — an additive objective whose optimum is exactly the
top-max_validators of the solver's (credit, stake) ranking, so the
solver is simultaneously the fallback and the honest best response.
"""
from __future__ import annotations

from .. import constants
from .state import DispatchError, State

PALLET = "election"
TREASURY_ACCOUNT = "treasury"

SIGNED_PHASE_BLOCKS = 10          # submission window before the unsigned one
UNSIGNED_PHASE_BLOCKS = 5         # OCW window ending each era
SOLUTION_DEPOSIT = 100 * constants.DOLLARS
CREDIT_WEIGHT = 1 << 40           # credit dominates stake in the score
UNSIGNED_SIGNING_CONTEXT = b"cess-election-unsigned-v1:"


def score_of(validators, stakes: dict[str, int],
             credits: dict[str, int]) -> int:
    return sum(credits.get(v, 0) * CREDIT_WEIGHT
               + stakes.get(v, 0) // constants.DOLLARS
               for v in validators)


class Election:
    def __init__(self, state: State, balances, staking, credit,
                 era_blocks: int,
                 signed_phase_blocks: int = SIGNED_PHASE_BLOCKS,
                 unsigned_phase_blocks: int = UNSIGNED_PHASE_BLOCKS,
                 max_validators: int = 0):
        self.state = state
        self.balances = balances
        self.staking = staking
        self.credit = credit
        self.era_blocks = era_blocks
        self.unsigned_phase_blocks = min(unsigned_phase_blocks,
                                         era_blocks - 1)
        self.signed_phase_blocks = min(
            signed_phase_blocks,
            era_blocks - 1 - self.unsigned_phase_blocks)
        self.max_validators = max_validators   # 0 -> caller supplies

    # -- phase ----------------------------------------------------------------
    def in_signed_phase(self) -> bool:
        pos = self.state.block % self.era_blocks
        start = self.era_blocks - self.signed_phase_blocks \
            - self.unsigned_phase_blocks
        return start <= pos < self.era_blocks - self.unsigned_phase_blocks

    def in_unsigned_phase(self) -> bool:
        pos = self.state.block % self.era_blocks
        return pos >= self.era_blocks - self.unsigned_phase_blocks

    # election snapshot bound: how many candidates (heaviest-stake
    # first, via the staking bags index) get scored per era — the
    # VoterList role (ref runtime/src/lib.rs:1512): snapshots stop
    # scanning the whole candidate set
    SNAPSHOT_FACTOR = 4
    SNAPSHOT_MIN = 64

    def _candidates(self) -> dict[str, int]:
        if self.max_validators:
            limit = max(self.max_validators * self.SNAPSHOT_FACTOR,
                        self.SNAPSHOT_MIN)
            members = self.staking.top_stakers(limit)
        else:
            members = self.staking.validators()
        return {v: self.staking.bonded(v) for v in members}

    # -- dispatchable ---------------------------------------------------------
    def submit_solution(self, who: str, validators: tuple,
                        claimed_score: int) -> None:
        """Signed-phase solution submission (reference's signed
        submissions, lib.rs:834-863). Cheap feasibility on admission;
        the full re-score happens at the era boundary where a false
        claim costs the deposit."""
        if not self.in_signed_phase():
            raise DispatchError("election.NotInSignedPhase")
        if not (isinstance(validators, tuple) and validators
                and all(isinstance(v, str) for v in validators)
                and len(set(validators)) == len(validators)):
            raise DispatchError("election.MalformedSolution")
        if self.max_validators and len(validators) > self.max_validators:
            raise DispatchError("election.SolutionTooLarge")
        if not isinstance(claimed_score, int) or claimed_score < 0:
            raise DispatchError("election.MalformedSolution")
        stakes = self._candidates()
        for v in validators:
            if stakes.get(v, 0) < constants.MIN_ELECTABLE_STAKE:
                raise DispatchError("election.IneligibleCandidate", v)
        best = self.state.get(PALLET, "best", default=None)
        if best is not None and best[2] >= claimed_score:
            raise DispatchError("election.WeakerThanQueued")
        self.balances.reserve(who, SOLUTION_DEPOSIT)
        if best is not None:
            # replaced submitter gets their deposit back immediately
            self.balances.unreserve(best[0], SOLUTION_DEPOSIT)
        self.state.put(PALLET, "best",
                       (who, tuple(validators), claimed_score))
        self.state.deposit_event(PALLET, "SolutionQueued", who=who,
                                 size=len(validators),
                                 claimed_score=claimed_score)

    def unsigned_payload(self, validators: tuple, claimed_score: int,
                         signer: str) -> bytes:
        """What the OCW's SESSION key signs: genesis-domain-separated
        so submissions cannot replay across chains, era-stamped so
        they cannot replay across eras."""
        from .. import codec

        genesis = self.state.get("system", "genesis", default=b"\0" * 32)
        era = self.state.block // self.era_blocks
        return UNSIGNED_SIGNING_CONTEXT + codec.encode(
            (genesis, era, signer, tuple(validators), claimed_score))

    def submit_unsigned(self, who: str, validators: tuple,
                        claimed_score: int, signature: bytes) -> None:
        """Unsigned-phase OCW submission (reference's mined unsigned
        solutions + validate_unsigned, lib.rs:834-863): feeless and
        deposit-free, so admission is FULL verification — registered
        validator, session signature over the era-stamped payload, and
        the claimed score recomputed exactly against current state."""
        from ..crypto import ed25519

        if not self.in_unsigned_phase():
            raise DispatchError("election.NotInUnsignedPhase")
        if not (isinstance(validators, tuple) and validators
                and all(isinstance(v, str) for v in validators)
                and len(set(validators)) == len(validators)
                and isinstance(claimed_score, int)
                and isinstance(signature, bytes)):
            raise DispatchError("election.MalformedSolution")
        if self.max_validators and len(validators) > self.max_validators:
            raise DispatchError("election.SolutionTooLarge")
        if who not in self.staking.validators():
            raise DispatchError("election.NotValidator", who)
        session_pub = self.state.get("system", "session_key", who)
        if session_pub is None or not ed25519.verify(
                session_pub,
                self.unsigned_payload(validators, claimed_score, who),
                signature):
            raise DispatchError("election.BadSessionSignature", who)
        stakes = self._candidates()
        for v in validators:
            if stakes.get(v, 0) < constants.MIN_ELECTABLE_STAKE:
                raise DispatchError("election.IneligibleCandidate", v)
        actual = score_of(validators, stakes, self.credit.credits())
        if claimed_score != actual:
            # a mis-scored unsigned solution is rejected outright —
            # with no deposit at stake there is nothing to slash later
            raise DispatchError("election.FalseScore",
                                f"{claimed_score} != {actual}")
        queued = self.state.get(PALLET, "best_unsigned", default=None)
        if queued is not None and queued[2] >= actual:
            raise DispatchError("election.WeakerThanQueued")
        self.state.put(PALLET, "best_unsigned",
                       (who, tuple(validators), actual))
        self.state.deposit_event(PALLET, "UnsignedQueued", who=who,
                                 size=len(validators), score=actual)

    # -- era boundary ---------------------------------------------------------
    def resolve(self, max_validators: int) -> tuple[str, ...]:
        """Resolve the election and store the result in state:
        verified queued solution if it beats the on-chain solver, else
        the solver result (fallback). MUST run inside block execution
        (the runtime era hook) — it moves deposits and sweeps the
        queue, which the block's undo log has to cover."""
        from ..node.consensus import elect_validators

        stakes = self._candidates()
        credits = self.credit.credits()
        fallback = elect_validators(stakes, credits, max_validators)
        fb_score = score_of(fallback, stakes, credits)

        def boundary_check(validators):
            """(feasible, actual) under the BOUNDARY's stakes —
            admission-time checks guard the queue, this guards the
            result against stake churn since admission."""
            feasible = (len(validators) <= max_validators
                        and all(stakes.get(v, 0)
                                >= constants.MIN_ELECTABLE_STAKE
                                for v in validators))
            return feasible, (score_of(validators, stakes, credits)
                              if feasible else -1)

        # SIGNED queue: deposit settlement happens regardless of who
        # wins (overclaim slash / honest refund semantics unchanged)
        signed_entry = None            # (who, validators, actual)
        best = self.state.get(PALLET, "best", default=None)
        if best is not None:
            self.state.delete(PALLET, "best")
            who, validators, claimed = best
            feasible, actual = boundary_check(validators)
            if feasible and actual < claimed:
                # OVERCLAIM: provably false — the whole deposit goes to
                # the treasury (the reference's defensive slash for bad
                # signed solutions). An underclaim (stake grew since
                # submission) and infeasibility through third-party
                # churn are NOT the submitter's fault: refund.
                self.balances.slash_reserved(who, SOLUTION_DEPOSIT,
                                             TREASURY_ACCOUNT)
                self.state.deposit_event(PALLET, "SolutionSlashed",
                                         who=who, claimed=claimed,
                                         actual=actual)
            else:
                self.balances.unreserve(who, SOLUTION_DEPOSIT)
                if feasible:
                    signed_entry = (who, tuple(validators), actual)

        # UNSIGNED queue (the OCW-mined solution, lib.rs:834-863):
        # fully verified at admission; boundary re-check only
        unsigned_entry = None
        unsigned = self.state.get(PALLET, "best_unsigned", default=None)
        if unsigned is not None:
            self.state.delete(PALLET, "best_unsigned")
            u_who, u_validators, _ = unsigned
            feasible, u_actual = boundary_check(u_validators)
            if feasible:
                unsigned_entry = (u_who, tuple(u_validators), u_actual)

        # pick ONE winner: the highest-scoring queued solution at or
        # above the fallback's score (a queued solution beats the
        # fallback on ties — the point of mining it); the unsigned
        # entry wins signed-vs-unsigned ties (it was fully verified)
        winner, win_event = fallback, None
        best_score = fb_score - 1
        if signed_entry is not None and signed_entry[2] > best_score:
            winner = signed_entry[1]
            win_event = ("SolutionElected", signed_entry[0],
                         signed_entry[2])
            best_score = signed_entry[2]
        if unsigned_entry is not None and unsigned_entry[2] >= fb_score \
                and unsigned_entry[2] >= best_score:
            winner = unsigned_entry[1]
            win_event = ("UnsignedElected", unsigned_entry[0],
                         unsigned_entry[2])
        if win_event is not None:
            name, who, sc = win_event
            self.state.deposit_event(PALLET, name, who=who, score=sc)
        elif fallback:
            self.state.deposit_event(PALLET, "FallbackElected",
                                     size=len(fallback))
        self.state.put(PALLET, "result", winner)
        return winner

    def result(self) -> tuple[str, ...]:
        """The last resolved authority set (what the node's session
        rotation reads; empty before the first era boundary)."""
        return self.state.get(PALLET, "result", default=())
