"""Multi-phase validator election (ElectionProviderMultiPhase analog).

The reference elects validators through ElectionProviderMultiPhase:
during a signed submission window, anyone may submit a pre-computed
election solution with a claimed score and a deposit; solutions are
feasibility-checked on admission, the best claim wins, false claims
are slashed, and an on-chain solver is the fallback when the phase
closes empty (/root/reference/runtime/src/lib.rs:613,834-863). The
solver objective here is the credit-weighted VrfSolver ranking
(cess_tpu/node/consensus.py:elect_validators; runtime lib.rs:764-786).

Flow per era:
- the SIGNED PHASE is the last ``signed_phase_blocks`` of the era;
  ``submit_solution(validators, claimed_score)`` reserves a deposit,
  cheap-checks feasibility (distinct bonded validators over the stake
  floor, within max size), and keeps only the highest claimed score;
- at the era boundary ``resolve`` (called INSIDE block execution by
  the runtime's era hook, so deposit moves and the queue sweep are
  covered by the block's undo log — a reorg rewinds them) re-scores
  the stored solution against CURRENT stakes/credits: an OVERCLAIM
  (actual < claimed on a feasible solution) is provably false and
  slashes the whole deposit to the treasury; a solution that merely
  went infeasible through third-party stake churn is refunded and
  discarded (honest submission must not be griefable); an honest
  solution scoring at least the on-chain solver's is adopted and
  refunded; otherwise the solver result stands (fallback). The node's
  session-rotation hook only READS the stored result.

Scoring: score(set) = sum over members of (credit * 2^40 + stake in
DOLLARS) — an additive objective whose optimum is exactly the
top-max_validators of the solver's (credit, stake) ranking, so the
solver is simultaneously the fallback and the honest best response.
"""
from __future__ import annotations

from .. import constants
from .state import DispatchError, State

PALLET = "election"
TREASURY_ACCOUNT = "treasury"

SIGNED_PHASE_BLOCKS = 10          # submission window before each era end
SOLUTION_DEPOSIT = 100 * constants.DOLLARS
CREDIT_WEIGHT = 1 << 40           # credit dominates stake in the score


def score_of(validators, stakes: dict[str, int],
             credits: dict[str, int]) -> int:
    return sum(credits.get(v, 0) * CREDIT_WEIGHT
               + stakes.get(v, 0) // constants.DOLLARS
               for v in validators)


class Election:
    def __init__(self, state: State, balances, staking, credit,
                 era_blocks: int,
                 signed_phase_blocks: int = SIGNED_PHASE_BLOCKS,
                 max_validators: int = 0):
        self.state = state
        self.balances = balances
        self.staking = staking
        self.credit = credit
        self.era_blocks = era_blocks
        self.signed_phase_blocks = min(signed_phase_blocks, era_blocks - 1)
        self.max_validators = max_validators   # 0 -> caller supplies

    # -- phase ----------------------------------------------------------------
    def in_signed_phase(self) -> bool:
        pos = self.state.block % self.era_blocks
        return pos >= self.era_blocks - self.signed_phase_blocks

    def _candidates(self) -> dict[str, int]:
        return {v: self.staking.bonded(v)
                for v in self.staking.validators()}

    # -- dispatchable ---------------------------------------------------------
    def submit_solution(self, who: str, validators: tuple,
                        claimed_score: int) -> None:
        """Signed-phase solution submission (reference's signed
        submissions, lib.rs:834-863). Cheap feasibility on admission;
        the full re-score happens at the era boundary where a false
        claim costs the deposit."""
        if not self.in_signed_phase():
            raise DispatchError("election.NotInSignedPhase")
        if not (isinstance(validators, tuple) and validators
                and all(isinstance(v, str) for v in validators)
                and len(set(validators)) == len(validators)):
            raise DispatchError("election.MalformedSolution")
        if self.max_validators and len(validators) > self.max_validators:
            raise DispatchError("election.SolutionTooLarge")
        if not isinstance(claimed_score, int) or claimed_score < 0:
            raise DispatchError("election.MalformedSolution")
        stakes = self._candidates()
        for v in validators:
            if stakes.get(v, 0) < constants.MIN_ELECTABLE_STAKE:
                raise DispatchError("election.IneligibleCandidate", v)
        best = self.state.get(PALLET, "best", default=None)
        if best is not None and best[2] >= claimed_score:
            raise DispatchError("election.WeakerThanQueued")
        self.balances.reserve(who, SOLUTION_DEPOSIT)
        if best is not None:
            # replaced submitter gets their deposit back immediately
            self.balances.unreserve(best[0], SOLUTION_DEPOSIT)
        self.state.put(PALLET, "best",
                       (who, tuple(validators), claimed_score))
        self.state.deposit_event(PALLET, "SolutionQueued", who=who,
                                 size=len(validators),
                                 claimed_score=claimed_score)

    # -- era boundary ---------------------------------------------------------
    def resolve(self, max_validators: int) -> tuple[str, ...]:
        """Resolve the election and store the result in state:
        verified queued solution if it beats the on-chain solver, else
        the solver result (fallback). MUST run inside block execution
        (the runtime era hook) — it moves deposits and sweeps the
        queue, which the block's undo log has to cover."""
        from ..node.consensus import elect_validators

        stakes = self._candidates()
        credits = self.credit.credits()
        fallback = elect_validators(stakes, credits, max_validators)
        fb_score = score_of(fallback, stakes, credits)
        best = self.state.get(PALLET, "best", default=None)
        winner = fallback
        if best is not None:
            self.state.delete(PALLET, "best")
            who, validators, claimed = best
            feasible = (len(validators) <= max_validators
                        and all(stakes.get(v, 0)
                                >= constants.MIN_ELECTABLE_STAKE
                                for v in validators))
            actual = score_of(validators, stakes, credits) \
                if feasible else -1
            if feasible and actual < claimed:
                # OVERCLAIM: provably false — the whole deposit goes to
                # the treasury (the reference's defensive slash for bad
                # signed solutions). An underclaim (stake grew since
                # submission) and infeasibility through third-party
                # churn are NOT the submitter's fault: refund.
                self.balances.slash_reserved(who, SOLUTION_DEPOSIT,
                                             TREASURY_ACCOUNT)
                self.state.deposit_event(PALLET, "SolutionSlashed",
                                         who=who, claimed=claimed,
                                         actual=actual)
            else:
                self.balances.unreserve(who, SOLUTION_DEPOSIT)
                if feasible and actual >= fb_score:
                    winner = tuple(validators)
                    self.state.deposit_event(PALLET, "SolutionElected",
                                             who=who, score=actual)
        if winner is fallback and fallback:
            self.state.deposit_event(PALLET, "FallbackElected",
                                     size=len(fallback))
        self.state.put(PALLET, "result", winner)
        return winner

    def result(self) -> tuple[str, ...]:
        """The last resolved authority set (what the node's session
        rotation reads; empty before the first era boundary)."""
        return self.state.get(PALLET, "result", default=())
