"""Named-task block scheduler (reference: pallet-scheduler usage).

file-bank schedules deal timeouts / calculate_end / miner-exit tasks as
named scheduled calls (c-pallets/file-bank/src/lib.rs:102-104,
functions.rs:154-170). Tasks are stored as (pallet, method, args)
descriptors and dispatched by the runtime at their block, root-origin,
best-effort (a failing task is dropped with an event, like FRAME's
scheduler).
"""
from __future__ import annotations

from .state import State

PALLET = "scheduler"


class Scheduler:
    def __init__(self, state: State):
        self.state = state

    def schedule_named(self, name: str, at_block: int, pallet: str,
                       method: str, *args) -> None:
        """Overwrites any pending task with the same name."""
        self.cancel_named(name)
        agenda = self.state.get(PALLET, "agenda", at_block, default=())
        self.state.put(PALLET, "agenda", at_block,
                       agenda + ((name, pallet, method, args),))
        self.state.put(PALLET, "lookup", name, at_block)

    def cancel_named(self, name: str) -> None:
        at = self.state.get(PALLET, "lookup", name)
        if at is None:
            return
        agenda = self.state.get(PALLET, "agenda", at, default=())
        agenda = tuple(t for t in agenda if t[0] != name)
        if agenda:
            self.state.put(PALLET, "agenda", at, agenda)
        else:
            self.state.delete(PALLET, "agenda", at)
        self.state.delete(PALLET, "lookup", name)

    def take_due(self) -> list[tuple[str, str, str, tuple]]:
        """Pop this block's agenda (runtime dispatches each entry)."""
        now = self.state.block
        agenda = self.state.get(PALLET, "agenda", now, default=())
        if agenda:
            self.state.delete(PALLET, "agenda", now)
            for name, *_ in agenda:
                self.state.delete(PALLET, "lookup", name)
        return list(agenda)
