"""Lock-discipline analyzers for the multithreaded node (serve/, node/).

The serve engine (serve/engine.py) is a lock-and-condition-variable
core; the gossip/RPC/DHT layers (node/net.py, node/rpc.py,
node/dht.py) share state across accept/dial/author/handler threads.
The bug classes here — a field mutated outside the lock that guards it
everywhere else, a blocking call made while holding a lock every other
thread needs, two locks taken in opposite orders on different paths —
produce rare, timing-dependent corruption no unit test reliably
reproduces, but all three are mechanically detectable from the AST.

Rules:
- lock-unguarded-write : an attribute written under ``with self.<lock>``
                         in one method is written WITHOUT the lock in
                         another (``__init__`` is pre-publication and
                         exempt)
- lock-blocking-call   : time.sleep / Future.result / Thread.join /
                         socket recv-accept / block_until_ready while
                         a lock is held (``cond.wait`` is exempt — it
                         releases the lock)
- lock-order-cycle     : lock acquisition order forms a cycle across
                         methods/classes (syntactic nesting plus
                         one level of self.method / typed-attribute
                         call resolution)
"""
from __future__ import annotations

import ast
import dataclasses

from .core import Finding, ParsedModule, Rule, dotted, path_parts, register

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_BLOCKING_METHODS = {"result", "join", "recv", "recv_into", "accept",
                     "block_until_ready", "sendall"}
_BLOCKING_CALLS = {"time.sleep"}


def _lock_factory(value: ast.AST) -> ast.Call | None:
    """The threading.Lock()/RLock()/Condition() call inside an
    assignment value, if any (handles ``x if y else Lock()``)."""
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            fq = dotted(n.func) or ""
            if fq.rsplit(".", 1)[-1] in _LOCK_FACTORIES \
                    and ("threading" in fq or "." not in fq):
                return n
    return None


@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    # canonical locks held at the write; None means "caller holds the
    # lock" (the *_locked method convention) — trusted, not reported
    held: frozenset | None
    node: ast.AST


@dataclasses.dataclass
class _Blocking:
    call: str
    lock: str
    method: str
    node: ast.AST


@dataclasses.dataclass
class _ClassLocks:
    """Everything the walker learned about one class."""
    name: str
    mod: ParsedModule
    lock_attrs: dict[str, str]          # attr -> canonical lock attr
    rlocks: set[str]                    # reentrant (self-nesting ok)
    conditions: set[str]                # attrs that are Condition objects
    writes: list[_Write]
    blocking: list[_Blocking]
    # lock-order evidence: (outer, inner) -> example node
    nest_edges: dict[tuple[str, str], ast.AST]
    # re-acquisition of a held non-reentrant lock: (attr, node)
    self_nest: list[tuple[str, ast.AST]]
    held_calls: list[tuple[str, str, ast.AST]]  # (held lock, call fq, node)
    attr_types: dict[str, str]          # self.X = ClassName(...) in __init__
    method_locks: dict[str, set[str]]   # method -> locks acquired directly


def _self_attr_target(node: ast.AST) -> str | None:
    """The X of a ``self.X = ...`` / ``self.X[...] = ...`` /
    ``del self.X[...]`` target."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _MethodWalker(ast.NodeVisitor):
    """Walk one method body tracking which of the class's locks are
    held (syntactic ``with self.<lock>`` scopes)."""

    def __init__(self, cls: _ClassLocks, method: str):
        self.cls = cls
        self.method = method
        self.stack: list[str] = []      # canonical lock names held
        # convention: a ``*_locked`` method is only called with the
        # lock already held — its writes are guarded by the caller
        self.assume_locked = method.endswith("_locked")

    # -- lock scopes -----------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            fq = dotted(item.context_expr)
            if fq and fq.startswith("self."):
                attr = fq[len("self."):]
                if attr in self.cls.lock_attrs:
                    lock = self.cls.lock_attrs[attr]
                    if lock in self.stack:
                        # re-acquiring a held lock: fine for RLock,
                        # guaranteed self-deadlock otherwise
                        if lock not in self.cls.rlocks:
                            self.cls.self_nest.append((attr, node))
                    elif self.stack:
                        self.cls.nest_edges.setdefault(
                            (self.stack[-1], lock), node)
                    self.cls.method_locks.setdefault(
                        self.method, set()).add(lock)
                    self.stack.append(lock)
                    acquired.append(lock)
        for child in node.body:
            self.visit(child)
        for _ in acquired:
            self.stack.pop()

    # -- nested defs run on their own thread/time: fresh lock context ----
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self.stack = self.stack, []
        for child in node.body:
            self.visit(child)
        self.stack = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self.stack = self.stack, []
        self.visit(node.body)
        self.stack = saved

    # -- writes ----------------------------------------------------------
    def _record_write(self, target: ast.AST, node: ast.AST) -> None:
        attr = _self_attr_target(target)
        if attr is not None and attr not in self.cls.lock_attrs:
            self.cls.writes.append(_Write(
                attr=attr, method=self.method,
                held=None if self.assume_locked
                else frozenset(self.stack), node=node))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                self._record_write(el, node)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, node)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._record_write(t, node)

    # -- calls -----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        fq = dotted(node.func) or ""
        if self.stack or self.assume_locked:
            held = self.stack[-1] if self.stack else "(caller-held lock)"
            leaf = fq.rsplit(".", 1)[-1]
            receiver = fq.rsplit(".", 1)[0] if "." in fq else ""
            blocking = (fq in _BLOCKING_CALLS
                        or (isinstance(node.func, ast.Attribute)
                            and leaf in _BLOCKING_METHODS))
            if leaf == "wait":
                # Condition.wait releases its OWN lock — exempt iff
                # the receiver is a known Condition and nothing BUT
                # that condition's lock is held. Event.wait (or a
                # cond.wait under a second, unrelated lock) blocks.
                attr = receiver[len("self."):] \
                    if receiver.startswith("self.") else None
                if attr in self.cls.conditions:
                    own = self.cls.lock_attrs[attr]
                    blocking = bool(set(self.stack) - {own})
                elif attr is None and "cond" in receiver.lower():
                    blocking = False    # local alias: benefit of doubt
                else:
                    blocking = True
            if blocking:
                self.cls.blocking.append(_Blocking(
                    call=fq or leaf, lock=held,
                    method=self.method, node=node))
            if fq.startswith("self.") and self.stack:
                self.cls.held_calls.append((self.stack[-1], fq, node))
        self.generic_visit(node)


def _analyze_class(mod: ParsedModule, cls_node: ast.ClassDef) -> _ClassLocks:
    cls = _ClassLocks(name=cls_node.name, mod=mod, lock_attrs={},
                      rlocks=set(), conditions=set(), writes=[],
                      blocking=[], nest_edges={}, self_nest=[],
                      held_calls=[], attr_types={}, method_locks={})
    methods = [n for n in cls_node.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # pass 1: find lock attributes + attribute types (constructor wiring)
    for m in methods:
        for node in ast.walk(m):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                attr = _self_attr_target(t) if not isinstance(
                    t, ast.Subscript) else None
                if attr is None:
                    continue
                fac = _lock_factory(node.value)
                if fac is not None:
                    fq = dotted(fac.func) or ""
                    kind = fq.rsplit(".", 1)[-1]
                    canonical = attr
                    if kind == "Condition":
                        cls.conditions.add(attr)
                        if fac.args:
                            inner = dotted(fac.args[0]) or ""
                            if inner.startswith("self."):
                                canonical = inner[len("self."):]
                    cls.lock_attrs[attr] = canonical
                    if kind == "RLock":
                        cls.rlocks.add(attr)
                elif isinstance(node.value, ast.Call):
                    fq = dotted(node.value.func) or ""
                    leaf = fq.rsplit(".", 1)[-1]
                    if leaf and leaf[0].isupper():
                        cls.attr_types[attr] = leaf
    # conditions created before their lock: canonicalize transitively
    for attr, canon in list(cls.lock_attrs.items()):
        seen = {attr}
        while canon in cls.lock_attrs and canon not in seen \
                and cls.lock_attrs[canon] != canon:
            seen.add(canon)
            canon = cls.lock_attrs[canon]
        cls.lock_attrs[attr] = canon
    # pass 2: walk every method with lock context
    for m in methods:
        walker = _MethodWalker(cls, m.name)
        for child in m.body:
            walker.visit(child)
    return cls


def _classes(mod: ParsedModule) -> list[_ClassLocks]:
    # one walk per module, shared by all three lock rules
    cached = getattr(mod, "_lock_classes", None)
    if cached is None:
        cached = [_analyze_class(mod, n) for n in ast.walk(mod.tree)
                  if isinstance(n, ast.ClassDef)]
        mod._lock_classes = cached
    return cached


class _NodeRule(Rule):
    def applies(self, path: str) -> bool:
        parts = path_parts(path)
        # resilience/ joined in ISSUE 4: HealthMonitor windows and
        # ResilienceStats counters are touched from batcher AND
        # submitter threads — exactly this family's territory.
        # obs/ joined in ISSUE 5: Tracer ring + Span attrs are shared
        # between submitter, batcher and scrape threads.
        # sim/ joined in ISSUE 8: the sim is single-threaded by design,
        # so any lock it grows must follow the same discipline as the
        # threaded stack it stands in for.
        # ops/regen.py joined in ISSUE 15: RegenCodec's warm/apply
        # caches are shared by the engine batcher and pool-lane worker
        # threads, so any locking it grows is this family's territory.
        # ops/xor_sched.py + ops/rs_xor.py joined in ISSUE 18: the
        # schedule memo and executor jit caches are hit from the same
        # batcher/pool-lane threads via _MatrixApply.
        if "ops" in parts and parts[-1] in ("regen.py", "xor_sched.py",
                                            "rs_xor.py"):
            return True
        return "serve" in parts or "node" in parts \
            or "resilience" in parts or "obs" in parts \
            or "sim" in parts


@register
class LockUnguardedWrite(_NodeRule):
    id = "lock-unguarded-write"
    description = ("attribute written under the lock in one method and "
                   "without it in another")
    hint = ("take the guarding lock around this write, or suppress "
            "with a comment explaining why lock-free is safe here "
            "(pre-publication, single-writer, etc.)")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for cls in _classes(mod):
            if not cls.lock_attrs:
                continue
            # infer each attribute's guard: the lock most often held
            # at its locked writes (ties break lexicographically)
            candidates: dict[str, dict[str, int]] = {}
            for w in cls.writes:
                if w.method == "__init__" or w.held is None:
                    continue
                for lock in w.held:
                    candidates.setdefault(w.attr, {})[lock] = \
                        candidates.setdefault(w.attr, {}).get(lock, 0) + 1
            guards = {attr: min(counts, key=lambda k: (-counts[k], k))
                      for attr, counts in candidates.items()}
            for w in cls.writes:
                if w.held is None or w.attr not in guards \
                        or w.method in ("__init__", "__new__"):
                    continue
                guard = guards[w.attr]
                if guard in w.held:
                    continue
                how = f"under {', '.join(sorted(w.held))} instead" \
                    if w.held else "without it"
                out.append(self.finding(
                    mod, w.node,
                    f"{cls.name}.{w.attr} is written under "
                    f"{cls.name}.{guard} elsewhere but {how} in "
                    f"`{w.method}`"))
        return out


@register
class LockBlockingCall(_NodeRule):
    id = "lock-blocking-call"
    description = "blocking call while a lock is held"
    hint = ("move the blocking call outside the `with` block (collect "
            "under the lock, act after releasing), or suppress with "
            "justification")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for cls in _classes(mod):
            for b in cls.blocking:
                out.append(self.finding(
                    mod, b.node,
                    f"{b.call}(...) blocks while holding "
                    f"{cls.name}.{b.lock} in `{b.method}`"))
        return out


@register
class LockOrderCycle(_NodeRule):
    id = "lock-order-cycle"
    description = ("lock acquisition order forms a cycle (or a "
                   "non-reentrant lock is re-acquired while held)")
    hint = ("pick one global acquisition order for these locks and "
            "restructure the paths that violate it")

    def check(self, mod: ParsedModule) -> list[Finding]:
        # the degenerate one-lock cycle: with self._lock: with
        # self._lock: deadlocks unless the lock is an RLock
        out = []
        for cls in _classes(mod):
            for attr, node in cls.self_nest:
                out.append(self.finding(
                    mod, node,
                    f"{cls.name}.{attr} re-acquired while already "
                    "held — a non-reentrant lock self-deadlocks here",
                    hint="use threading.RLock, or restructure so the "
                         "inner scope runs with the lock already "
                         "held (e.g. a *_locked helper)"))
        return out

    def check_project(self, mods: list[ParsedModule]) -> list[Finding]:
        classes = [c for m in mods for c in _classes(m)]
        by_name = {c.name: c for c in classes}
        # node ids: "Class.attr" (canonical); edges with example sites
        edges: dict[tuple[str, str], tuple[ParsedModule, ast.AST]] = {}

        def lock_id(cls: _ClassLocks, attr: str) -> str:
            return f"{cls.name}.{attr}"

        for cls in classes:
            for (outer, inner), node in cls.nest_edges.items():
                edges.setdefault(
                    (lock_id(cls, outer), lock_id(cls, inner)),
                    (cls.mod, node))
            for held, fq, node in cls.held_calls:
                # resolve one call level: self.m() and self.X.m()
                parts = fq.split(".")
                target_cls, meth = None, None
                if len(parts) == 2:                      # self.m()
                    target_cls, meth = cls, parts[1]
                elif len(parts) == 3:                    # self.X.m()
                    tname = cls.attr_types.get(parts[1])
                    if tname in by_name:
                        target_cls, meth = by_name[tname], parts[2]
                if target_cls is None:
                    continue
                for lock in target_cls.method_locks.get(meth, ()):
                    a = lock_id(cls, held)
                    b = lock_id(target_cls, lock)
                    if a != b:
                        edges.setdefault((a, b), (cls.mod, node))
        # cycle detection: DFS over the edge graph
        graph: dict[str, list[str]] = {}
        for a, b in edges:
            graph.setdefault(a, []).append(b)
        out, reported = [], set()

        def dfs(start: str, node: str, path: list[str]) -> None:
            for nxt in graph.get(node, ()):
                if nxt == start:
                    cyc = tuple(sorted(path))
                    if cyc in reported:
                        continue
                    reported.add(cyc)
                    # the closing edge always exists: nxt came from
                    # graph[path[-1]], which is built from edges' keys
                    mod, site = edges[(path[-1], start)]
                    chain = " -> ".join(path + [start])
                    out.append(self.finding(
                        mod, site,
                        f"lock-order cycle: {chain}"))
                elif nxt not in path:
                    dfs(start, nxt, path + [nxt])

        for start in sorted(graph):
            dfs(start, start, [start])
        return out
