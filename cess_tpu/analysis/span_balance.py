"""span-balance: every ``Tracer.start(...)`` must be closed.

The tracing subsystem (cess_tpu/obs) records a span only when it
FINISHES — an unclosed span never reaches the ring buffer, silently
orphans every child that named it as parent, and (if made current)
leaks a stale context that mis-parents unrelated spans. The safe
shapes are structural:

- ``with tracer.start(...):`` / ``with tracer.start(...) as sp:``
  (the context manager finishes on exit, error attr included), or
- starting inside a ``try:`` whose ``finally`` owns the ``finish()``
  (the generator/driver shape — serve/stream.py).

A span that legitimately OUTLIVES its frame (the engine's per-request
spans are finished by the batcher thread at resolve time) is the
exception, not the rule — those sites carry an inline
``# cesslint: disable=span-balance`` with the justification, exactly
like the other analyzer families handle justified violations.

Detection is receiver-name based (an attribute call ``<recv>.start()``
where the receiver's last segment names a tracer): AST analysis cannot
type ``x.start()``, and matching every ``.start()`` would drown in
``Thread.start()`` false positives. The obs package itself is exempt
(it is the implementation being wrapped).
"""
from __future__ import annotations

import ast

from .core import Finding, ParsedModule, Rule, dotted, path_parts, register


def _is_tracer_start(node: ast.AST) -> bool:
    """A call ``<recv>.start(...)`` whose receiver's final name
    segment identifies a tracer (``tracer``, ``_tracer``,
    ``self.tracer``, ``engine_tracer``, ...)."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "start"):
        return False
    recv = dotted(node.func.value)
    if recv is None:
        return False
    return recv.rsplit(".", 1)[-1].lower().endswith("tracer")


@register
class SpanBalance(Rule):
    id = "span-balance"
    description = ("Tracer.start(...) not managed by a with block or "
                   "a try/finally")
    hint = ("wrap the call: `with tracer.start(...) as span:` (or use "
            "obs.span(...)), or start inside a try: whose finally: "
            "calls span.finish(); a span that must outlive the frame "
            "needs an inline justification "
            "(# cesslint: disable=span-balance)")

    def applies(self, path: str) -> bool:
        # everywhere tracing is threaded — except trace.py itself,
        # whose whole job is constructing and managing spans. The
        # exemption used to cover the whole obs package; ISSUE 6 adds
        # obs/slo.py (a CONSUMER of spans, not the implementation), so
        # the carve-out is now exactly the implementation module.
        parts = path_parts(path)
        return not ("obs" in parts and parts
                    and parts[-1] == "trace.py")

    def check(self, mod: ParsedModule) -> list[Finding]:
        managed: set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # anything inside a with-item's context expression is
                # closed by __exit__ (IfExp-wrapped starts included)
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if _is_tracer_start(sub):
                            managed.add(id(sub))
            elif isinstance(node, ast.Try) and node.finalbody:
                # a start anywhere under a try/finally is treated as
                # balanced — the finally path owns the finish()
                for sub in ast.walk(node):
                    if _is_tracer_start(sub):
                        managed.add(id(sub))
        out = []
        for node in ast.walk(mod.tree):
            if _is_tracer_start(node) and id(node) not in managed:
                out.append(self.finding(
                    mod, node,
                    f"`{dotted(node.func)}(...)` is not closed by a "
                    "with block or try/finally — an unfinished span "
                    "never reaches the ring buffer and orphans its "
                    "children"))
        return out
