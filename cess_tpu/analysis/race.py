"""race: cross-thread field writes must share a lock guard.

lock_discipline checks per-file *consistency* ("this attr is usually
written under self._lock — here it is not") but cannot see WHICH
threads reach a write, so a field that two threads hammer lock-free
is invisible as long as it is consistently lock-free. The flow
layer's thread-root attribution closes that gap, RacerD-style
(Blackshear et al., OOPSLA '18): no whole-program alias analysis,
just ownership-ish roots plus lock sets.

A finding requires ALL of:
- the field is written from >= 2 distinct thread roots (batcher
  loops spawned via ``Thread(target=...)``, registered listeners/
  callbacks, and the public ``caller`` root) — single-writer/
  multi-reader is exempt by construction;
- the lock-set intersection over those writes is empty — writes that
  all share one guard are fine, as are ``*_locked`` helpers (the
  caller holds the guard by convention, trusted exactly as
  lock_discipline trusts them);
- the write is post-publication — ``__init__``/``__new__`` run
  before any thread can see the object and are exempt.
"""
from __future__ import annotations

from .core import Finding, ParsedModule, Rule, register
from .flow import CALLER_ROOT, flow_graph
from .lock_discipline import _classes


@register
class CrossThreadRace(Rule):
    id = "race"
    description = ("field written from >= 2 thread roots without a "
                   "common lock guard")
    hint = ("hold one consistent lock around every cross-thread "
            "write (or move the write into the owning thread's loop "
            "and publish via a queue); pre-start writes belong in "
            "__init__")

    def applies(self, path: str) -> bool:
        return True              # package-wide: thread roots cross files

    def check_project(self, mods: list[ParsedModule]) -> list[Finding]:
        graph = flow_graph(mods)
        out: list[Finding] = []
        for mod in mods:
            for cls in _classes(mod):
                ci = graph._classes_by_path.get((mod.path, cls.name))
                if ci is None:
                    continue
                roots = graph.method_roots(ci)
                by_attr: dict[str, list] = {}
                for w in cls.writes:
                    if w.method in ("__init__", "__new__"):
                        continue     # pre-publication
                    if w.attr in cls.lock_attrs:
                        continue     # the locks themselves
                    by_attr.setdefault(w.attr, []).append(w)
                for attr, writes in sorted(by_attr.items()):
                    writer_roots: set[str] = set()
                    for w in writes:
                        writer_roots |= roots.get(w.method,
                                                  {CALLER_ROOT})
                    if len(writer_roots) < 2:
                        continue     # single-writer/multi-reader
                    common = None    # None == universal set so far
                    culprit = writes[0]
                    for w in writes:
                        if w.held is None:
                            continue   # *_locked: caller holds guard
                        if common is None:
                            common = set(w.held)
                        else:
                            common &= w.held
                        if not w.held:
                            culprit = w
                    if common is None or common:
                        continue     # consistently guarded (or all
                        #              caller-held by convention)
                    names = ", ".join(sorted(writer_roots))
                    held = ", ".join(sorted(culprit.held or ())) \
                        or "no lock"
                    out.append(self.finding(
                        mod, culprit.node,
                        f"`{cls.name}.{attr}` is written from "
                        f"{len(writer_roots)} thread roots ({names}) "
                        f"with no common lock — this write holds "
                        f"{held}"))
        return out
