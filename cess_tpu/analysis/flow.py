"""flow: the shared interprocedural layer under the dataflow rules.

Six PRs of observability/remediation planes rest on three house
contracts — count-sequenced replay witnesses, lock-guarded shared
state across batcher/author/announce threads, and zero-cost-when-off
hook seams — that the per-file AST rules cannot see: each contract is
a property of how values FLOW between functions, classes and threads.
This module builds, once per scan, the package-wide facts the three
rule families on top of it consume:

- an import-resolved CALL GRAPH: ``f()``, ``self.m()``,
  ``self.attr.m()`` (typed-attribute resolution reusing the
  lock-discipline machinery: ``self.X = ClassName(...)``, annotated
  ``__init__`` params stored onto ``self``, dataclass field
  annotations), ``alias.f()`` through relative imports, and
  ``ClassName(...)`` constructors;
- THREAD-ROOT attribution: which methods run on which thread —
  ``Thread(target=self.m)`` targets (directly or through one level of
  spawn-helper indirection), methods registered as listeners/
  callbacks (``x.add_listener(self.m)``-style), and everything else
  on the public ``caller`` root — closed over resolvable call edges;
- a TAINT LATTICE over nondeterminism sources (``time.*``,
  ``random.*``, ``threading.get_ident``, ``id()``, dict/set
  iteration order escapes): per-function return taint, per-class
  field taint and per-parameter taint, iterated to a fixpoint so a
  wallclock read three calls away from a witness still reaches it.

Only EXPLICIT dataflow is tracked (assignments, calls, containers,
field writes) — never implicit flow through branch conditions: a
count-sequenced state machine whose *timing* of observations is
wall-clock driven is exactly the house design, not a bug
(Engler et al., "bugs as deviant behavior": infer the codebase's own
contracts, flag deviations — not every theoretical channel).

The graph is built once per ``lint_modules`` run and cached on the
first module of the scanned set, so the three families share one
pass (the same parse-once discipline core.py applies per file).
"""
from __future__ import annotations

import ast
import dataclasses

from .core import ParsedModule, dotted

# ---------------------------------------------------------------------------
# taint sources — the nondeterminism registry (documented in README)
# ---------------------------------------------------------------------------
#: exact dotted names whose *call or read* yields a nondeterministic
#: value (wall clocks, entropy, thread identity)
TAINT_SOURCES = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "os.urandom", "os.getpid", "uuid.uuid4", "uuid.uuid1",
    "threading.get_ident", "threading.get_native_id",
    "threading.current_thread", "threading.active_count",
})
#: dotted-name prefixes treated as sources (whole entropy families)
TAINT_PREFIXES = ("random.", "np.random.", "numpy.random.", "secrets.")
#: builtins whose result is process-dependent (``id`` is an address;
#: ``hash`` of str/bytes is salted per process via PYTHONHASHSEED)
TAINT_BUILTINS = frozenset({"id", "hash"})
#: the order-taint tag for values whose CONTENT is deterministic but
#: whose iteration order is not (set/dict-view escapes)
ORDER_SOURCE = "unordered-iteration"

#: calls that erase ORDER taint (the result's order is canonical or
#: order no longer exists) but pass value taint through
ORDER_SANITIZERS = frozenset({"sorted", "len", "sum", "min", "max",
                              "any", "all", "set", "frozenset",
                              "dict", "Counter", "collections.Counter"})
#: calls whose result is untainted regardless of arguments (structure
#: queries, types — no nondeterministic bytes survive them)
VALUE_SANITIZERS = frozenset({"len", "isinstance", "type", "bool",
                              "callable", "hasattr"})

_UNORDERED_METHODS = {"keys", "values", "items"}


@dataclasses.dataclass(frozen=True)
class Taint:
    """One nondeterminism origin: which source, observed where."""
    source: str
    path: str
    line: int

    def describe(self) -> str:
        return f"`{self.source}` at {self.path}:{self.line}"


# cap per-fact taint sets so the fixpoint stays bounded (first-come
# origins win; a fact past the cap is already a reportable finding)
_TAINT_CAP = 6


# ---------------------------------------------------------------------------
# graph nodes
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class FuncInfo:
    """One function or method."""
    fqid: str                        # "path::Class.meth" / "path::func"
    path: str
    name: str
    cls: str | None                  # owning class name, if a method
    node: ast.AST                    # FunctionDef / AsyncFunctionDef
    params: list[str]                # positional+kw param names (no self)
    mod: ParsedModule = None


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    methods: dict[str, FuncInfo]
    attr_types: dict[str, str]       # self.X -> ClassName
    thread_targets: set[str]         # method names run as Thread targets
    listener_methods: set[str]       # methods registered as callbacks


# calls whose leaf name registers a bound method as a cross-thread
# callback (the flight-recorder listener idiom and friends)
LISTENER_REGISTRARS = frozenset({
    "add_listener", "add_handler", "subscribe", "register_listener",
    "attach_listener", "on_edge",
})

#: the implicit root every public method runs on
CALLER_ROOT = "caller"


class FlowGraph:
    """Package-wide call graph + thread roots + taint facts."""

    def __init__(self, mods: list[ParsedModule]):
        self.mods = mods
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}      # by unique name
        self._class_dupes: set[str] = set()
        self.module_funcs: dict[str, dict[str, str]] = {}  # path -> name -> fqid
        self.imports: dict[str, dict[str, str]] = {}       # path -> alias -> path
        # taint facts (the fixpoint state)
        self.ret_taints: dict[str, set[Taint]] = {}
        self.field_taints: dict[tuple[str, str], set[Taint]] = {}
        self.param_taints: dict[tuple[str, int], set[Taint]] = {}
        # where a field FIRST picked up each taint (finding evidence)
        self.field_sites: dict[tuple[str, str], tuple[str, int]] = {}
        # worklist machinery: fact keys changed this round, and which
        # functions READ each fact key (reads are syntactic — stable
        # across rounds — so one full pass learns the whole map)
        self._dirty: set[tuple] = set()
        self._readers: dict[tuple, set[str]] = {}
        self._collect()
        self._resolve_thread_roots()
        self._taint_fixpoint()

    # -- construction ------------------------------------------------------
    def _collect(self) -> None:
        by_path = {m.path: m for m in self.mods}
        for mod in self.mods:
            self.imports[mod.path] = _import_map(mod, by_path)
            funcs: dict[str, str] = {}
            self.module_funcs[mod.path] = funcs
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    fi = self._add_func(mod, node, None)
                    funcs[node.name] = fi.fqid
                elif isinstance(node, ast.ClassDef):
                    self._add_class(mod, node)

    def _add_func(self, mod: ParsedModule, node: ast.AST,
                  cls: str | None) -> FuncInfo:
        qual = f"{cls}.{node.name}" if cls else node.name
        fqid = f"{mod.path}::{qual}"
        args = node.args
        params = [a.arg for a in args.posonlyargs + args.args
                  if a.arg not in ("self", "cls")]
        params += [a.arg for a in args.kwonlyargs]
        fi = FuncInfo(fqid=fqid, path=mod.path, name=node.name,
                      cls=cls, node=node, params=params, mod=mod)
        self.functions[fqid] = fi
        return fi

    def _add_class(self, mod: ParsedModule, node: ast.ClassDef) -> None:
        methods: dict[str, FuncInfo] = {}
        attr_types: dict[str, str] = {}
        # dataclass-style field annotations: ``world: World``
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                t = _annotation_class(stmt.annotation)
                if t:
                    attr_types[stmt.target.id] = t
        for stmt in node.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            methods[stmt.name] = self._add_func(mod, stmt, node.name)
            # annotated params stored onto self:  def __init__(self,
            # board: SloBoard): ... self.board = board
            ann = {a.arg: _annotation_class(a.annotation)
                   for a in stmt.args.args if a.annotation is not None}
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if isinstance(sub.value, ast.Name) \
                            and ann.get(sub.value.id):
                        attr_types.setdefault(attr, ann[sub.value.id])
                    elif isinstance(sub.value, ast.Call):
                        leaf = (dotted(sub.value.func) or "") \
                            .rsplit(".", 1)[-1]
                        if leaf and leaf[0].isupper():
                            attr_types.setdefault(attr, leaf)
        ci = ClassInfo(name=node.name, path=mod.path, node=node,
                       methods=methods, attr_types=attr_types,
                       thread_targets=set(), listener_methods=set())
        if node.name in self.classes:
            self._class_dupes.add(node.name)
            self.classes.pop(node.name, None)
        elif node.name not in self._class_dupes:
            self.classes[node.name] = ci
        # always findable by (path, name) even when the name collides
        self.module_funcs.setdefault(mod.path, {})
        for mname, fi in methods.items():
            self.functions[fi.fqid] = fi
        self._classes_by_path = getattr(self, "_classes_by_path", {})
        self._classes_by_path[(mod.path, node.name)] = ci

    # -- call resolution ---------------------------------------------------
    def class_of(self, name: str | None) -> ClassInfo | None:
        if name is None:
            return None
        return self.classes.get(name)

    def resolve_call(self, fq: str | None, caller: FuncInfo,
                     local_types: dict[str, str] | None = None,
                     ) -> FuncInfo | None:
        """Best-effort single target for a dotted callee, or None."""
        if not fq:
            return None
        parts = fq.split(".")
        local_types = local_types or {}
        owner = self.class_of(caller.cls)
        # self.m()  /  cls-local call
        if parts[0] == "self" and owner is not None:
            if len(parts) == 2:
                return owner.methods.get(parts[1])
            if len(parts) == 3:
                tcls = self.class_of(owner.attr_types.get(parts[1]))
                if tcls is not None:
                    return tcls.methods.get(parts[2])
            return None
        # f()  — module function or class constructor in scope
        if len(parts) == 1:
            fqid = self.module_funcs.get(caller.path, {}).get(parts[0])
            if fqid:
                return self.functions.get(fqid)
            tcls = self.class_of(parts[0]) \
                if parts[0][:1].isupper() else None
            if tcls is not None:
                return tcls.methods.get("__init__")
            return None
        # alias.f() through the import map;  Local.m() via local types
        if len(parts) == 2:
            head, leaf = parts
            target_path = self.imports.get(caller.path, {}).get(head)
            if target_path is not None:
                fqid = self.module_funcs.get(target_path, {}).get(leaf)
                if fqid:
                    return self.functions.get(fqid)
                tcls = self._classes_by_path.get((target_path, leaf))
                if tcls is not None:
                    return tcls.methods.get("__init__")
            tcls = self.class_of(local_types.get(head)) \
                or (self.class_of(head) if head[:1].isupper() else None)
            if tcls is not None:
                return tcls.methods.get(leaf)
        # alias.Class.m() / alias.Class()
        if len(parts) == 3:
            target_path = self.imports.get(caller.path, {}).get(parts[0])
            if target_path is not None:
                tcls = self._classes_by_path.get((target_path, parts[1]))
                if tcls is not None:
                    return tcls.methods.get(parts[2])
        return None

    # -- thread roots ------------------------------------------------------
    def _resolve_thread_roots(self) -> None:
        """Mark Thread targets and listener registrations, including
        one level of spawn-helper indirection
        (``self._spawn(self._author_loop)`` where the helper does
        ``Thread(target=fn)``)."""
        # pass 1: direct Thread(target=self.m) + helpers whose PARAM
        # becomes a Thread target + listener registrations
        spawn_params: dict[str, set[int]] = {}   # fqid -> param indexes
        for fi in list(self.functions.values()):
            owner = self.class_of(fi.cls)
            local_types = _local_class_types(fi.node)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                fq = dotted(node.func) or ""
                leaf = fq.rsplit(".", 1)[-1]
                if leaf == "Thread":
                    target = _kwarg(node, "target")
                    tfq = dotted(target) if target is not None else None
                    if tfq and tfq.startswith("self.") and owner:
                        owner.thread_targets.add(tfq[len("self."):])
                    elif tfq and tfq in fi.params:
                        spawn_params.setdefault(fi.fqid, set()).add(
                            fi.params.index(tfq))
                elif leaf in LISTENER_REGISTRARS:
                    for arg in node.args:
                        afq = dotted(arg)
                        if not afq or "." not in afq:
                            continue
                        head, meth = afq.rsplit(".", 1)
                        tcls = None
                        if head == "self" and owner is not None:
                            tcls = owner
                        elif owner is not None \
                                and head.startswith("self."):
                            tcls = self.class_of(
                                owner.attr_types.get(head[5:]))
                        else:
                            tcls = self.class_of(local_types.get(head))
                        if tcls is not None and meth in tcls.methods:
                            tcls.listener_methods.add(meth)
        # pass 2: callers of spawn helpers pass self.m as the target
        if spawn_params:
            for fi in list(self.functions.values()):
                owner = self.class_of(fi.cls)
                if owner is None:
                    continue
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self.resolve_call(dotted(node.func), fi)
                    if callee is None or callee.fqid not in spawn_params:
                        continue
                    for idx in spawn_params[callee.fqid]:
                        if idx < len(node.args):
                            afq = dotted(node.args[idx]) or ""
                            if afq.startswith("self."):
                                owner.thread_targets.add(afq[5:])

    def method_roots(self, ci: ClassInfo) -> dict[str, set[str]]:
        """method name -> thread roots it can run on. Thread-target
        and listener methods seed their own roots; every OTHER method
        seeds ``caller``; roots close over resolvable self-call
        edges (a helper called from the batcher loop runs on the
        batcher thread)."""
        roots: dict[str, set[str]] = {}
        for name in ci.methods:
            if name in ci.thread_targets:
                roots[name] = {f"thread:{name}"}
            elif name in ci.listener_methods:
                roots[name] = {f"listener:{name}"}
            elif name.startswith("_") and not name.endswith("__"):
                # private helper: reachable only through the edges
                # below — seeding ``caller`` here would hand every
                # loop-only helper a phantom second root
                roots[name] = set()
            else:
                roots[name] = {CALLER_ROOT}
        # close over intra-class call edges (self.m() and the
        # *_locked/helper conventions); a few rounds reach fixpoint
        edges: dict[str, set[str]] = {name: set() for name in ci.methods}
        for name, fi in ci.methods.items():
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    fq = dotted(node.func) or ""
                    if fq.startswith("self.") and "." not in fq[5:] \
                            and fq[5:] in ci.methods:
                        edges[name].add(fq[5:])
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in ci.methods \
                        and node.attr not in ci.thread_targets \
                        and not isinstance(node.ctx, ast.Store):
                    # bound-method reference (callbacks, futures) —
                    # but NOT a known thread target: the reference in
                    # ``Thread(target=self._run)`` is the spawn site,
                    # not a synchronous call on the spawning thread
                    edges[name].add(node.attr)
        for _ in range(len(ci.methods)):
            changed = False
            for src, callees in edges.items():
                for callee in callees:
                    if callee in ("__init__", "__new__"):
                        continue
                    before = len(roots[callee])
                    # a helper invoked from a thread root runs there
                    # IN ADDITION to anywhere else it is reachable
                    # from — except __init__ (pre-publication)
                    roots[callee] |= roots[src]
                    changed |= len(roots[callee]) != before
            if not changed:
                break
        # __init__ runs pre-thread-start, on the constructing thread
        for name in ("__init__", "__new__"):
            if name in roots:
                roots[name] = {CALLER_ROOT}
        return roots

    # -- taint -------------------------------------------------------------
    def _taint_fixpoint(self) -> None:
        """Worklist iteration: one full pass learns every function's
        (syntactic, hence stable) fact reads; afterwards only the
        readers of facts that actually changed re-run — deep call
        chains converge without re-walking 1500 function bodies per
        round. The finite taint sets + per-fact cap make the lattice
        finite, so this terminates; the pass budget is pure defense."""
        for fi in self.functions.values():
            p = _TaintPass(self, fi)
            p.run()
            for key in p.reads:
                self._readers.setdefault(key, set()).add(fi.fqid)
        budget = 40 * max(1, len(self.functions))
        while self._dirty and budget > 0:
            dirty, self._dirty = self._dirty, set()
            affected: set[str] = set()
            for key in dirty:
                affected |= self._readers.get(key, set())
            for fqid in affected:
                fi = self.functions.get(fqid)
                if fi is None:
                    continue
                budget -= 1
                _TaintPass(self, fi).run()

    def _merge(self, store: dict, kind: str, key,
               taints: set[Taint]) -> bool:
        if not taints:
            return False
        cur = store.setdefault(key, set())
        before = len(cur)
        for t in taints:
            if len(cur) >= _TAINT_CAP:
                break
            cur.add(t)
        if len(cur) != before:
            self._dirty.add((kind, key))
            return True
        return False


def _import_map(mod: ParsedModule,
                by_path: dict[str, ParsedModule]) -> dict[str, str]:
    """alias -> module path, for modules inside the scanned set.
    Resolves ``from ..obs import flight as _flight`` and
    ``from . import clock`` against the module's own path."""
    out: dict[str, str] = {}
    pkg_parts = mod.path.split("/")[:-1]        # containing package
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
            else:
                base = (node.module or "").split(".")
            rel = (node.module or "").split(".") if node.level else []
            stem = base + [p for p in rel if p]
            for alias in node.names:
                cand = "/".join(stem + [alias.name]) + ".py"
                if cand in by_path:
                    out[alias.asname or alias.name] = cand
                else:
                    # ``from .clock import EventQueue`` — names from a
                    # sibling module: map the NAME to that module so
                    # ``EventQueue(...)`` resolves through it
                    sib = "/".join(stem) + ".py"
                    if sib in by_path:
                        out.setdefault(alias.asname or alias.name, sib)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                cand = alias.name.replace(".", "/") + ".py"
                if cand in by_path:
                    out[alias.asname or alias.name] = cand
    return out


def _annotation_class(ann: ast.AST | None) -> str | None:
    """The ClassName inside an annotation (handles ``X | None`` and
    string annotations), if it looks like a class."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return _annotation_class(ann.left) or _annotation_class(ann.right)
    name = dotted(ann)
    if name:
        leaf = name.rsplit(".", 1)[-1]
        if leaf[:1].isupper():
            return leaf
    return None


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _kwarg(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _local_class_types(fn: ast.AST) -> dict[str, str]:
    """name -> ClassName for ``x = ClassName(...)`` locals (used by
    listener registration and receiver typing)."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            leaf = (dotted(node.value.func) or "").rsplit(".", 1)[-1]
            if leaf and leaf[0].isupper():
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = leaf
    return out


# ---------------------------------------------------------------------------
# the per-function taint pass
# ---------------------------------------------------------------------------
class _TaintPass:
    """One forward pass over a function body, updating the graph's
    return/field/param facts. Statements are walked in source order,
    twice, so loop-carried locals stabilize within the pass."""

    def __init__(self, graph: FlowGraph, fi: FuncInfo):
        self.g = graph
        self.fi = fi
        self.owner = graph.class_of(fi.cls)
        self.env: dict[str, set[Taint]] = {}
        self.local_types = _local_class_types(fi.node)
        self.changed = False
        self.reads: set[tuple] = set()   # fact keys this body reads

    def _read_ret(self, fqid: str) -> set[Taint]:
        self.reads.add(("ret", fqid))
        return set(self.g.ret_taints.get(fqid, ()))

    def _read_param(self, key: tuple) -> set[Taint]:
        self.reads.add(("param", key))
        return set(self.g.param_taints.get(key, ()))

    def _read_field(self, key: tuple) -> set[Taint]:
        self.reads.add(("field", key))
        return set(self.g.field_taints.get(key, ()))

    def run(self) -> bool:
        body = getattr(self.fi.node, "body", [])
        for _ in range(2):
            for stmt in body:
                self._stmt(stmt)
        return self.changed

    # -- statements --------------------------------------------------------
    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                       # nested scopes analyzed on their own
        if isinstance(node, ast.Assign):
            t = self._expr(node.value)
            for tgt in node.targets:
                self._assign(tgt, t)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self._assign(node.target, self._expr(node.value))
        elif isinstance(node, ast.AugAssign):
            t = self._expr(node.value) | self._read_target(node.target)
            self._assign(node.target, t)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                t = self._expr(node.value)
                self.changed |= self.g._merge(self.g.ret_taints,
                                              "ret", self.fi.fqid, t)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            t = self._expr(node.iter)
            if _unordered_iter(node.iter, self.env):
                t = t | {Taint(ORDER_SOURCE, self.fi.path,
                               getattr(node.iter, "lineno", 1))}
            self._assign(node.target, t)
            for child in node.body + node.orelse:
                self._stmt(child)
        elif isinstance(node, (ast.If, ast.While)):
            self._expr(node.test)
            for child in node.body + node.orelse:
                self._stmt(child)
        elif isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            for item in node.items:
                t = self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, t)
            for child in node.body:
                self._stmt(child)
        elif isinstance(node, ast.Try):
            for child in (node.body + node.orelse + node.finalbody):
                self._stmt(child)
            for h in node.handlers:
                for child in h.body:
                    self._stmt(child)
        elif isinstance(node, ast.Expr):
            self._expr(node.value)
        elif isinstance(node, (ast.Delete, ast.Pass, ast.Break,
                               ast.Continue, ast.Import, ast.ImportFrom,
                               ast.Global, ast.Nonlocal, ast.Assert,
                               ast.Raise)):
            if isinstance(node, ast.Assert):
                self._expr(node.test)
            if isinstance(node, ast.Raise) and node.exc is not None:
                self._expr(node.exc)
        elif isinstance(node, ast.Match):
            self._expr(node.subject)
            for case in node.cases:
                for child in case.body:
                    self._stmt(child)

    def _assign(self, tgt: ast.AST, taints: set[Taint]) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._assign(el, taints)
            return
        if isinstance(tgt, ast.Starred):
            self._assign(tgt.value, taints)
            return
        if isinstance(tgt, ast.Subscript):
            # d[k] = v taints d as a whole — but a keyed store
            # LAUNDERS order taint: the container's content no longer
            # depends on which iteration order produced it (value
            # taints like wall clocks survive)
            taints = {t for t in taints if t.source != ORDER_SOURCE}
            tgt = tgt.value
            taints = taints | self._read_target(tgt)
        if isinstance(tgt, ast.Name):
            cur = self.env.get(tgt.id, set())
            self.env[tgt.id] = cur | taints if taints else taints
            return
        attr = _self_attr(tgt) if isinstance(tgt, ast.Attribute) else None
        if attr is not None and self.owner is not None:
            key = (self.owner.name, attr)
            if taints and key not in self.g.field_sites:
                self.g.field_sites[key] = (self.fi.path,
                                           getattr(tgt, "lineno", 1))
            self.changed |= self.g._merge(self.g.field_taints, "field",
                                          key, taints)

    def _read_target(self, tgt: ast.AST) -> set[Taint]:
        if isinstance(tgt, ast.Name):
            return set(self.env.get(tgt.id, ()))
        if isinstance(tgt, ast.Attribute):
            return self._expr(tgt)
        return set()

    # -- expressions -------------------------------------------------------
    def _expr(self, node: ast.AST | None) -> set[Taint]:
        if node is None:
            return set()
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.Name):
            t = set(self.env.get(node.id, ()))
            if node.id in self.fi.params:
                t |= self._read_param(
                    (self.fi.fqid, self.fi.params.index(node.id)))
            return t
        if isinstance(node, ast.Attribute):
            fq = dotted(node)
            if fq is not None:
                if fq in TAINT_SOURCES or fq.startswith(TAINT_PREFIXES):
                    return {Taint(fq, self.fi.path, node.lineno)}
                # self.X -> field taints; typed locals: x.attr
                if fq.startswith("self.") and "." not in fq[5:] \
                        and self.owner is not None:
                    return self._read_field((self.owner.name, fq[5:]))
                parts = fq.split(".")
                if len(parts) == 2:
                    tname = self.local_types.get(parts[0]) \
                        or (self.owner.attr_types.get(parts[0])
                            if self.owner else None)
                    if tname and tname in self.g.classes:
                        return self._read_field((tname, parts[1]))
                if len(parts) == 3 and parts[0] == "self" \
                        and self.owner is not None:
                    tname = self.owner.attr_types.get(parts[1])
                    if tname and tname in self.g.classes:
                        return self._read_field((tname, parts[2]))
            return self._expr(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self._expr(node.left) | self._expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._expr(node.operand)
        if isinstance(node, ast.BoolOp):
            out = set()
            for v in node.values:
                out |= self._expr(v)
            return out
        if isinstance(node, ast.Compare):
            out = self._expr(node.left)
            for c in node.comparators:
                out |= self._expr(c)
            return out
        if isinstance(node, ast.IfExp):
            self._expr(node.test)
            return self._expr(node.body) | self._expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = set()
            for el in node.elts:
                out |= self._expr(el)
            return out
        if isinstance(node, ast.Set):
            out = set()
            for el in node.elts:
                out |= self._expr(el)
            return out
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                out |= self._expr(k)
            for v in node.values:
                out |= self._expr(v)
            return out
        if isinstance(node, ast.Subscript):
            return self._expr(node.value) | self._expr(node.slice)
        if isinstance(node, ast.Starred):
            return self._expr(node.value)
        if isinstance(node, ast.JoinedStr):
            out = set()
            for v in node.values:
                out |= self._expr(v)
            return out
        if isinstance(node, ast.FormattedValue):
            return self._expr(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            out = set()
            for gen in node.generators:
                t = self._expr(gen.iter)
                if _unordered_iter(gen.iter, self.env) \
                        and not isinstance(node, ast.SetComp):
                    t = t | {Taint(ORDER_SOURCE, self.fi.path,
                                   getattr(gen.iter, "lineno", 1))}
                self._assign(gen.target, t)
                out |= t
            if isinstance(node, ast.DictComp):
                out |= self._expr(node.key) | self._expr(node.value)
                # a dict comprehension is a keyed store: content is
                # order-independent, so its OWN generators' order
                # taint is laundered (value taints survive)
                out = {t for t in out if t.source != ORDER_SOURCE}
            else:
                out |= self._expr(node.elt)
            return out
        if isinstance(node, ast.Slice):
            return (self._expr(node.lower) | self._expr(node.upper)
                    | self._expr(node.step))
        if isinstance(node, ast.Lambda):
            return set()
        if isinstance(node, (ast.Constant, ast.NamedExpr)):
            if isinstance(node, ast.NamedExpr):
                t = self._expr(node.value)
                self._assign(node.target, t)
                return t
            return set()
        if isinstance(node, ast.Await):
            return self._expr(node.value)
        return set()

    def _call(self, node: ast.Call) -> set[Taint]:
        fq = dotted(node.func)
        leaf = (fq or "").rsplit(".", 1)[-1]
        arg_taints = set()
        for a in node.args:
            arg_taints |= self._expr(a)
        for kw in node.keywords:
            arg_taints |= self._expr(kw.value)
        # sources
        if fq and (fq in TAINT_SOURCES or fq.startswith(TAINT_PREFIXES)):
            return {Taint(fq, self.fi.path, node.lineno)}
        if fq in TAINT_BUILTINS:
            return {Taint(f"{fq}()", self.fi.path, node.lineno)}
        # order escapes:  list(d)/tuple(s.keys()) without sorted
        if leaf in ("list", "tuple", "iter", "next") and node.args \
                and _unordered_iter(node.args[0], self.env):
            arg_taints = arg_taints | {
                Taint(ORDER_SOURCE, self.fi.path, node.lineno)}
        # sanitizers
        if leaf in VALUE_SANITIZERS:
            return set()
        if leaf in ORDER_SANITIZERS:
            return {t for t in arg_taints if t.source != ORDER_SOURCE}
        # method receiver taint rides through (x.strip() of tainted x)
        recv_taints = set()
        if isinstance(node.func, ast.Attribute):
            recv_taints = self._expr(node.func.value)
        # resolved callee: propagate arg taints into params, return
        # the callee's known return taints
        callee = self.g.resolve_call(fq, self.fi, self.local_types)
        if callee is not None:
            for i, a in enumerate(node.args):
                t = self._expr(a)
                if t and i < len(callee.params):
                    self.changed |= self.g._merge(
                        self.g.param_taints, "param",
                        (callee.fqid, i), t)
            for kw in node.keywords:
                t = self._expr(kw.value)
                if t and kw.arg in callee.params:
                    self.changed |= self.g._merge(
                        self.g.param_taints, "param",
                        (callee.fqid, callee.params.index(kw.arg)), t)
                elif t and kw.arg is None:
                    # **kwargs fan-out: taint every parameter
                    for i in range(len(callee.params)):
                        self.changed |= self.g._merge(
                            self.g.param_taints, "param",
                            (callee.fqid, i), t)
            out = self._read_ret(callee.fqid)
            if callee.name == "__init__" and callee.cls:
                # constructing a class whose fields are tainted does
                # not itself yield a tainted VALUE; field reads do
                out = set()
            return out | recv_taints
        # unresolved: conservative pass-through of args + receiver
        return arg_taints | recv_taints


def _unordered_iter(expr: ast.AST, env: dict) -> bool:
    """Does iterating ``expr`` observe hash/insertion order? (set and
    dict-view escapes; ``sorted(...)`` upstream clears it)"""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        fq = dotted(expr.func) or ""
        leaf = fq.rsplit(".", 1)[-1]
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in _UNORDERED_METHODS \
                and not expr.args \
                and not isinstance(expr.func.value, ast.Dict):
            return True
        if leaf in ("set", "frozenset"):
            return True
    return False


# ---------------------------------------------------------------------------
# the shared-graph cache (one build per lint_modules run)
# ---------------------------------------------------------------------------
def flow_graph(mods: list[ParsedModule]) -> FlowGraph:
    """The FlowGraph for this exact module set, built once and cached
    on the first module (all flow rules apply package-wide, so every
    family sees the same list and shares the build)."""
    if not mods:
        return FlowGraph([])
    anchor = mods[0]
    key = tuple(id(m) for m in mods)
    cached = getattr(anchor, "_flow_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    graph = FlowGraph(mods)
    anchor._flow_cache = (key, graph)
    return graph
