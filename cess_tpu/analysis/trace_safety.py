"""Trace-safety analyzers for the device code (ops/, serve/).

A function traced by ``jax.jit`` (or handed to ``pallas_call``) runs
its Python body ONCE per compile, against abstract tracers — so Python
side effects silently freeze at trace-time values, host conversions
(`.item()`, `float(tracer)`, `np.*` on a traced arg) either fail under
jit or force a device->host sync, and an out-of-range integer literal
fed into a narrow dtype wraps silently on the uint8/uint32 lanes the
GF(2^8)/M31 kernels (ops/gf.py, ops/pfield.py) do exact math on.
These are invisible to unit tests that only check eager results —
and mechanically detectable from the AST.

Rules:
- trace-global-mutation : ``global``/``nonlocal`` inside a traced body
- trace-print           : ``print`` inside a traced body
- trace-host-sync       : ``.item()``/``.tolist()``/``.tobytes()``/
                          ``float/int/bool(traced arg)`` inside a
                          traced body
- trace-host-transfer   : ``np.*`` applied to a traced argument
- dtype-overflow        : integer literal outside the target integer
                          dtype's range in ``np.uint8(...)``-style
                          constructions
"""
from __future__ import annotations

import ast

from .core import Finding, ParsedModule, Rule, dotted, path_parts, register

_JIT = {"jax.jit", "jit"}
_PARTIAL = {"functools.partial", "partial"}


def _static_spec(call: ast.Call) -> tuple[set[int], set[str]]:
    """(positional indices, parameter names) marked static in a
    jax.jit(...)/partial(jax.jit, ...) call."""
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        v = kw.value
        elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for e in elts:
            if isinstance(e, ast.Constant):
                if isinstance(e.value, int):
                    nums.add(e.value)
                elif isinstance(e.value, str):
                    names.add(e.value)
    return nums, names


def _jit_decorator(dec: ast.AST) -> tuple[bool, set[int], set[str]]:
    """(is_jit, static argnums, static argnames)."""
    if dotted(dec) in _JIT:
        return True, set(), set()
    if isinstance(dec, ast.Call):
        fq = dotted(dec.func)
        if fq in _JIT:
            return (True, *_static_spec(dec))
        if fq in _PARTIAL and dec.args and dotted(dec.args[0]) in _JIT:
            return (True, *_static_spec(dec))
    return False, set(), set()


def _traced_functions(mod: ParsedModule
                      ) -> list[tuple[ast.FunctionDef, set[str]]]:
    """Every function the device will trace, with its TRACED parameter
    names (static_argnums positions excluded — those stay Python).
    Cached on the module: all four trace rules share one walk."""
    cached = getattr(mod, "_traced_fns", None)
    if cached is not None:
        return cached
    # names referenced as jax.jit(fn, ...) / pl.pallas_call(kernel, ...)
    # — keeping the call-form's static_argnums/argnames
    wrapped: dict[str, tuple[set[int], set[str]]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and node.args:
            fq = dotted(node.func) or ""
            if fq in _JIT or fq.endswith("pallas_call"):
                target = node.args[0]
                if isinstance(target, ast.Name):
                    wrapped[target.id] = _static_spec(node) \
                        if fq in _JIT else (set(), set())
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        is_jit, nums, names = False, set(), set()
        for dec in node.decorator_list:
            is_jit, nums, names = _jit_decorator(dec)
            if is_jit:
                break
        if not is_jit:
            if node.name not in wrapped:
                continue
            nums, names = wrapped[node.name]
        a = node.args
        positional = [p.arg for p in a.posonlyargs + a.args]
        params = {p for i, p in enumerate(positional)
                  if i not in nums and p not in names}
        params.update(p.arg for p in a.kwonlyargs
                      if p.arg not in names)
        for extra in (a.vararg, a.kwarg):
            if extra is not None:
                params.add(extra.arg)
        out.append((node, params))
    mod._traced_fns = out
    return out


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _DeviceRule(Rule):
    def applies(self, path: str) -> bool:
        parts = path_parts(path)
        # obs/ joined in ISSUE 5: the tracing hooks sit beside jitted
        # hot paths, so the same trace-safety discipline applies there.
        # sim/ joined in ISSUE 8: scenario rounds run armed-tracer
        # spans around the same runtime paths the live stack jits
        return "ops" in parts or "serve" in parts or "obs" in parts \
            or "sim" in parts


@register
class TraceGlobalMutation(_DeviceRule):
    id = "trace-global-mutation"
    description = ("global/nonlocal statement inside a jit-traced "
                   "function body")
    hint = ("return the value from the traced function (or carry it "
            "through the functional state) instead of mutating "
            "enclosing scope at trace time")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for fn, _ in _traced_functions(mod):
            for node in ast.walk(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = ("global" if isinstance(node, ast.Global)
                            else "nonlocal")
                    out.append(self.finding(
                        mod, node,
                        f"`{kind} {', '.join(node.names)}` inside "
                        f"jit-traced `{fn.name}`: the mutation runs "
                        "once at trace time, not per call"))
        return out


@register
class TracePrint(_DeviceRule):
    id = "trace-print"
    description = "print() inside a jit-traced function body"
    hint = ("use jax.debug.print (prints per execution) or log "
            "outside the traced function; print() fires once at "
            "trace time with tracer reprs")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for fn, _ in _traced_functions(mod):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    out.append(self.finding(
                        mod, node,
                        f"print() inside jit-traced `{fn.name}` fires "
                        "at trace time only"))
        return out


_SYNC_METHODS = {"item", "tolist", "tobytes"}
_SYNC_BUILTINS = {"float", "int", "bool"}


@register
class TraceHostSync(_DeviceRule):
    id = "trace-host-sync"
    description = (".item()/.tolist()/.tobytes() or float/int/bool on "
                   "a traced value inside a jit body")
    hint = ("keep the value on device (jnp ops / astype); concretize "
            "only outside the traced function")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for fn, params in _traced_functions(mod):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _SYNC_METHODS:
                    out.append(self.finding(
                        mod, node,
                        f".{f.attr}() inside jit-traced `{fn.name}` "
                        "forces a host sync (fails on tracers)"))
                elif isinstance(f, ast.Name) \
                        and f.id in _SYNC_BUILTINS and node.args \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in params:
                    out.append(self.finding(
                        mod, node,
                        f"{f.id}({node.args[0].id}) concretizes a "
                        f"traced argument of `{fn.name}`"))
        return out


_NP_ROOTS = ("np.", "numpy.")


@register
class TraceHostTransfer(_DeviceRule):
    id = "trace-host-transfer"
    description = "np.* applied to a traced argument inside a jit body"
    hint = ("use the jnp equivalent on traced values; numpy calls "
            "pull the tracer to host (TracerArrayConversionError or a "
            "silent device->host transfer)")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for fn, params in _traced_functions(mod):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                fq = dotted(node.func) or ""
                if not fq.startswith(_NP_ROOTS):
                    continue
                touched = sorted(params & set().union(
                    *(_names_in(a) for a in node.args), *(
                        _names_in(kw.value) for kw in node.keywords))
                ) if (node.args or node.keywords) else []
                if touched:
                    out.append(self.finding(
                        mod, node,
                        f"{fq}(...) over traced argument(s) "
                        f"{', '.join(touched)} inside jit-traced "
                        f"`{fn.name}`"))
        return out


_INT_RANGES = {
    "uint8": (0, 2**8 - 1), "uint16": (0, 2**16 - 1),
    "uint32": (0, 2**32 - 1), "uint64": (0, 2**64 - 1),
    "int8": (-2**7, 2**7 - 1), "int16": (-2**15, 2**15 - 1),
    "int32": (-2**31, 2**31 - 1), "int64": (-2**63, 2**63 - 1),
}
_ARRAY_CTORS = {"array", "asarray", "full", "full_like"}


def _dtype_name(node: ast.AST | None) -> str | None:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _INT_RANGES else None
    name = dotted(node)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    return leaf if leaf in _INT_RANGES else None


_FOLD_OPS = {
    ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b, ast.Pow: lambda a, b: a ** b,
    ast.LShift: lambda a, b: a << b, ast.RShift: lambda a, b: a >> b,
    ast.BitOr: lambda a, b: a | b, ast.BitAnd: lambda a, b: a & b,
    ast.BitXor: lambda a, b: a ^ b, ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
}


def _const_value(node: ast.AST) -> int | None:
    """Fold a constant integer expression (handles ``2**40``-style
    literals); None when not statically an int."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_value(node.operand)
        return None if v is None else -v
    if isinstance(node, ast.BinOp) and type(node.op) in _FOLD_OPS:
        a, b = _const_value(node.left), _const_value(node.right)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.Pow) and (abs(a) > 2 ** 16
                                             or not 0 <= b < 256):
            return None          # keep folding cheap and exact
        try:
            return _FOLD_OPS[type(node.op)](a, b)
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
    return None


def _int_literals(node: ast.AST):
    """Statically-known ints inside a literal payload (scalar, folded
    constant expression, or list/tuple/set of those)."""
    value = _const_value(node)
    if value is not None:
        yield node, value
        return
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        for el in node.elts:
            yield from _int_literals(el)


@register
class DtypeOverflow(_DeviceRule):
    id = "dtype-overflow"
    description = ("integer literal outside the target dtype's range "
                   "in an explicit dtype construction")
    hint = ("the literal wraps silently on the narrow lane; widen the "
            "dtype or reduce the literal into range explicitly")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fq = dotted(node.func) or ""
            leaf = fq.rsplit(".", 1)[-1]
            payloads: list[ast.AST] = []
            dtype: str | None = None
            if leaf in _INT_RANGES and node.args:
                # np.uint8(x) / jnp.uint32(x) style cast
                dtype, payloads = leaf, [node.args[0]]
            elif leaf in _ARRAY_CTORS:
                # payload position: full/full_like(shape, VALUE, dtype)
                # vs array/asarray(VALUE, dtype)
                val_i = 1 if leaf in ("full", "full_like") else 0
                kw_dtype = next((kw.value for kw in node.keywords
                                 if kw.arg == "dtype"), None)
                pos_dtype = node.args[val_i + 1] \
                    if len(node.args) > val_i + 1 else None
                dtype = _dtype_name(kw_dtype if kw_dtype is not None
                                    else pos_dtype)
                if dtype is not None and len(node.args) > val_i:
                    payloads = [node.args[val_i]]
            if dtype is None:
                continue
            lo, hi = _INT_RANGES[dtype]
            for payload in payloads:
                for lit, value in _int_literals(payload):
                    if not lo <= value <= hi:
                        out.append(self.finding(
                            mod, lit,
                            f"literal {value} out of {dtype} range "
                            f"[{lo}, {hi}] in {fq}(...)"))
        return out
