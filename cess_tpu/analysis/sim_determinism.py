"""Determinism analyzers for the simulation harness (cess_tpu/sim).

The sim package's whole contract is bit-identical replay: every run
of a (seed, scenario) pair must produce the same event order, the
same finalized prefixes, the same SLO transitions. One stray wall
clock read or ``random`` draw breaks that silently — the replay tests
would flake instead of fail. These rules make the contract static:

- sim-wallclock : time.time/monotonic/perf_counter — AND time.sleep,
                  which is worse than nondeterministic in a sim: it
                  blocks the host for virtual-time that SimClock
                  should absorb
- sim-entropy   : random.* / np.random.* / os.urandom / uuid / secrets
                  — all entropy must come from SHA-256 streams over
                  the world seed (the ``_u64`` idiom)

The family also covers the flight recorder's retention-decision code
(obs/flight.py + obs/incident.py, ISSUE 9), the fleet plane
(obs/fleet.py, ISSUE 12), the profile plane (obs/profile.py,
ISSUE 13) and the chain plane (obs/chainwatch.py, ISSUE 14): "same
seed retains the same traces, bundles the same incidents, federates
the same fleet witness, profiles the same counters and logs the same
chain anomalies" is the identical replay contract, so a wall-clock
read or entropy draw in a pin decision, a scrape round or a watchdog
window is the same class of bug as one in a sim world. (The profile plane's
timings are measured by its serve-layer CALLERS and passed in — the
module itself never touches a clock.)
"""
from __future__ import annotations

import ast

from .core import Finding, ParsedModule, Rule, dotted, path_parts, register

_WALLCLOCK = {"time.time", "time.time_ns", "time.monotonic",
              "time.monotonic_ns", "time.perf_counter",
              "time.perf_counter_ns", "time.sleep",
              "datetime.now", "datetime.utcnow",
              "datetime.datetime.now", "datetime.datetime.utcnow"}
_ENTROPY = {"os.urandom", "uuid.uuid4", "uuid.uuid1"}
_ENTROPY_PREFIXES = ("random.", "np.random.", "numpy.random.",
                     "secrets.")


class _SimRule(Rule):
    def applies(self, path: str) -> bool:
        parts = path_parts(path)
        if "sim" in parts:
            return True
        # the regenerating repair plane (ISSUE 15): its coefficient
        # and matrix constructions feed the repair storm's replay
        # contract, so a clock read or entropy draw there would break
        # bit-identical replays just like one inside sim/
        # ops/xor_sched.py + ops/rs_xor.py (ISSUE 18): the schedule
        # witness is canonical bytes — same matrix, byte-identical
        # program on every host — so wallclock/entropy/dict-order
        # anywhere in compile or execute breaks that contract
        if "ops" in parts and parts[-1] in ("regen.py", "xor_sched.py",
                                            "rs_xor.py"):
            return True
        # the remediation plane's action journal is part of the replay
        # witness (same seed => byte-identical action log), so it is
        # held to the sim contract: decisions advance on observation
        # count only, never a clock read or an entropy draw
        if "serve" in parts and parts[-1] == "remediate.py":
            return True
        # the retention layer, the fleet plane, the profile plane,
        # the chain plane and the custody plane make seeded decisions
        # under the same replay contract as sim worlds (the custody
        # ledger log + margin fold is the eighth witness stream)
        return "obs" in parts and parts[-1] in ("flight.py",
                                                "incident.py",
                                                "fleet.py",
                                                "profile.py",
                                                "chainwatch.py",
                                                "custody.py")


@register
class SimWallclock(_SimRule):
    id = "sim-wallclock"
    description = ("wall-clock read or blocking sleep in the "
                   "simulation harness")
    hint = ("use the world's SimClock (now()/sleep()) or schedule an "
            "EventQueue event — virtual time must be the only time "
            "the sim observes")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            fq = dotted(node)
            if fq in _WALLCLOCK:
                out.append(self.finding(
                    mod, node,
                    f"`{fq}` reads (or blocks on) the wall clock in "
                    "the deterministic sim"))
        return out


@register
class SimEntropy(_SimRule):
    id = "sim-entropy"
    description = "OS / library entropy source in the simulation harness"
    hint = ("derive every draw from a SHA-256 stream over the world "
            "seed (world.u64/_u64), so the same seed replays the "
            "same world")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            fq = dotted(node)
            if fq is None:
                continue
            if fq in _ENTROPY or fq.startswith(_ENTROPY_PREFIXES):
                out.append(self.finding(
                    mod, node,
                    f"`{fq}` is fresh entropy — a same-seed replay "
                    "would diverge"))
        return out
