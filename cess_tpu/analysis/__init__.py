"""cesslint: AST-based static analysis for the cess_tpu codebase.

Three rule families over one shared parse (core.py):

- trace-safety (trace_safety.py)      — ops/, serve/
- lock-discipline (lock_discipline.py) — serve/, node/
- consensus-determinism (determinism.py) — chain/

CLI: ``python tools/cesslint.py [paths] [--rule ID] [--json]
[--fix-hints] [--baseline FILE] [--write-baseline]``. Gate:
tests/test_lint.py (tier-1). Suppress a single true positive with
``# cesslint: disable=<rule-id>`` on (or directly above) the line;
bulk legacy debt goes in tools/cesslint_baseline.json.
"""
from .core import (Finding, LintResult, ParsedModule, Rule, all_rules,
                   apply_baseline, lint_modules, lint_paths, lint_source,
                   load_baseline, write_baseline)

__all__ = [
    "Finding", "LintResult", "ParsedModule", "Rule", "all_rules",
    "apply_baseline", "lint_modules", "lint_paths", "lint_source",
    "load_baseline", "write_baseline",
]
