"""cesslint: AST-based static analysis for the cess_tpu codebase.

Rule families over one shared parse (core.py) and one shared
interprocedural pass (flow.py — call graph, thread roots, taint):

- trace-safety (trace_safety.py)      — ops/, serve/
- lock-discipline (lock_discipline.py) — serve/, node/
- consensus-determinism (determinism.py) — chain/
- sim-determinism (sim_determinism.py) — sim/, obs/ planes
- span-balance (span_balance.py)       — serve/, node/, obs/
- witness-purity (witness_purity.py)   — package-wide taint flow
- race (race.py)                       — cross-thread lock sets
- seam-cost (seam_cost.py)             — zero-cost hook guards

CLI: ``python tools/cesslint.py [paths] [--rule ID] [--json]
[--fix-hints] [--baseline FILE] [--write-baseline]``. Gate:
tests/test_lint.py (tier-1). Suppress a single true positive with
``# cesslint: disable=<rule-id>`` on (or directly above) the line;
bulk legacy debt goes in tools/cesslint_baseline.json.
"""
from .core import (Directive, Finding, LintResult, ParsedModule, Rule,
                   all_rules, apply_baseline, lint_modules, lint_paths,
                   lint_source, load_baseline, sarif_report,
                   write_baseline)

__all__ = [
    "Directive", "Finding", "LintResult", "ParsedModule", "Rule",
    "all_rules", "apply_baseline", "lint_modules", "lint_paths",
    "lint_source", "load_baseline", "sarif_report", "write_baseline",
]
