"""Consensus-determinism analyzers for the runtime pallets (chain/).

Every replica must compute bit-identical state transitions from the
same block stream. Three bug classes break that silently:

- iterating a ``set`` (hash order — randomized per process for
  bytes/str keys) or a ``dict`` (insertion order — divergent when
  replicas built the map along different paths) on a path that feeds
  hashing, state roots, or extrinsic application;
- reading the wall clock or an OS entropy source inside a state
  transition (replicas disagree; replay disagrees with live
  execution);
- float arithmetic (platform-dependent rounding; the reference
  runtime is integer-only for exactly this reason).

Rules:
- consensus-unordered-iter : for/comprehension over .keys()/.values()/
                             .items()/set(...) without sorted(...)
                             (order-insensitive folds like
                             sum()/min()/max()/any()/all() are exempt)
- consensus-wallclock      : time.time / random.* / os.urandom /
                             datetime.now / uuid4 in a chain module
- consensus-float          : float literal, true division, or
                             float(...) in a chain module
"""
from __future__ import annotations

import ast

from .core import Finding, ParsedModule, Rule, dotted, path_parts, register


class _ChainRule(Rule):
    def applies(self, path: str) -> bool:
        return "chain" in path_parts(path)


# -- unordered iteration ------------------------------------------------------
_UNORDERED_METHODS = {"keys", "values", "items"}
_WRAP_TRANSPARENT = {"list", "tuple", "iter", "reversed", "enumerate"}
_ORDER_INSENSITIVE = {"sorted", "sum", "min", "max", "any", "all", "len",
                      "set", "frozenset", "dict", "Counter"}


_CONTAINER_CTORS = {"dict", "set", "frozenset", "defaultdict", "Counter"}


def _local_containers(scope: ast.AST) -> set[str]:
    """Names in this scope assigned ONLY from dict/set displays,
    comprehensions, or dict()/set()-style constructors — cheap local
    inference so bare ``for k in d:`` is caught, not just
    ``d.items()``. A name also assigned from anything else is
    ambiguous and dropped."""
    container: set[str] = set()
    other: set[str] = set()
    for node in ast.walk(scope):
        if node is not scope and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue            # nested scopes infer separately
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        is_container = isinstance(
            value, (ast.Dict, ast.Set, ast.DictComp, ast.SetComp)) \
            or (isinstance(value, ast.Call)
                and (dotted(value.func) or "").rsplit(".", 1)[-1]
                in _CONTAINER_CTORS)
        for t in targets:
            if isinstance(t, ast.Name):
                (container if is_container else other).add(t.id)
    return container - other


def _unordered_root(expr: ast.AST,
                    containers: set[str] = frozenset()) -> ast.AST | None:
    """The unordered set/dict-view subexpression an iteration order
    depends on, or None if the expression has a defined order."""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return expr
    if isinstance(expr, ast.Name) and expr.id in containers:
        return expr
    if isinstance(expr, ast.Call):
        fq = dotted(expr.func) or ""
        leaf = fq.rsplit(".", 1)[-1]
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in _UNORDERED_METHODS \
                and not expr.args:
            # a dict DISPLAY iterates in source order — deterministic
            if isinstance(expr.func.value, ast.Dict):
                return None
            return expr
        if leaf in ("set", "frozenset"):
            return expr
        if leaf in _WRAP_TRANSPARENT and expr.args:
            return _unordered_root(expr.args[0], containers)
        if leaf == "zip":
            for a in expr.args:
                r = _unordered_root(a, containers)
                if r is not None:
                    return r
    return None


def _scope_nodes(scope: ast.AST):
    """Nodes of one scope, not descending into nested functions."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


@register
class UnorderedIter(_ChainRule):
    id = "consensus-unordered-iter"
    description = ("set/dict iteration without sorted() in a consensus "
                   "module")
    hint = ("wrap the iterable in sorted(...) (key=repr for "
            "heterogeneous keys), or suppress with a comment proving "
            "the consumer is order-independent")

    def check(self, mod: ParsedModule) -> list[Finding]:
        # comprehensions that are the direct argument of an
        # order-insensitive fold (sum(x for ...), sorted([... for ...]))
        exempt: set[ast.AST] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fq = dotted(node.func) or ""
                if fq.rsplit(".", 1)[-1] in _ORDER_INSENSITIVE:
                    for a in node.args:
                        exempt.add(a)
        out = []
        scopes = [mod.tree] + [
            n for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            containers = _local_containers(scope)
            for node in _scope_nodes(scope):
                sites: list[tuple[ast.AST, ast.AST]] = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    sites.append((node.iter, node))
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    if node in exempt:
                        continue
                    for gen in node.generators:
                        sites.append((gen.iter, node))
                for iterable, at in sites:
                    root = _unordered_root(iterable, containers)
                    if root is None:
                        continue
                    desc = ast.unparse(root) if hasattr(ast, "unparse") \
                        else "unordered iterable"
                    out.append(self.finding(
                        mod, at,
                        f"iteration over `{desc}` has no canonical "
                        "order in a consensus module"))
        return out


# -- wall clock / entropy -----------------------------------------------------
_WALLCLOCK = {"time.time", "time.time_ns", "time.monotonic",
              "time.monotonic_ns", "time.perf_counter",
              "datetime.now", "datetime.utcnow",
              "datetime.datetime.now", "datetime.datetime.utcnow",
              "os.urandom", "uuid.uuid4", "uuid.uuid1"}
_WALLCLOCK_PREFIXES = ("random.", "np.random.", "numpy.random.",
                       "secrets.")


@register
class Wallclock(_ChainRule):
    id = "consensus-wallclock"
    description = ("wall-clock or process-entropy source in a "
                   "consensus module")
    hint = ("derive from on-chain inputs instead: block number, "
            "randomness pallet output, or a seeded deterministic "
            "stream")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            fq = dotted(node)
            if fq is None:
                continue
            if fq in _WALLCLOCK or fq.startswith(_WALLCLOCK_PREFIXES):
                out.append(self.finding(
                    mod, node,
                    f"`{fq}` is nondeterministic across replicas"))
        return out


# -- float arithmetic ---------------------------------------------------------
@register
class FloatArithmetic(_ChainRule):
    id = "consensus-float"
    description = ("float literal, true division, or float() in a "
                   "consensus module")
    hint = ("use integer arithmetic: `//` with an explicit rounding "
            "rule, or fixed-point (PER_BILL-style) ratios")

    def check(self, mod: ParsedModule) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, float):
                out.append(self.finding(
                    mod, node,
                    f"float literal {node.value!r} in a consensus "
                    "module"))
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
                out.append(self.finding(
                    mod, node,
                    "true division `/` produces platform-rounded "
                    "floats"))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "float":
                out.append(self.finding(
                    mod, node, "float(...) in a consensus module"))
        return out
