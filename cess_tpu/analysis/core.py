"""cesslint core: findings, suppressions, baseline, and the runner.

The analysis framework behind ``tools/cesslint.py`` (gated in tier-1
by tests/test_lint.py). Three rule families plug into it:

- trace_safety.py    — side effects / host sync inside ``@jax.jit`` or
                       pallas-called bodies, dtype-literal discipline
                       (ops/, serve/);
- lock_discipline.py — guarded-attribute inference, blocking calls
                       under a held lock, lock-order cycles
                       (serve/, node/);
- determinism.py     — unordered set/dict iteration, wall-clock /
                       randomness / float arithmetic in consensus
                       state-transition modules (chain/).

Design constraints (ISSUE 2): each file is parsed ONCE and the AST is
fanned out to every applicable rule; findings carry ``file:line``, a
rule id and a fix hint; a true positive is silenced either by fixing
it, by an inline ``# cesslint: disable=<rule>`` comment on the
offending line (or the line above), or by the checked-in baseline
file (``tools/cesslint_baseline.json``) for bulk debt.

Baseline identity is LINE-NUMBER INDEPENDENT: a finding's fingerprint
is (rule, path, normalized source snippet), counted — so unrelated
edits shifting line numbers do not invalidate the baseline, while a
new instance of a baselined pattern in the same file still needs its
own entry.
"""
from __future__ import annotations

import ast
import collections
import dataclasses
import io
import json
import os
import re
import tokenize
from pathlib import PurePosixPath
from typing import Iterable, Iterator


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit: where, which rule, what, and how to fix it."""

    rule: str       # rule id, e.g. "lock-unguarded-write"
    path: str       # repo-relative posix path
    line: int       # 1-based
    col: int        # 0-based
    message: str
    fix_hint: str = ""
    snippet: str = ""   # stripped source line (fingerprint component)

    def fingerprint(self) -> str:
        """Line-number-independent identity used by the baseline."""
        return f"{self.rule}|{self.path}|{' '.join(self.snippet.split())}"

    def format(self, hints: bool = False) -> str:
        s = f"{self.path}:{self.line}:{self.col + 1}: " \
            f"[{self.rule}] {self.message}"
        if hints and self.fix_hint:
            s += f"\n    hint: {self.fix_hint}"
        return s

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """One analyzer. Subclasses set ``id``/``description``/``hint``
    and implement ``check`` (per-module) and/or ``check_project``
    (cross-module, e.g. lock-order cycles)."""

    id: str = ""
    description: str = ""
    hint: str = ""

    def applies(self, path: str) -> bool:
        return True

    def check(self, mod: "ParsedModule") -> list[Finding]:
        return []

    def check_project(self,
                      mods: "list[ParsedModule]") -> list[Finding]:
        return []

    # -- helpers shared by rule implementations -------------------------
    def finding(self, mod: "ParsedModule", node: ast.AST, message: str,
                hint: str | None = None) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=self.id, path=mod.path, line=line,
                       col=getattr(node, "col_offset", 0),
                       message=message,
                       fix_hint=self.hint if hint is None else hint,
                       snippet=mod.line(line))


_RULES: dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator: instantiate + add to the global rule registry."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in _RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    _RULES[rule.id] = rule
    return cls


def all_rules() -> dict[str, Rule]:
    """The registry with every rule family imported."""
    from . import (determinism, lock_discipline, race,  # noqa: F401
                   seam_cost, sim_determinism, span_balance,
                   trace_safety, witness_purity)

    return dict(_RULES)


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def path_parts(path: str) -> tuple[str, ...]:
    return PurePosixPath(path.replace(os.sep, "/")).parts


# ---------------------------------------------------------------------------
# suppressions:  # cesslint: disable=<rule>[,<rule>...]   (or bare
# "disable" for all rules). A comment suppresses its own line; a
# comment alone on a line also suppresses the next line.
# ---------------------------------------------------------------------------
_ALL = "*"


@dataclasses.dataclass(frozen=True)
class Directive:
    """One inline ``# cesslint: disable=...`` comment — kept as an
    object (not just a line->rules map) so the stale-suppression
    audit can ask, per directive and per rule id, whether anything
    was actually silenced."""
    line: int                    # the comment's own line
    covers: tuple                # line numbers it suppresses
    rules: frozenset             # rule ids, or {_ALL}


def _parse_directives(source: str) -> list[Directive]:
    out: list[Directive] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            text = tok.string.lstrip("#").strip()
            if not text.startswith("cesslint:"):
                continue
            directive = text[len("cesslint:"):].strip()
            if not directive.startswith("disable"):
                continue
            rest = directive[len("disable"):].strip()
            if rest.startswith("="):
                # the rule list is the contiguous comma-separated ids
                # right after "="; trailing prose ("— why...") is fine
                m = re.match(r"\s*([A-Za-z0-9_\-]+"
                             r"(?:\s*,\s*[A-Za-z0-9_\-]+)*)", rest[1:])
                if not m:
                    continue
                rules = frozenset(r.strip()
                                  for r in m.group(1).split(","))
            elif rest == "":
                rules = frozenset({_ALL})
            else:
                # "disabled", "disable-next-line", ...: an unknown
                # directive must NOT silently blanket-suppress
                continue
            covers = [tok.start[0]]
            if tok.line[:tok.start[1]].strip() == "":
                covers.append(tok.start[0] + 1)   # own-line comment
            out.append(Directive(line=tok.start[0],
                                 covers=tuple(covers), rules=rules))
    except tokenize.TokenError:
        pass
    return out


def _parse_suppressions(source: str) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    for d in _parse_directives(source):
        for ln in d.covers:
            out.setdefault(ln, set()).update(d.rules)
    return out


class ParsedModule:
    """One source file, parsed once; every rule reads the same AST."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.directives = _parse_directives(source)
        self.suppressions: dict[int, set[str]] = {}
        for d in self.directives:
            for ln in d.covers:
                self.suppressions.setdefault(ln, set()).update(d.rules)

    def line(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line)
        if not rules:
            return False
        return _ALL in rules or finding.rule in rules


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------
def iter_python_files(paths: Iterable[str],
                      root: str | None = None) -> Iterator[tuple[str, str]]:
    """Yield (abs_path, repo_relative_path) for every .py under paths."""
    root = os.path.abspath(root or os.getcwd())
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            files = [ap]
        else:
            files = sorted(
                os.path.join(dirpath, f)
                for dirpath, dirs, names in os.walk(ap)
                if "__pycache__" not in dirpath
                for f in names if f.endswith(".py"))
        for f in files:
            rel = os.path.relpath(f, root)
            if rel.startswith(".."):
                rel = f
            yield f, rel.replace(os.sep, "/")


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]             # active (not inline-suppressed)
    suppressed: list[Finding]           # silenced by inline comments
    errors: list[str]                   # unparseable files
    files: int = 0
    # (path, comment line, rule ids that silenced nothing) — only
    # meaningful when every rule family ran (the CLI forbids
    # --audit-suppressions on a --rule-narrowed scan)
    stale_suppressions: list = dataclasses.field(default_factory=list)


def lint_modules(mods: list[ParsedModule],
                 rules: dict[str, Rule] | None = None) -> LintResult:
    rules = rules if rules is not None else all_rules()
    by_path = {m.path: m for m in mods}
    raw: list[Finding] = []
    for mod in mods:
        for rule in rules.values():
            if rule.applies(mod.path):
                raw.extend(rule.check(mod))
    for rule in rules.values():
        applicable = [m for m in mods if rule.applies(m.path)]
        if applicable:
            raw.extend(rule.check_project(applicable))
    active, suppressed = [], []
    for f in raw:
        mod = by_path.get(f.path)
        (suppressed if mod is not None and mod.is_suppressed(f)
         else active).append(f)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    stale = []
    for mod in mods:
        for d in mod.directives:
            silenced = {f.rule for f in suppressed
                        if f.path == mod.path and f.line in d.covers
                        and (_ALL in d.rules or f.rule in d.rules)}
            if _ALL in d.rules:
                if not silenced:
                    stale.append((mod.path, d.line, (_ALL,)))
                continue
            unused = sorted(d.rules - silenced)
            if unused:
                stale.append((mod.path, d.line, tuple(unused)))
    return LintResult(findings=active, suppressed=suppressed,
                      errors=[], files=len(mods), stale_suppressions=stale)


def lint_source(source: str, path: str,
                rules: dict[str, Rule] | None = None) -> LintResult:
    """Analyze one in-memory snippet as if it lived at ``path`` (the
    path decides which rule families apply) — the fixture-test entry."""
    return lint_modules([ParsedModule(path, source)], rules)


def lint_paths(paths: Iterable[str],
               rules: dict[str, Rule] | None = None,
               root: str | None = None) -> LintResult:
    mods, errors = [], []
    for abspath, rel in iter_python_files(paths, root):
        try:
            with open(abspath, encoding="utf-8") as fh:
                mods.append(ParsedModule(rel, fh.read()))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{rel}: {e}")
    result = lint_modules(mods, rules)
    result.errors = errors
    return result


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> collections.Counter:
    """fingerprint -> allowed count. Missing file = empty baseline."""
    if not os.path.exists(path):
        return collections.Counter()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return collections.Counter({e["fingerprint"]: int(e.get("count", 1))
                                for e in data.get("findings", [])})


def write_baseline(findings: list[Finding], path: str) -> None:
    counts = collections.Counter(f.fingerprint() for f in findings)
    data = {"findings": [{"fingerprint": fp, "count": n}
                         for fp, n in sorted(counts.items())]}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: list[Finding],
                   baseline: collections.Counter,
                   ) -> tuple[list[Finding], list[Finding]]:
    """Split findings into (new, baselined)."""
    budget = collections.Counter(baseline)
    new, matched = [], []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
            matched.append(f)
        else:
            new.append(f)
    return new, matched


# ---------------------------------------------------------------------------
# SARIF 2.1.0 export (code-review rendering: GitHub code scanning,
# VS Code SARIF viewer)
# ---------------------------------------------------------------------------
SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def sarif_report(findings: list[Finding],
                 rules: dict[str, Rule] | None = None) -> dict:
    """The findings as a SARIF 2.1.0 log (one run, one driver). Rule
    metadata (description + fix hint) rides in the driver's rules
    array; each result carries ruleId, file/line/col and the
    baseline fingerprint."""
    rules = rules if rules is not None else all_rules()
    used = sorted({f.rule for f in findings})
    index = {rid: i for i, rid in enumerate(used)}
    rule_meta = []
    for rid in used:
        rule = rules.get(rid)
        entry: dict = {"id": rid}
        if rule is not None:
            entry["shortDescription"] = {"text": rule.description}
            if rule.hint:
                entry["help"] = {"text": rule.hint}
        rule_meta.append(entry)
    results = []
    for f in findings:
        message = f.message if not f.fix_hint \
            else f"{f.message} (fix: {f.fix_hint})"
        results.append({
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
            }],
            "partialFingerprints": {
                "cesslint/v1": f.fingerprint(),
            },
        })
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "cesslint",
                "informationUri":
                    "https://github.com/cess-tpu/cess-tpu",
                "rules": rule_meta,
            }},
            "results": results,
        }],
    }
