"""seam-cost: zero-cost-when-off hook seams must really be zero-cost.

Every observability/resilience plane hangs off the hot path through
one idiom — load a carrier, check it, bail:

    rec = _RECORDER            # one module-global load
    if rec is None:            # one check
        return                 # disarmed: nothing allocated, nothing
    rec.note(...)              #           formatted, nothing called

The contract is repeated in a dozen docstrings ("one load, one
check") but until now nothing verified it, and the failure mode is
silent: an f-string, a dict literal or a helper call drifting above
the guard taxes EVERY production request to feed a hook that is off.
This rule recognizes the guard shape structurally and audits the
statements the disarmed path executes before it.

Carriers (the seam registry, documented in README):
- module globals named ``_ALLCAPS`` (``_RECORDER``, ``_TRACER``,
  ``_PLAN``) and no-arg ``.get()`` reads off them (the ContextVar
  idiom ``_CURRENT.get()`` — a load-equivalent);
- optional plane attributes read off ``self``: the ``SEAM_ATTRS``
  registry (``self.slo``, ``self.adaptive``, ``self.profile``, ...).

Before the guard only docstrings and pure-load binds (name,
constant, attribute chain, carrier ``.get()``) may run; any
allocation (container/tuple literal), f-string, arithmetic or call
is a finding.  Functions that do real work before a late guard are
NOT seams and are skipped — the audit stops at the first
non-bind statement, so ``self._drain(); rec = _RECORDER; ...`` is
legitimate armed-and-disarmed work, while ``payload = f"{a}:{b}"``
before the guard is the bug.

Registered hooks (``REGISTERED_HOOKS``) — the seams production code
actually calls — must additionally HAVE a conforming guard at all.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, ParsedModule, Rule, register

#: module-global seam carriers: _RECORDER, _TRACER, _PLAN, _CURRENT...
CARRIER_RE = re.compile(r"^_[A-Z][A-Z0-9_]*$")
#: optional plane attributes consumers guard with ``x = self.<attr>``
SEAM_ATTRS = frozenset({
    "slo", "adaptive", "profile", "tracer", "recorder", "flight",
    "fleet", "chainwatch", "remediation", "custody", "watch",
    "admission", "resilience", "plan",
})
#: (path suffix, function) pairs that MUST carry the guard — the
#: hooks every subsystem calls unconditionally on hot paths
REGISTERED_HOOKS = frozenset({
    ("obs/flight.py", "note"),
    ("obs/trace.py", "span"),
    ("obs/trace.py", "current_span"),
    ("obs/trace.py", "event"),
    ("obs/trace.py", "context"),
    ("resilience/faults.py", "_fire"),
})


def _is_carrier(node: ast.AST, binds: dict[str, bool]) -> bool:
    """Is this expression a seam carrier read (directly, or a local
    bound from one)?"""
    if isinstance(node, ast.Name):
        return CARRIER_RE.match(node.id) is not None \
            or binds.get(node.id, False)
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name):
        if node.value.id == "self" and node.attr in SEAM_ATTRS:
            return True
        return CARRIER_RE.match(node.value.id) is not None
    return False


def _pure_load(node: ast.AST) -> bool:
    """Name / constant / dotted attribute chain — no allocation, no
    call, no formatting."""
    if isinstance(node, (ast.Name, ast.Constant)):
        return True
    if isinstance(node, ast.Attribute):
        return _pure_load(node.value)
    return False


def _carrier_get(node: ast.AST) -> bool:
    """``_CURRENT.get()`` — the no-arg ContextVar read, one load
    equivalent."""
    return (isinstance(node, ast.Call)
            and not node.args and not node.keywords
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and CARRIER_RE.match(node.func.value.id) is not None)


def _allowed_bind(rhs: ast.AST) -> bool:
    return _pure_load(rhs) or _carrier_get(rhs)


def _guard_test(test: ast.AST) -> tuple[ast.AST, bool] | None:
    """(tested expr, negated) for ``X is None`` / ``not X`` (negated:
    the body is the DISARMED path) or ``X is not None`` / ``X``
    (body is the armed path)."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return test.left, True
        if isinstance(test.ops[0], ast.IsNot):
            return test.left, False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return test.operand, True
    if isinstance(test, (ast.Name, ast.Attribute)):
        return test, False
    return None


def _cheap_return(body: list[ast.stmt]) -> bool:
    """The disarmed path: a single return of nothing / a constant / a
    pure load (``return NOOP_SPAN``)."""
    if len(body) != 1 or not isinstance(body[0], ast.Return):
        return False
    value = body[0].value
    return value is None or _pure_load(value)


@register
class SeamCost(Rule):
    id = "seam-cost"
    description = ("work (allocation / f-string / call) on the "
                   "disarmed path before a zero-cost seam guard")
    hint = ("the disarmed path must be one carrier load plus one "
            "None/truthiness check — move every allocation, format "
            "and call below the guard so an un-armed hook costs "
            "nothing")

    def applies(self, path: str) -> bool:
        return True

    def check(self, mod: ParsedModule) -> list[Finding]:
        out: list[Finding] = []
        hooks_due = {name for (suffix, name) in REGISTERED_HOOKS
                     if mod.path.endswith(suffix)}
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            guarded = self._audit(mod, fn, out)
            if guarded and fn.name in hooks_due:
                hooks_due.discard(fn.name)
            elif not guarded and fn.name in hooks_due:
                out.append(self.finding(
                    mod, fn,
                    f"registered zero-cost hook `{fn.name}` has no "
                    "one-load + None-check guard at the top — every "
                    "call pays full cost even when the plane is "
                    "disarmed"))
                hooks_due.discard(fn.name)
        return out

    def _audit(self, mod: ParsedModule,
               fn: ast.FunctionDef, out: list[Finding]) -> bool:
        """Walk the statement prefix; returns True when a conforming
        seam guard was found (after reporting any expensive
        statements the disarmed path would execute first)."""
        body = list(fn.body)
        if body and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            body = body[1:]                       # docstring
        binds: dict[str, bool] = {}               # name -> carrier?
        prefix: list[tuple[ast.stmt, ast.AST]] = []   # (stmt, rhs)
        for i, stmt in enumerate(body):
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                rhs = stmt.value
                binds[stmt.targets[0].id] = (
                    _is_carrier(rhs, binds) or _carrier_get(rhs))
                prefix.append((stmt, rhs))
                continue
            if isinstance(stmt, ast.If):
                parsed = _guard_test(stmt.test)
                if parsed is None:
                    return False
                tested, negated = parsed
                if not _is_carrier(tested, binds):
                    return False
                if negated:                # if X is None: return ...
                    seam = _cheap_return(stmt.body)
                else:                      # if X is not None: <body>
                    seam = i == len(body) - 1 and not stmt.orelse
                if not seam:
                    return False
                carrier = ast.unparse(tested)
                for bstmt, rhs in prefix:
                    if not _allowed_bind(rhs):
                        out.append(self.finding(
                            mod, bstmt,
                            f"`{ast.unparse(bstmt.targets[0])} = "
                            f"{ast.unparse(rhs)}` runs before the "
                            f"disarmed-seam guard on `{carrier}` — "
                            "this work is paid even when the hook "
                            "is off"))
                return True
            return False                   # real work: not a seam
        return False
