"""witness-purity: taint tracking from nondeterminism sources into
replay-witness sinks.

The house replay contract — "same seed ⇒ byte-identical witness" — is
re-proven per PR by hand-written replay drills, but the property is
static: a witness byte can only diverge if a nondeterministic VALUE
(wall clock, entropy, thread id, object address, hash-order escape)
flows into the bytes the witness serializes. This rule makes that a
compile-time property: the flow layer's taint lattice (flow.py)
propagates sources through calls, parameters, fields and containers
to a fixpoint, and any taint reaching a witness sink is an error.

Sinks (the taint-sink registry, documented in README):
- the RETURN value of any function named ``witness``, ``canon``,
  ``transition_log``, ``fired_log`` or ``placement_log`` — the
  serialization points every replay assertion compares;
- APPENDS into journal-shaped fields (attribute name containing
  ``journal``, ``transition``, ``fired``, ``placement`` or
  ``witness``) — the count-sequenced logs those methods read back.

Only explicit dataflow counts (see flow.py): a detector whose
*decisions* are count-sequenced but whose observation timing is
wall-clock driven is the house design, not a finding.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, ParsedModule, Rule, register
from .flow import ORDER_SOURCE, Taint, _TaintPass, flow_graph

#: functions whose return value IS witness bytes
SINK_FUNCS = frozenset({"witness", "canon", "transition_log",
                        "fired_log", "placement_log"})
#: fields that hold count-sequenced witness journals
SINK_FIELD_RE = re.compile(
    r"journal|transition|fired|placement|witness", re.IGNORECASE)
#: container-mutating methods that feed a sink field
_ADDERS = frozenset({"append", "appendleft", "extend", "add", "insert"})


class _SinkPass(_TaintPass):
    """A reporting pass over one function: re-evaluates taint with
    the converged facts and records tainted sink touches."""

    def __init__(self, graph, fi, hits: list):
        super().__init__(graph, fi)
        self.hits = hits                 # (node, kind, taints)
        self.sink_fn = fi.name in SINK_FUNCS
        self.aliases: dict[str, str] = {}    # local -> sink field attr

    def _stmt(self, node):
        # track local aliases of sink fields BEFORE evaluating the
        # statement (``journal = self._journals.get(...)``)
        if isinstance(node, ast.Assign):
            attr = _sink_field_read(node.value)
            if attr is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.aliases[t.id] = attr
        if isinstance(node, ast.Return) and node.value is not None \
                and self.sink_fn:
            t = self._expr(node.value)
            if t:
                self.hits.append((node, "return", t))
        super()._stmt(node)

    def _call(self, node):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _ADDERS:
            recv = node.func.value
            attr = None
            if isinstance(recv, ast.Name):
                attr = self.aliases.get(recv.id)
            else:
                attr = _sink_field_read(recv)
            if attr is not None and SINK_FIELD_RE.search(attr):
                t = set()
                for a in node.args:
                    t |= self._expr(a)
                if t:
                    self.hits.append((node, f"append to self.{attr}", t))
        return super()._call(node)


def _sink_field_read(expr: ast.AST) -> str | None:
    """The self-attr a (possibly subscripted/called) expression reads
    through, when that attr looks like a witness journal."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self" \
                and SINK_FIELD_RE.search(sub.attr):
            return sub.attr
    return None


@register
class WitnessPurity(Rule):
    id = "witness-purity"
    description = ("nondeterministic value (wall clock / entropy / "
                   "thread id / id() / hash-order escape) flows into "
                   "a replay-witness sink")
    hint = ("witnesses must be pure functions of the seed and the "
            "count-sequenced event stream: derive the value from a "
            "sequence counter or a SHA-256 stream over the seed, or "
            "keep the timing field OUT of the witnessed bytes")

    def applies(self, path: str) -> bool:
        return True              # package-wide: the flow graph needs
        #                          every module to resolve calls

    def check_project(self, mods: list[ParsedModule]) -> list[Finding]:
        graph = flow_graph(mods)
        by_path = {m.path: m for m in mods}
        out: list[Finding] = []
        seen: set[tuple] = set()
        for fi in graph.functions.values():
            hits: list = []
            _SinkPass(graph, fi, hits).run()
            mod = by_path.get(fi.path)
            if mod is None:
                continue
            qual = f"{fi.cls}.{fi.name}" if fi.cls else fi.name
            for node, kind, taints in hits:
                origin = _pick(taints)
                key = (fi.fqid, getattr(node, "lineno", 0),
                       origin.source)
                if key in seen:
                    continue
                seen.add(key)
                what = "returns a value" if kind == "return" \
                    else f"{kind} records a value"
                why = "iteration order of an unordered container " \
                      "escapes into the witness" \
                    if origin.source == ORDER_SOURCE \
                    else f"influenced by {origin.describe()}"
                out.append(self.finding(
                    mod, node,
                    f"witness sink `{qual}` {what} {why} — same-seed "
                    "replays can diverge byte-for-byte"))
        return out


def _pick(taints: set[Taint]) -> Taint:
    """Deterministic representative origin (wallclock-style sources
    outrank order taint; then lexicographic)."""
    return min(taints, key=lambda t: (t.source == ORDER_SOURCE,
                                      t.source, t.path, t.line))
