"""Remediation plane: detector edges -> journaled recovery actions.

Five observability planes (SLO board, flight recorder, profile
watchdog, fleet stragglers, chainwatch anomalies) end at an incident
bundle for a human to read. This module closes the control loop: a
count-sequenced policy engine that subscribes to the SAME flight-note
edges those detectors already announce and maps each one to a concrete
action through seams that already exist:

- perf regression     -> pin the affected class to the reference
                         backend (``HealthMonitor.hold_open``), then
                         re-probe/``release`` on recovery;
- breaker trip        -> latch the tripped monitor held (stop paying
                         probe failures), re-probe after a cooldown;
- fleet straggler     -> quarantine the lane: hold its per-lane
                         breakers so DevicePool placement avoids it
                         and in-flight work drains to siblings;
- chain equivocation  -> file ``offences.report_equivocation``
                         on-chain from the node's own vote evidence;
- repair-ingress      -> flip ``MinerAgent.repair_mode`` between
  regression              "symbols" and whole-fragment by the measured
                         bytes-per-recovered-byte ratio.

Every decision goes through a declarative :class:`Policy` table
(trigger edge -> guard -> action -> release condition) with per-policy
count-based rate limits and cooldowns, and lands in a bounded
append-only action journal that is part of the replay witness: the
plane never reads a clock and never draws entropy, decisions advance
on observation count alone, so same seed => byte-identical
``witness()`` action logs. ``dry_run=True`` journals every decision
without touching a seam — the journal (and witness) are identical to
the acting run given identical inputs; only ``applied`` (which is
NOT part of the witness) differs.

A policy that fires, releases, and re-fires within its own cooldown
window is flapping — the plane journals a ``flap`` entry and emits a
``("remediation", "flap")`` flight note that obs/incident.py turns
into a ``remediation-flap`` postmortem bundle instead of letting the
loop churn silently.

Lock discipline (the serve/adaptive.py contract): decisions are made
under the plane's own ``_mu``; seam calls (``hold_open``/``release``,
``submit_extrinsic``, ``set_repair_mode``) and flight notes always
happen AFTER the lock is released. The plane's lock may nest over a
HealthMonitor's — never the reverse.

Zero-cost when off: the plane only exists when armed; every consumer
seam (node metrics merge, RPC dispatch, sim round loop, author loop)
is one attribute load + ``None`` check.
"""
from __future__ import annotations

import collections
import dataclasses
import json
import threading
from typing import Any

from ..obs import flight as _flight

__all__ = ["Policy", "RemediationPlane", "default_policies"]

# action verbs a Policy row may name; engage/disengage semantics live
# in RemediationPlane._apply
ACTIONS = ("pin-reference", "quarantine-lane", "file-offence",
           "flip-repair-mode", "proactive-repair")

# one-shot actions complete at fire time (nothing to hold, nothing to
# release); the rest stay "engaged" until their release condition
_ONE_SHOT = frozenset(("file-offence",))

# class -> backend monitor name; mirrors SubmissionEngine._BACKEND_OF
# (read from the bound engine when one is attached)
_CLASS_BACKEND = {"encode": "codec", "decode": "codec",
                  "repair": "codec", "tag": "audit", "prove": "audit",
                  "verify_batch": "audit", "verify_agg": "audit"}

# detector notes folded into the evidence map (snapshot context for
# humans; never actions by themselves)
_EVIDENCE = frozenset((("slo", "transition"), ("breaker", "trip"),
                       ("breaker", "hold"), ("breaker", "release"),
                       ("breaker", "recover"), ("perf", "regression"),
                       ("chain", "anomaly"), ("fleet", "outlier"),
                       ("repair", "fallback"), ("repair", "mode"),
                       ("custody", "at_risk"), ("custody", "lost")))


@dataclasses.dataclass(frozen=True)
class Policy:
    """One declarative remediation rule: trigger edge -> guard ->
    action -> release condition.

    ``trigger`` is a ``(subsystem, kind)`` flight-note edge; ``match``
    is the guard — ``((field, value), ...)`` pairs the note's detail
    must carry verbatim. ``key_field`` names the detail field whose
    value keys the engagement (one engagement per key); empty means
    the policy itself is the key. ``release_on``/``release_match``
    name the edge that releases an engagement ("recovered");
    ``release_after`` is the count-based re-probe fallback: after that
    many plane ticks the engagement releases unconditionally (0 =
    never auto-release). ``cooldown`` is the minimum tick gap between
    fires per key; ``max_fires`` the lifetime cap per policy — both
    COUNT-based, never wall-clock."""

    name: str
    trigger: tuple
    action: str
    match: tuple = ()
    key_field: str = ""
    release_on: tuple = ()
    release_match: tuple = ()
    release_after: int = 8
    cooldown: int = 4
    max_fires: int = 64
    enabled: bool = True

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown action {self.action!r}; "
                             f"choose from {ACTIONS}")
        if self.cooldown < 0 or self.max_fires < 1 \
                or self.release_after < 0:
            raise ValueError("cooldown/release_after must be >= 0 and "
                             "max_fires >= 1")

    def row(self) -> dict:
        d = dataclasses.asdict(self)
        d["trigger"] = list(self.trigger)
        d["match"] = [list(p) for p in self.match]
        d["release_on"] = list(self.release_on)
        d["release_match"] = [list(p) for p in self.release_match]
        return d


def default_policies() -> tuple:
    """The shipped policy table — one row per detector altitude."""
    return (
        # PerfWatchdog edge: live GiB/s collapsed vs the bench
        # baseline. Pin the class to the reference backend; release on
        # the recovery edge, or re-probe after release_after ticks
        # (while pinned the watchdog only sees the reference path, so
        # a count-based re-probe is the only honest recovery check).
        Policy(name="perf-pin", trigger=("perf", "regression"),
               match=(("to", "regressed"),), key_field="metric",
               action="pin-reference",
               release_on=("perf", "regression"),
               release_match=(("to", "ok"),),
               release_after=8, cooldown=4, max_fires=64),
        # A window-tripped breaker keeps paying probe failures against
        # a dead backend; latch it held, re-probe after the cooldown.
        Policy(name="breaker-pin", trigger=("breaker", "trip"),
               match=(), key_field="name", action="pin-reference",
               release_after=12, cooldown=8, max_fires=64),
        # Fleet straggler: hold the lane's per-device breakers so
        # placement avoids it and DevicePool.requeue drains in-flight
        # work to siblings; re-probe after release_after ticks.
        Policy(name="straggler-quarantine",
               trigger=("fleet", "outlier"), match=(),
               key_field="instance", action="quarantine-lane",
               release_after=16, cooldown=8, max_fires=32),
        # Chainwatch equivocation edge: file the offence on-chain from
        # the node's own signed vote evidence. One-shot; the on-chain
        # AlreadyReported dedup backstops the per-key cooldown.
        Policy(name="equivocation-report",
               trigger=("chain", "anomaly"),
               match=(("cls", "equivocation"),), key_field="key",
               action="file-offence", release_after=0,
               cooldown=1_000_000, max_fires=32),
        # Repair-ingress regression (sampled by tick(), synthesized as
        # a ("remediation", "ingress") edge): symbol-mode repairs are
        # ingressing more than the configured bound per recovered byte
        # — flip the miner to whole-fragment mode, flip back to
        # re-probe after release_after ticks.
        Policy(name="repair-ingress",
               trigger=("remediation", "ingress"), match=(),
               key_field="miner", action="flip-repair-mode",
               release_after=12, cooldown=6, max_fires=32),
        # Custody at-risk edge (obs/custody.py): a segment's erasure
        # margin fell to the detector threshold — proactively rebuild
        # its unhealthy fragments through the regenerating symbol path
        # (1.0 fragment-equivalents of ingress per rebuild) BEFORE the
        # k-th fragment dies. Engaged until the margin-recovered edge
        # releases it; each tick in between re-attempts the rebuild
        # (the filed restoral order only applies one block later).
        Policy(name="custody-repair", trigger=("custody", "at_risk"),
               match=(("to", "bad"),), key_field="key",
               action="proactive-repair",
               release_on=("custody", "at_risk"),
               release_match=(("to", "ok"),),
               release_after=8, cooldown=2, max_fires=64),
    )


def _match(pairs: tuple, detail: dict) -> bool:
    for field, value in pairs:
        if detail.get(field) != value:
            return False
    return True


def _canon_detail(detail: dict) -> dict:
    """JSON-canonical copy of a note detail: strings/ints/bools pass
    through, floats round to 3 places, everything else reprs — the
    journal is part of the replay witness, so every value must
    serialize byte-identically."""
    out = {}
    for k in sorted(detail):
        v = detail[k]
        if isinstance(v, bool) or isinstance(v, (str, int)):
            out[str(k)] = v
        elif isinstance(v, float):
            out[str(k)] = round(v, 3)
        else:
            out[str(k)] = repr(v)
    return out


class RemediationPlane:
    """Count-sequenced policy engine over the flight-note edge stream.

    Wire-up: ``recorder.add_listener(plane.on_note)`` feeds the edges;
    ``bind_engine``/``bind_node``/``bind_miners`` attach the action
    seams; a driver (the sim round loop, the net author loop) calls
    ``tick()`` once per observation round — edges observed since the
    last tick are decided and applied there, in arrival order, so the
    edge->action latency is exactly one observation round and the
    journal order is a pure function of the input edge order."""

    def __init__(self, seed: bytes = b"", policies=None, *,
                 dry_run: bool = False, journal_cap: int = 256,
                 edge_cap: int = 256, reporter: str = "root",
                 ingress_bound: float = 1.5):
        if journal_cap < 1 or edge_cap < 1:
            raise ValueError("journal_cap/edge_cap must be >= 1")
        pols = tuple(default_policies() if policies is None
                     else policies)
        names = [p.name for p in pols]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate policy names: {names}")
        self._seed = bytes(seed)
        self._policies = pols
        self.dry_run = bool(dry_run)
        self._reporter = reporter
        self._ingress_bound = float(ingress_bound)
        self._by_trigger: dict[tuple, list] = {}
        self._by_release: dict[tuple, list] = {}
        for p in pols:
            self._by_trigger.setdefault(tuple(p.trigger), []).append(p)
            if p.release_on:
                self._by_release.setdefault(
                    tuple(p.release_on), []).append(p)
        self._by_name = {p.name: p for p in pols}
        self._mu = threading.Lock()
        self._count = 0                 # plane ticks (observation rounds)
        self._journal: collections.deque = collections.deque(
            maxlen=journal_cap)
        self._journal_total = 0
        self._edges: collections.deque = collections.deque(
            maxlen=edge_cap)
        self._edge_total = 0
        self._pending_fire: list = []   # (policy, key, edge_id, detail)
        self._pending_release: list = []            # (policy, key)
        self._engaged: dict[tuple, dict] = {}       # (policy, key) ->
        self._fires: dict[str, int] = {}            # policy -> count
        self._last_fire: dict[tuple, int] = {}      # (policy, key) -> tick
        self._released_at: dict[tuple, int] = {}    # (policy, key) -> tick
        self._health: dict[str, dict] = {"slo": {}, "breaker": {},
                                         "perf": {}, "chain": {},
                                         "fleet": {}, "repair": {},
                                         "custody": {}}
        self._engine = None
        self._node = None
        self._custody = None
        self._miners: dict[str, Any] = {}
        self._intended_mode: dict[str, str] = {}
        self._ingress_last: dict[str, tuple] = {}
        self._applied = 0
        self._skipped = 0
        self._suppressed = 0
        self._releases = 0
        self._flaps = 0

    # -- seam binding --------------------------------------------------------
    def bind_engine(self, engine) -> None:
        """Attach the submission engine whose monitors (and pool lane
        breakers) pin/quarantine actions act through."""
        with self._mu:
            self._engine = engine

    def bind_node(self, node) -> None:
        """Attach the node whose finality evidence and extrinsic
        surface the file-offence action uses."""
        with self._mu:
            self._node = node

    def bind_custody(self, plane) -> None:
        """Attach the custody plane (obs/custody.py) whose
        at-risk-segment repair targets the proactive-repair action
        rebuilds through the bound miners."""
        with self._mu:
            self._custody = plane

    def bind_miners(self, miners) -> None:
        """Attach the miner agents whose repair_mode the ingress
        policy may flip. The plane tracks each miner's INTENDED mode
        itself (seeded from the live attribute here) so dry-run
        decisions evolve identically to acting ones."""
        with self._mu:
            for m in miners:
                acct = m.account
                self._miners[acct] = m
                self._intended_mode[acct] = m.repair_mode
                self._ingress_last[acct] = (
                    int(m.repair_ingress_bytes),
                    int(m.repair_recovered_bytes))

    # -- the edge stream (FlightRecorder listener) ---------------------------
    def on_note(self, seq: int, subsystem: str, kind: str,
                detail: dict) -> None:
        """Journal-listener entry point: record matching trigger and
        release edges for the next tick. Never acts here — the noting
        thread may sit inside another subsystem's announce path."""
        trig = (subsystem, kind)
        pols = self._by_trigger.get(trig)
        rels = self._by_release.get(trig)
        if pols is None and rels is None and trig not in _EVIDENCE:
            return
        with self._mu:
            self._observe_evidence_locked(subsystem, kind, detail)
            for p in pols or ():
                if not p.match or _match(p.match, detail):
                    self._record_edge_locked(p, detail, int(seq))
            for p in rels or ():
                if _match(p.release_match, detail):
                    key = str(detail.get(p.key_field, p.name)) \
                        if p.key_field else p.name
                    self._pending_release.append((p.name, key))

    def _record_edge_locked(self, p: Policy, detail: dict,
                     seq: int) -> None:
        """Caller holds ``_mu``. Every guard-passing trigger edge is
        recorded — including for a DISABLED policy, which is exactly
        what the ``remediation-coverage`` invariant catches (an edge
        the table matched but nobody journaled a decision for)."""
        key = str(detail.get(p.key_field, p.name)) if p.key_field \
            else p.name
        self._edge_total += 1
        self._edges.append({"id": self._edge_total, "seq": seq,
                            "tick": self._count, "policy": p.name,
                            "key": key})
        if p.enabled:
            self._pending_fire.append(
                (p.name, key, self._edge_total,
                 _canon_detail(detail)))

    def _observe_evidence_locked(self, subsystem: str, kind: str,
                          detail: dict) -> None:
        """Caller holds ``_mu``: fold detector notes into the bounded
        per-subsystem evidence map (snapshot context only)."""
        h = self._health.get(subsystem)
        if h is None:
            return
        if subsystem == "slo":
            h[str(detail.get("cls", "?"))] = str(detail.get("to", "?"))
        elif subsystem == "breaker":
            h[str(detail.get("name", "?"))] = kind
        elif subsystem == "perf":
            h[str(detail.get("metric", "?"))] = str(
                detail.get("to", "?"))
        elif subsystem == "chain":
            h[str(detail.get("key", "?"))] = str(
                detail.get("to", detail.get("cls", "?")))
        elif subsystem == "fleet":
            h[str(detail.get("instance", "?"))] = str(
                detail.get("metric", "?"))
        elif subsystem == "repair":
            h[str(detail.get("miner", "?"))] = str(
                detail.get("to", kind))
        elif subsystem == "custody":
            h[str(detail.get("key", "?"))] = \
                f"{kind}:{detail.get('to', '?')}"
        while len(h) > 64:           # bounded: evict oldest insertion
            h.pop(next(iter(h)))

    # -- the decision round --------------------------------------------------
    def tick(self) -> int:
        """Advance one observation round: sample the repair-ingress
        ratios, decide every pending release and fire in arrival
        order, then apply the decided actions OUTSIDE the plane lock
        (adaptive.py discipline). Returns the number of journal
        entries this round."""
        todo: list = []
        notes: list = []
        pumps: list = []
        with self._mu:
            self._count += 1
            self._sample_ingress_locked()
            # releases decide before fires so a recover-edge and a
            # fresh trigger landing in the same round re-engage (and
            # register as a flap when inside the cooldown window)
            for pname, key in self._pending_release:
                self._decide_release_locked(pname, key, "recovered", todo,
                                     notes)
            self._pending_release = []
            for (pname, key), eng in sorted(self._engaged.items()):
                p = self._by_name[pname]
                if p.release_after > 0 and \
                        self._count - eng["fired_tick"] \
                        >= p.release_after:
                    self._decide_release_locked(pname, key, "re-probe", todo,
                                         notes)
            # engagements that survived the release pass pump one
            # rebuild attempt per tick: the fire-time attempt usually
            # only FILES the restoral order (applied a block later),
            # so the engagement retries until the margin-recovered
            # edge releases it. Decisions are unaffected (no journal
            # entry), so a dry run's witness stays byte-identical.
            if not self.dry_run:
                pumps = [key for (pname, key), eng
                         in sorted(self._engaged.items())
                         if eng["action"] == "proactive-repair"]
            entries = 0
            for pname, key, edge_id, detail in self._pending_fire:
                self._decide_fire_locked(pname, key, edge_id, detail, todo,
                                  notes)
                entries += 1
            self._pending_fire = []
        for key in pumps:
            self._proactive_repair(key)
        for kind, args in todo:
            ok = self._apply(kind, args)
            args[0]["applied"] = ok
            if ok:
                self._applied += 1
            else:
                self._skipped += 1
        for kind, detail in notes:
            _flight.note("remediation", kind, **detail)
        return entries

    def _journal_entry_locked(self, event: str, policy: str, action: str,
                       key: str, reason: str, edge: int,
                       detail: dict) -> dict:
        """Caller holds ``_mu``. ``applied`` is bookkeeping for humans
        (dry-run vs acting) and is excluded from the witness."""
        self._journal_total += 1
        ent = {"seq": self._journal_total, "tick": self._count,
               "event": event, "policy": policy, "action": action,
               "key": key, "reason": reason, "edge": edge,
               "detail": detail, "applied": False}
        self._journal.append(ent)
        return ent

    def _decide_fire_locked(self, pname: str, key: str, edge_id: int,
                     detail: dict, todo: list, notes: list) -> None:
        p = self._by_name[pname]
        ekey = (pname, key)
        fired = self._fires.get(pname, 0)
        if fired >= p.max_fires:
            reason = "rate-limit"
        elif ekey in self._engaged:
            reason = "engaged"
        elif self._count - self._last_fire.get(ekey, -p.cooldown - 1) \
                <= p.cooldown:
            reason = "cooldown"
        else:
            reason = ""
        if reason:
            self._journal_entry_locked("suppress", pname, p.action, key,
                                reason, edge_id, detail)
            self._suppressed += 1
            return
        self._fires[pname] = fired + 1
        self._last_fire[ekey] = self._count
        ent = self._journal_entry_locked("fire", pname, p.action, key, "",
                                  edge_id, detail)
        if p.action not in _ONE_SHOT:
            self._engaged[ekey] = {"fired_tick": self._count,
                                   "edge": edge_id,
                                   "action": p.action}
        if p.action == "flip-repair-mode":
            self._intended_mode[key] = "fragments"
        todo.append((("engage", p.action), (ent, key, pname, detail)))
        notes.append(("action", {"policy": pname, "action": p.action,
                                 "key": key}))
        rel = self._released_at.get(ekey)
        if rel is not None and self._count - rel <= p.cooldown:
            self._journal_entry_locked("flap", pname, p.action, key,
                                "refire-inside-cooldown", edge_id, {})
            self._flaps += 1
            notes.append(("flap", {"policy": pname,
                                   "action": p.action, "key": key,
                                   "gap": self._count - rel}))

    def _decide_release_locked(self, pname: str, key: str, reason: str,
                        todo: list, notes: list) -> None:
        p = self._by_name.get(pname)
        eng = self._engaged.pop((pname, key), None)
        if p is None or eng is None:
            return
        self._released_at[(pname, key)] = self._count
        self._releases += 1
        if p.action == "flip-repair-mode":
            self._intended_mode[key] = "symbols"
        ent = self._journal_entry_locked("release", pname, p.action, key,
                                  reason, eng["edge"], {})
        todo.append((("release", p.action), (ent, key, pname, {})))
        notes.append(("release", {"policy": pname, "action": p.action,
                                  "key": key, "reason": reason}))

    def _sample_ingress_locked(self) -> None:
        """Caller holds ``_mu``. The repair-ingress edge is SAMPLED
        from the miners' accounting counters rather than subscribed —
        there is no detector note for it — and synthesized through the
        same edge path every note-driven policy uses. The mode gate
        reads the plane's INTENDED mode, not the live attribute, so a
        dry run's decisions match the acting run's."""
        pols = [p for p in self._by_trigger.get(
            ("remediation", "ingress"), ())]
        if not pols or not self._miners:
            return
        for acct in sorted(self._miners):
            if self._intended_mode.get(acct) != "symbols":
                continue
            m = self._miners[acct]
            ing = int(m.repair_ingress_bytes)
            rec = int(m.repair_recovered_bytes)
            last_ing, last_rec = self._ingress_last.get(acct, (0, 0))
            self._ingress_last[acct] = (ing, rec)
            d_rec = rec - last_rec
            if d_rec <= 0:
                continue
            ratio = round((ing - last_ing) / d_rec, 3)
            if ratio <= self._ingress_bound:
                continue
            detail = {"miner": acct, "ratio": ratio,
                      "bound": self._ingress_bound}
            for p in pols:
                self._record_edge_locked(p, detail, 0)

    # -- action seams (called OUTSIDE the plane lock) ------------------------
    def _apply(self, kind: tuple, args: tuple) -> bool:
        step, action = kind
        ent, key, pname, detail = args
        if self.dry_run:
            return False
        engage = step == "engage"
        if action == "pin-reference":
            mons = self._pin_monitors(key)
        elif action == "quarantine-lane":
            mons = self._lane_monitors(key)
        elif action == "file-offence":
            return self._file_offence(key)
        elif action == "flip-repair-mode":
            return self._flip_mode(key, engage)
        elif action == "proactive-repair":
            if not engage:
                return True          # release: nothing held
            return self._proactive_repair(key)
        else:
            return False
        for mon in mons:
            if engage:
                mon.hold_open(reason=f"remediation:{pname}")
            else:
                mon.release()
        return bool(mons)

    def _pin_monitors(self, key: str) -> list:
        """Resolve a pin key — a monitor name (``codec``,
        ``audit.d1``), an op class, or a watchdog metric name — to the
        HealthMonitor(s) to latch."""
        eng = self._engine
        if eng is None:
            return []
        mons = dict(eng.monitors)
        pool = getattr(eng, "pool", None)
        if pool is not None:
            for lane in pool.lanes:
                for backend, mon in lane.monitors.items():
                    mons[f"{backend}.d{lane.index}"] = mon
        if key in mons:
            return [mons[key]]
        cls = key
        prof = getattr(eng, "profile", None)
        tracked = getattr(prof, "tracked", None) or {}
        for c in sorted(tracked):
            if tracked[c] == key:
                cls = c
                break
        backend = getattr(eng, "_BACKEND_OF", _CLASS_BACKEND).get(cls)
        return [mons[backend]] if backend in mons else []

    def _lane_monitors(self, key: str) -> list:
        """A quarantine key names a pool lane (``d<i>``, or any
        instance name ending in ``d<i>``); holding every per-backend
        breaker on that lane makes placement avoid it and drains its
        in-flight batches through DevicePool.requeue. A key that names
        a foreign host resolves to nothing — quarantining another
        machine is an operator action, and the journal still records
        the intent."""
        eng = self._engine
        pool = getattr(eng, "pool", None) if eng is not None else None
        if pool is None:
            return []
        tail = key.rsplit("d", 1)
        if len(tail) != 2 or not tail[1].isdigit():
            return []
        idx = int(tail[1])
        for lane in pool.lanes:
            if lane.index == idx:
                return [lane.monitors[b]
                        for b in sorted(lane.monitors)]
        return []

    def _file_offence(self, key: str) -> bool:
        """Match an equivocation anomaly key (``offender@round``)
        against the node's own signed vote evidence and file the
        offence. The chainwatch evidence record carries only hashes;
        the actual Vote pair — verifiable on-chain — lives in the
        finality gadget's equivocation list."""
        node = self._node
        if node is None or "@" not in key:
            return False
        offender, _, rnd_s = key.rpartition("@")
        if not rnd_s.isdigit():
            return False
        rnd = int(rnd_s)
        fin = getattr(node, "finality", None)
        pairs = list(getattr(fin, "equivocations", ()) or ())
        for va, vb in pairs:
            if va.voter == offender and va.round == rnd \
                    and va.target_hash != vb.target_hash:
                try:
                    node.submit_extrinsic(
                        self._reporter, "offences.report_equivocation",
                        va, vb)
                except Exception:
                    # AlreadyReported / BadOrigin: the evidence path
                    # worked, the chain said no — journaled either way
                    return False
                return True
        return False

    def _proactive_repair(self, key: str) -> bool:
        """Rebuild one at-risk segment's unhealthy fragments through
        the existing MinerAgent repair seams. For a silently-dead
        custodian (nobody filed the loss) the plane files the restoral
        order itself — it applies one block later, so the engagement's
        per-tick pump finishes the rebuild next round. Rescuers run
        the regenerating symbol chain: 1.0 fragment-equivalents of
        ingress per rebuilt fragment."""
        with self._mu:
            plane = self._custody
            node = self._node
            miners = [self._miners[a] for a in sorted(self._miners)]
        if plane is None or node is None or not miners:
            return False
        rt = node.runtime
        progressed = False
        for tgt in plane.repair_targets(key):
            frag = bytes.fromhex(tgt["frag"])
            holder = tgt["holder"]
            if rt.file_bank.restoral_order(frag) is None:
                if holder is not None:
                    node.submit_extrinsic(
                        holder, "file_bank.generate_restoral_order",
                        bytes.fromhex(tgt["file"]), frag)
                    progressed = True
                continue
            rescuer = next(
                (m for m in miners
                 if m.account != holder and frag not in m.store
                 and plane.holder_alive(m.account)), None)
            if rescuer is None:
                continue
            if rescuer.repair_mode != "symbols":
                rescuer.set_repair_mode("symbols")
                with self._mu:
                    self._intended_mode[rescuer.account] = "symbols"
            if rescuer.try_repair(frag, miners):
                progressed = True
        return progressed

    def _flip_mode(self, key: str, engage: bool) -> bool:
        miner = self._miners.get(key)
        if miner is None:
            return False
        miner.set_repair_mode("fragments" if engage else "symbols")
        return True

    # -- introspection -------------------------------------------------------
    @property
    def count(self) -> int:
        with self._mu:
            return self._count

    def policies(self) -> tuple:
        return self._policies

    def edge_log(self) -> list:
        """Every guard-passing trigger edge observed (bounded), for
        the sim's ``remediation-coverage`` invariant."""
        with self._mu:
            return [dict(e) for e in self._edges]

    def journal(self, limit: int | None = None) -> list:
        with self._mu:
            entries = [dict(e) for e in self._journal]
        return entries[-limit:] if limit else entries

    def engagements(self) -> dict:
        with self._mu:
            return {f"{p}:{k}": dict(v)
                    for (p, k), v in sorted(self._engaged.items())}

    def intended_mode(self, account: str) -> str | None:
        with self._mu:
            return self._intended_mode.get(account)

    def witness(self) -> bytes:
        """Canonical bytes of the action journal — the replay
        contract: same seed (=> same edge stream) => byte-identical,
        acting or dry-run (``applied`` is excluded)."""
        with self._mu:
            entries = [{k: e[k] for k in
                        ("seq", "tick", "event", "policy", "action",
                         "key", "reason", "edge", "detail")}
                       for e in self._journal]
            payload = {"seed": self._seed.hex(),
                       "total": self._journal_total,
                       "journal": entries}
        return json.dumps(payload, sort_keys=True,
                          separators=(",", ":")).encode()

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "dry_run": self.dry_run,
                "count": self._count,
                "policies": [p.row() for p in self._policies],
                "engaged": {f"{p}:{k}": dict(v) for (p, k), v
                            in sorted(self._engaged.items())},
                "fires": dict(sorted(self._fires.items())),
                "journal": [dict(e) for e in self._journal],
                "edges_total": self._edge_total,
                "journal_total": self._journal_total,
                "health": {s: dict(h)
                           for s, h in sorted(self._health.items())},
                "counters": {"applied": self._applied,
                             "skipped": self._skipped,
                             "suppressed": self._suppressed,
                             "releases": self._releases,
                             "flaps": self._flaps},
            }

    def metrics(self) -> dict:
        with self._mu:
            return {
                "cess_remediation_policies": len(self._policies),
                "cess_remediation_ticks_total": self._count,
                "cess_remediation_edges_total": self._edge_total,
                "cess_remediation_fires_total":
                    sum(self._fires.values()),
                "cess_remediation_suppressed_total": self._suppressed,
                "cess_remediation_actions_applied_total":
                    self._applied,
                "cess_remediation_releases_total": self._releases,
                "cess_remediation_flaps_total": self._flaps,
                "cess_remediation_engaged": len(self._engaged),
                "cess_remediation_dry_run": int(self.dry_run),
            }
