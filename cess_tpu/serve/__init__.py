"""cess_tpu.serve — the device submission engine.

A dynamic micro-batching service between every off-chain client
(OssGateway encode, MinerAgent proving, TeeAgent tagging/verification)
and the ``ErasureCodec`` / ``AuditBackend`` device gates: bounded
per-class queues, a size-or-deadline batcher that coalesces ragged
requests into shape-bucketed device programs, explicit backpressure,
and engine counters on the node metrics surface. See engine.py for
the full design; the direct synchronous path stays the default
everywhere an engine is not explicitly configured.

stream.py adds the double-buffered host->device streaming driver for
the fused encode+tag workload (one H2D copy per batch, staging of
batch i+1 overlapped with compute of batch i, ragged tail handled).

adaptive.py closes the observability loop (ISSUE 6): per-class
batching knobs tuned from the live latency signal
(AdaptiveBatchPolicy) and SLO-gated, deadline-aware admission
(AdmissionController) over an obs.SloBoard — opt-in via
``make_engine(slo=..., adaptive=...)`` / ``node.cli --slo --adaptive``.

pool.py is the multi-chip serving plane (ISSUE 10): a DevicePool
routes the batcher's drained batches across per-device worker lanes
(deterministic least-loaded placement, per-(backend, device)
breakers, drain-to-sibling on lane failure) — opt-in via
``make_engine(pool=...)`` / ``node.cli --pool[=N]``.

remediate.py closes the control loop (ISSUE 16): a count-sequenced
RemediationPlane subscribes to the flight recorder's detector edges
and maps each through a declarative Policy table to a journaled,
replayable recovery action (pin-to-reference, lane quarantine,
on-chain offence filing, repair-mode flip) — opt-in via
``node.cli --remediate`` / ``Scenario.remediate=True``.
"""
from .adaptive import AdaptiveBatchPolicy, AdmissionController
from .engine import EngineFuture, SubmissionEngine, make_engine
from .policy import (AdmissionPolicy, EngineClosed, EngineError,
                     EngineSaturated, EngineShed, EngineTimeout)
from .pool import DevicePool
from .remediate import Policy, RemediationPlane, default_policies
from .stats import EngineStats, StreamStats
from .stream import StreamingIngest

__all__ = [
    "AdaptiveBatchPolicy",
    "AdmissionController",
    "AdmissionPolicy",
    "DevicePool",
    "EngineClosed",
    "EngineError",
    "EngineFuture",
    "EngineSaturated",
    "EngineShed",
    "EngineStats",
    "EngineTimeout",
    "Policy",
    "RemediationPlane",
    "StreamStats",
    "StreamingIngest",
    "SubmissionEngine",
    "default_policies",
    "make_engine",
]
