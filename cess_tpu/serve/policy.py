"""Admission control for the device submission engine.

The engine's contract with its callers is explicit about overload
(ISSUE: "no silent drops or unbounded queues"):

- every op class has a bounded queue; a submit against a full queue
  raises :class:`EngineSaturated` immediately (backpressure the caller
  can act on — retry, shed, or route to the direct path);
- every request may carry a deadline; a request still queued when its
  deadline passes is cancelled with :class:`EngineTimeout` (the audit
  flow's challenge_deadline shape: a proof delivered after the round
  closes is worthless, so the engine never spends device time on it);
- classes drain in fixed priority order — challenge verification
  preempts bulk encode, mirroring the reference's audit urgency (a
  missed verify window slashes a miner; a delayed upload just waits).
"""
from __future__ import annotations

import dataclasses


class EngineError(Exception):
    """Base class for submission-engine errors."""


class EngineSaturated(EngineError):
    """The op class's bounded queue is full: explicit backpressure.

    Callers choose the response (retry with jitter, shed load, or fall
    back to the direct synchronous path) — the engine never queues
    unboundedly and never drops silently.
    """


class EngineTimeout(EngineError):
    """The request's deadline expired before its batch ran."""


class EngineShed(EngineError):
    """The request was rejected by SLO-gated admission control
    (serve/adaptive.py): either a protected class's SLO is burning and
    this class is being shed to protect it, or the request's own
    deadline is already below the class's live p99 estimate. Distinct
    from :class:`EngineSaturated` on purpose — a saturated queue wants
    a backoff-retry, shed load wants the caller to STOP offering
    (route to the direct path, or wait for the SLO to recover)."""


class EngineClosed(EngineError):
    """Submit against an engine that has been shut down."""


# Drain order: lower drains first. Verification answers a live audit
# round (missing the window slashes a miner); proving races the same
# challenge_deadline; tagging gates uploads becoming chargeable;
# repair restores redundancy; bulk encode has no deadline at all.
CLASS_PRIORITY: dict[str, int] = {
    "verify": 0,
    "prove": 1,
    "tag": 2,
    "repair": 3,
    "encode": 4,
}

CLASSES = tuple(sorted(CLASS_PRIORITY, key=CLASS_PRIORITY.__getitem__))


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """Per-class bounds + batching trigger knobs.

    queue_cap:          max queued requests per class (EngineSaturated
                        beyond it).
    max_batch_requests: size trigger — a class with this many queued
                        coalescible requests drains immediately.
    max_batch_rows:     row budget per device batch (padding bucket
                        ceiling; requests beyond it wait for the next
                        batch).
    max_delay:          deadline trigger, seconds — the oldest queued
                        request never waits longer than this for
                        companions before its batch launches.
    default_timeout:    deadline applied to requests submitted without
                        one (None = no deadline).
    """

    queue_cap: int = 256
    max_batch_requests: int = 32
    max_batch_rows: int = 512
    max_delay: float = 0.002
    default_timeout: float | None = None

    def __post_init__(self):
        if self.queue_cap < 1 or self.max_batch_requests < 1 \
                or self.max_batch_rows < 1 or self.max_delay < 0:
            raise ValueError("invalid admission policy bounds")
