"""Shape buckets + compile-once program cache for the engine.

Every distinct array shape handed to a jitted op is a fresh XLA
compile; a serving layer that forwards each caller's ragged batch size
verbatim spends its life recompiling (the Ragged Paged Attention
lesson, PAPERS.md arxiv 2604.15464: coalesce ragged requests into a
small set of shape-bucketed device programs). The engine therefore

- pads every coalesced batch's leading (row) axis up to a bucket —
  powers of two, clamped to the policy's row budget — so the device
  only ever sees O(log max_rows) distinct shapes per op, and
- memoizes the bound device callable per (op, bucket shape, aux key)
  in :class:`ProgramCache`, so bucket reuse is visible in the stats
  (``programs_built`` vs ``programs_reused``) and table builds
  (nibble tables, bit-matrix expansion, decode-matrix Gauss-Jordan)
  happen once per key rather than per call.

Padding is with zero rows and is sliced off after the op; every engine
op is row-independent (vmap / per-row matrix apply), so padded results
are bit-identical to unpadded ones — the determinism tests in
tests/test_serve.py pin this.
"""
from __future__ import annotations

import time
from typing import Callable


def bucket_rows(n: int) -> int:
    """Smallest power-of-two >= n — ALWAYS on the power-of-two grid.

    Coalesced batches respect the policy row budget (the drain never
    combines requests past max_batch_rows), so a bigger n happens only
    for a single oversized request. That request still pads to the
    next power of two rather than compiling an exact-size one-off
    program: an irregular caller then costs at most O(log n) extra
    programs and < 2x pad waste, never a compile per distinct size —
    the churn this module exists to prevent."""
    if n < 1:
        raise ValueError(f"bucket for {n} rows")
    b = 1
    while b < n:
        b <<= 1
    return b


class ProgramCache:
    """(op, bucket shape, aux key) -> bound device callable, LRU.

    The underlying jax.jit caches by traced shape anyway; this layer
    exists so (a) host-side table/matrix builds are done once per key,
    (b) the engine can report compile-vs-reuse counts, and (c) the
    bucket policy has one place to be enforced.

    Bounded: prove/verify keys embed the challenge-round digest, so a
    long-running engine sees a stream of keys that are hot for one
    audit round and dead afterwards — an unbounded dict would be a
    slow leak of closures (and their captured round arrays). LRU with
    a generous capacity keeps every live round's programs resident
    while letting dead rounds fall out.
    """

    CAPACITY = 256

    def __init__(self, stats=None, capacity: int = CAPACITY):
        import collections
        import threading

        self._programs: "collections.OrderedDict[tuple, Callable]" = \
            collections.OrderedDict()
        self._stats = stats
        self.capacity = capacity
        # continuous profiling (obs/profile.py, opt-in): the engine
        # arms this with its ProfilePlane so every cache MISS lands in
        # the CompileLedger with its key and compile wall time — a
        # recompile storm becomes a ranked account. None = one
        # attribute load + None check per miss.
        self.profile = None
        # the batcher thread owns steady-state lookups, but warm-path
        # callers (SubmissionEngine.warm_repair) pre-populate from the
        # submitter thread — the OrderedDict needs its own tiny lock
        self._mu = threading.Lock()

    def __len__(self) -> int:
        with self._mu:
            return len(self._programs)

    def get(self, key: tuple, build: Callable[[], Callable]) -> Callable:
        with self._mu:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                if self._stats is not None:
                    self._stats.programs_reused += 1
                return prog
        # build OUTSIDE the lock: builds compile device programs and
        # must not serialize against concurrent cache hits
        prof = self.profile
        if prof is None:
            prog = build()
        else:
            t0 = time.perf_counter()
            prog = build()
            prof.compile_event(key, time.perf_counter() - t0)
        with self._mu:
            if key not in self._programs:
                self._programs[key] = prog
                if self._stats is not None:
                    self._stats.programs_built += 1
                while len(self._programs) > self.capacity:
                    self._programs.popitem(last=False)
            else:
                prog = self._programs[key]
        return prog
